"""Happens-before sanitizer for the shared-state allowlist
(``NOMAD_TPU_TSAN=1``).

The static race detector (nomadlint ``shared-state-guard``) proves
which shared attributes are consistently locked and forces a
justified ``SHARED_STATE_ALLOWLIST`` entry for every deliberate
exception (GIL-atomic counters, epoch-keyed cache rebinds).  This
module keeps that allowlist honest from the RUNTIME direction: with
``NOMAD_TPU_TSAN=1`` the shared singletons instrument their
attribute accesses and lock operations into a vector-clock
happens-before log, and the tier-1 soak (tests/test_tsan.py) asserts
that every conflicting access pair observed while 64 evals storm the
pipeline is either lock-ordered or inside the static allowlist.  A
pair outside both is a bug one of the two analyses missed.

Mechanics (FastTrack-shaped, full vector clocks for simplicity):

* every thread carries a vector clock; lock release publishes the
  holder's clock on the lock, acquire joins it — the classic
  release/acquire edge;
* ``threading.Thread.start/run/join``, ``threading.Event.set/wait``
  and ``concurrent.futures.Future.result`` are patched (ONLY while
  the knob is set) to add fork/join, publish/absorb and
  task-completion edges — the handoffs the pipeline actually uses
  (watchdog sacrificial threads signal through Events; the replay
  pool hands results back through Futures);
* ``maybe_instrument(obj, family)`` retypes the instance so
  ``__getattribute__``/``__setattr__`` record instance-dict accesses
  and wrap lock attributes (including locks REPLACED after init —
  the supervisor-failover swap) in tracking proxies keyed by the
  underlying primitive, so ``Condition(self._lock)`` aliases unify;
* two accesses to one ``(family, attr)`` from different threads with
  at least one write and no happens-before path are recorded as a
  conflict (deduped per attribute).

Everything is inert without the knob: ``maybe_instrument`` is one
env read, no classes are retyped and no stdlib methods are patched.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

_LOCK_TYPES = (
    type(threading.Lock()),
    type(threading.RLock()),
)


def enabled() -> bool:
    return os.environ.get("NOMAD_TPU_TSAN") == "1"


# -- vector clocks -----------------------------------------------------


def _join(a: Dict[int, int], b: Dict[int, int]) -> None:
    for t, c in b.items():
        if a.get(t, 0) < c:
            a[t] = c


class _Runtime:
    """Process-wide happens-before state.  Internal lock is a leaf:
    held only for table updates, never while calling out."""

    def __init__(self) -> None:
        # RLock: patched Event.set can re-enter (Thread bootstrap
        # sets _started before registering in threading._active, and
        # a current_thread() fallback would construct a _DummyThread
        # whose __init__ sets ANOTHER event)
        self._mu = threading.RLock()
        self._clocks: Dict[int, Dict[int, int]] = {}
        self._lock_clocks: Dict[int, Dict[int, int]] = {}
        # pending fork edges: thread object id -> parent clock
        self._forks: Dict[int, Dict[int, int]] = {}
        # published clocks: event/future id -> clock
        self._published: Dict[int, Dict[int, int]] = {}
        # (family, obj id, attr) -> last write (tid, epoch) and
        # reads {tid: epoch}; conflicts dedupe per (family, attr)
        self._writes: Dict[
            Tuple[str, int, str], Tuple[int, int]
        ] = {}
        self._reads: Dict[
            Tuple[str, int, str], Dict[int, int]
        ] = {}
        self._conflicts: Dict[Tuple[str, str], Dict] = {}
        self._names: Dict[int, str] = {}

    # -- clock helpers (call with self._mu held) ----------------------

    def _clock(self, tid: int) -> Dict[int, int]:
        c = self._clocks.get(tid)
        if c is None:
            c = {tid: 1}
            self._clocks[tid] = c
            # NON-creating name lookup: current_thread() during
            # thread bootstrap would construct a _DummyThread (and
            # recursively fire the patched Event.set)
            th = getattr(threading, "_active", {}).get(tid)
            self._names[tid] = (
                th.name if th is not None else f"thread-{tid}"
            )
        return c

    def _tick(self, tid: int) -> None:
        c = self._clock(tid)
        c[tid] = c.get(tid, 0) + 1

    # -- edges --------------------------------------------------------

    def lock_acquired(self, key: int) -> None:
        tid = threading.get_ident()
        with self._mu:
            _join(self._clock(tid), self._lock_clocks.get(key, {}))

    def lock_released(self, key: int) -> None:
        tid = threading.get_ident()
        with self._mu:
            self._lock_clocks[key] = dict(self._clock(tid))
            self._tick(tid)

    def fork(self, thread_obj_id: int) -> None:
        tid = threading.get_ident()
        with self._mu:
            self._forks[thread_obj_id] = dict(self._clock(tid))
            self._tick(tid)

    def absorb_fork(self, thread_obj_id: int) -> None:
        tid = threading.get_ident()
        with self._mu:
            parent = self._forks.pop(thread_obj_id, None)
            if parent:
                _join(self._clock(tid), parent)

    def publish(self, key: int) -> None:
        """Event.set / task completion: expose the publisher's
        clock under ``key`` for a later absorb."""
        tid = threading.get_ident()
        with self._mu:
            self._published[key] = dict(self._clock(tid))
            self._tick(tid)

    def absorb(self, key: int) -> None:
        tid = threading.get_ident()
        with self._mu:
            pub = self._published.get(key)
            if pub:
                _join(self._clock(tid), pub)

    def absorb_once(self, key: int) -> None:
        """Absorb-and-forget for single-consumer edges (the pool
        submit token): keeps ``_published`` from growing one entry
        per submit for the process lifetime.  Events/futures keep
        their entries — they legitimately have multiple waiters."""
        tid = threading.get_ident()
        with self._mu:
            pub = self._published.pop(key, None)
            if pub:
                _join(self._clock(tid), pub)

    def thread_finished(self, thread_obj_id: int) -> None:
        tid = threading.get_ident()
        with self._mu:
            self._published[thread_obj_id] = dict(
                self._clock(tid)
            )

    # -- accesses ------------------------------------------------------

    def access(
        self, family: str, obj_id: int, attr: str, kind: str
    ) -> None:
        tid = threading.get_ident()
        # keyed per INSTANCE: two live objects of one family have
        # disjoint state (and disjoint locks), so cross-instance
        # accesses must never read as a race on one attribute.
        # Conflicts still REPORT per (family, attr).
        key = (family, obj_id, attr)
        with self._mu:
            clock = self._clock(tid)
            my_epoch = clock.get(tid, 1)

            def hb(other_tid: int, other_epoch: int) -> bool:
                return clock.get(other_tid, 0) >= other_epoch

            report_key = (family, attr)
            w = self._writes.get(key)
            if (
                w is not None
                and w[0] != tid
                and not hb(*w)
                and report_key not in self._conflicts
            ):
                self._conflicts[report_key] = {
                    "family": family,
                    "attr": attr,
                    "kinds": f"w-{kind}",
                    "tids": (w[0], tid),
                }
            if kind == "w":
                for rtid, repoch in self._reads.get(
                    key, {}
                ).items():
                    if (
                        rtid != tid
                        and not hb(rtid, repoch)
                        and report_key not in self._conflicts
                    ):
                        self._conflicts[report_key] = {
                            "family": family,
                            "attr": attr,
                            "kinds": "r-w",
                            "tids": (rtid, tid),
                        }
                self._writes[key] = (tid, my_epoch)
                self._reads.pop(key, None)
            else:
                self._reads.setdefault(key, {})[tid] = my_epoch

    def conflicts(self) -> List[Dict]:
        active = getattr(threading, "_active", {})

        def name_of(t: int) -> str:
            th = active.get(t)
            if th is not None:
                return th.name
            return self._names.get(t, f"thread-{t}")

        with self._mu:
            out = []
            for c in self._conflicts.values():
                rec = dict(c)
                rec["threads"] = tuple(
                    name_of(t) for t in rec.pop("tids")
                )
                out.append(rec)
            return sorted(
                out, key=lambda c: (c["family"], c["attr"])
            )

    def reset_accesses(self) -> None:
        with self._mu:
            self._writes.clear()
            self._reads.clear()
            self._conflicts.clear()


_runtime: Optional[_Runtime] = None
_runtime_mu = threading.Lock()
_patched = False


def _rt() -> _Runtime:
    global _runtime
    with _runtime_mu:
        if _runtime is None:
            _runtime = _Runtime()
        return _runtime


def conflicts() -> List[Dict]:
    """Conflicting access pairs observed so far (deduped per
    attribute); empty when the sanitizer never ran."""
    if _runtime is None:
        return []
    return _runtime.conflicts()


def reset() -> None:
    """Drop recorded accesses/conflicts (per-test isolation).  Clock
    state survives — happens-before is a property of the process."""
    if _runtime is not None:
        _runtime.reset_accesses()


# -- lock proxies ------------------------------------------------------


class _TsanLock:
    """Tracking proxy delegating to the real primitive.  HB edges key
    on the UNDERLYING object's id, so a Condition wrapping the same
    lock and the proxy itself publish to one clock."""

    def __init__(self, real) -> None:
        object.__setattr__(self, "_tsan_real", real)
        object.__setattr__(self, "_tsan_key", id(real))

    def acquire(self, *a, **k):
        got = self._tsan_real.acquire(*a, **k)
        if got and enabled():
            _rt().lock_acquired(self._tsan_key)
        return got

    def release(self):
        if enabled():
            _rt().lock_released(self._tsan_key)
        return self._tsan_real.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, name):
        return getattr(
            object.__getattribute__(self, "_tsan_real"), name
        )


class _TsanCondition(_TsanLock):
    """Condition proxy: wait() releases/re-acquires the underlying
    lock — modelled as release -> absorb-on-wake -> acquire."""

    def __init__(self, real) -> None:
        object.__setattr__(self, "_tsan_real", real)
        inner = getattr(real, "_lock", real)
        object.__setattr__(self, "_tsan_key", id(inner))

    def wait(self, timeout=None):
        if not enabled():
            return self._tsan_real.wait(timeout)
        key = object.__getattribute__(self, "_tsan_key")
        _rt().lock_released(key)
        got = self._tsan_real.wait(timeout)
        _rt().lock_acquired(key)
        return got

    def wait_for(self, predicate, timeout=None):
        if not enabled():
            return self._tsan_real.wait_for(predicate, timeout)
        key = object.__getattribute__(self, "_tsan_key")
        _rt().lock_released(key)
        got = self._tsan_real.wait_for(predicate, timeout)
        _rt().lock_acquired(key)
        return got


def _wrap_lock(value):
    if isinstance(value, (_TsanLock, _TsanCondition)):
        return value
    if isinstance(value, threading.Condition):
        return _TsanCondition(value)
    if isinstance(value, _LOCK_TYPES):
        return _TsanLock(value)
    return value


# -- stdlib handoff edges ---------------------------------------------


def _ensure_patched() -> None:
    """Patch the handoff primitives ONCE (only reached when the knob
    is set).  Every wrapper re-checks ``enabled()`` and passes
    straight through when the knob is off — so after a TSAN test
    unsets the env var, the rest of the process (e.g. the remaining
    tier-1 suite sharing this interpreter) pays one env read per
    handoff, never clock bookkeeping, and the clock/publish tables
    stop growing."""
    global _patched
    if _patched:
        return
    with _runtime_mu:
        if _patched:
            return
        _orig_start = threading.Thread.start
        _orig_run = threading.Thread.run
        _orig_join = threading.Thread.join

        def start(self):
            if enabled():
                _rt().fork(id(self))
            return _orig_start(self)

        def run(self):
            if not enabled():
                return _orig_run(self)
            _rt().absorb_fork(id(self))
            try:
                return _orig_run(self)
            finally:
                _rt().thread_finished(id(self))

        def join(self, timeout=None):
            out = _orig_join(self, timeout)
            if enabled() and not self.is_alive():
                _rt().absorb(id(self))
            return out

        threading.Thread.start = start  # type: ignore[assignment]
        threading.Thread.run = run  # type: ignore[assignment]
        threading.Thread.join = join  # type: ignore[assignment]

        _orig_set = threading.Event.set
        _orig_wait = threading.Event.wait

        def eset(self):
            if enabled():
                _rt().publish(id(self))
            return _orig_set(self)

        def ewait(self, timeout=None):
            got = _orig_wait(self, timeout)
            if got and enabled():
                _rt().absorb(id(self))
            return got

        threading.Event.set = eset  # type: ignore[assignment]
        threading.Event.wait = ewait  # type: ignore[assignment]

        from concurrent.futures import Future

        _orig_set_result = Future.set_result
        _orig_set_exc = Future.set_exception
        _orig_result = Future.result

        def set_result(self, result):
            if enabled():
                _rt().publish(id(self))
            return _orig_set_result(self, result)

        def set_exception(self, exc):
            if enabled():
                _rt().publish(id(self))
            return _orig_set_exc(self, exc)

        def result(self, timeout=None):
            out = _orig_result(self, timeout)
            if enabled():
                _rt().absorb(id(self))
            return out

        Future.set_result = set_result  # type: ignore[assignment]
        Future.set_exception = set_exception  # type: ignore[assignment]
        Future.result = result  # type: ignore[assignment]

        # submit-side edge: work submitted to a pool thread sees
        # everything the submitter wrote before submit()
        from concurrent.futures import ThreadPoolExecutor

        _orig_submit = ThreadPoolExecutor.submit

        def submit(self, fn, *args, **kwargs):
            if not enabled():
                return _orig_submit(self, fn, *args, **kwargs)
            token = object()
            _rt().publish(id(token))

            def wrapped(*a, **k):
                # the closure pins `token`, so its id stays unique
                _rt().absorb_once(id(token))
                return fn(*a, **k)

            return _orig_submit(self, wrapped, *args, **kwargs)

        ThreadPoolExecutor.submit = submit  # type: ignore[assignment]
        _patched = True


# -- instance instrumentation -----------------------------------------

_subclass_cache: Dict[Tuple[type, str], type] = {}


def _instrumented_subclass(cls: type, family: str) -> type:
    cached = _subclass_cache.get((cls, family))
    if cached is not None:
        return cached

    def __getattribute__(self, name):
        value = object.__getattribute__(self, name)
        if name.startswith("_tsan") or name.startswith("__"):
            return value
        if isinstance(value, (_TsanLock, _TsanCondition)):
            return value
        # an instrumented instance can outlive the TSAN window (a
        # singleton constructed while the knob was set): re-check,
        # so clock bookkeeping stops the moment the knob clears
        if not enabled():
            return value
        try:
            d = object.__getattribute__(self, "__dict__")
        except AttributeError:
            return value
        if name in d:
            _rt().access(family, id(self), name, "r")
        return value

    def __setattr__(self, name, value):
        if not name.startswith("_tsan") and enabled():
            value = _wrap_lock(value)
            _rt().access(family, id(self), name, "w")
        object.__setattr__(self, name, value)

    sub = type(
        f"_Tsan{cls.__name__}",
        (cls,),
        {
            "__getattribute__": __getattribute__,
            "__setattr__": __setattr__,
        },
    )
    _subclass_cache[(cls, family)] = sub
    return sub


def maybe_instrument(obj, family: str) -> None:
    """Retype ``obj`` for access tracking when NOMAD_TPU_TSAN=1.
    Call at the END of ``__init__`` — construction writes happen
    before any thread can see the object, so they are not recorded,
    and existing lock attributes are wrapped in one pass.  ``family``
    names the attribute namespace and must match the flowgraph's
    family key (subclasses collapse onto their root: BatchWorker
    instruments as "Worker")."""
    if not enabled():
        return
    _ensure_patched()
    cls = type(obj)
    try:
        wrapped = {
            k: _wrap_lock(v) for k, v in obj.__dict__.items()
        }
        obj.__dict__.update(wrapped)
        obj.__class__ = _instrumented_subclass(cls, family)
    except (TypeError, AttributeError):
        # slotted classes cannot be retyped — skip silently, the
        # static analysis still covers them
        return
