"""External Consul / Vault integration.

The reference registers task services with a real Consul agent
(command/agent/consul/client.go: agent-API register/deregister with
checks, keyed by a stable service id) and derives per-task Vault tokens
server-side (nomad/vault.go: the server holds a management token and
creates renewable child tokens scoped to the task's policies;
client/vaultclient renews them).  This module is the same seam over
plain HTTP:

* `ConsulClient` — Consul agent API (service register/deregister/list,
  KV get/put).
* `ConsulSyncer` — mirrors the in-framework ServiceCatalog to an
  external Consul agent: hooks the store's alloc watcher and pushes
  incremental register/deregister calls, exactly the push-per-alloc
  shape the reference's sync loop settles into.
* `VaultClient` — token derivation (auth/token/create), renewal,
  revocation, and KV reads.
* `VaultSecretsProvider` — plugs VaultClient into the template
  engine's SecretsProvider protocol, so `{{ secret "kv/web" "user" }}`
  templates read through a real Vault.

All network use is opt-in: nothing here runs unless an address is
configured (`consul { address = ... }` / `vault { address = ... }` in
the agent config), and every call degrades to a logged failure rather
than wedging task startup — the reference treats Consul/Vault outages
the same way (fingerprint flips, tasks gate on recovery).
"""
from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional

LOG = logging.getLogger("nomad_tpu.external")


class ExternalError(Exception):
    pass


def _http(
    method: str,
    url: str,
    body: Optional[Any] = None,
    headers: Optional[Dict[str, str]] = None,
    timeout: float = 10.0,
    raw_body: Optional[bytes] = None,
) -> Any:
    data = raw_body
    if body is not None and data is None:
        data = json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read()
            return json.loads(raw) if raw else None
    except urllib.error.HTTPError as exc:
        raise ExternalError(
            f"{method} {url}: HTTP {exc.code} {exc.read()[:200]!r}"
        ) from exc
    except urllib.error.URLError as exc:
        raise ExternalError(f"{method} {url}: {exc.reason}") from exc


# ---------------------------------------------------------------------------
# Consul
# ---------------------------------------------------------------------------


class ConsulClient:
    """Consul agent HTTP API subset (reference
    command/agent/consul/client.go + api.Agent)."""

    def __init__(self, address: str, token: str = "") -> None:
        self.address = address.rstrip("/")
        self.token = token

    def _headers(self) -> Dict[str, str]:
        return {"X-Consul-Token": self.token} if self.token else {}

    def register_service(
        self,
        service_id: str,
        name: str,
        address: str = "",
        port: int = 0,
        tags: Optional[List[str]] = None,
        checks: Optional[List[Dict[str, Any]]] = None,
    ) -> None:
        payload: Dict[str, Any] = {
            "ID": service_id,
            "Name": name,
            "Tags": tags or [],
        }
        if address:
            payload["Address"] = address
        if port:
            payload["Port"] = port
        if checks:
            payload["Checks"] = checks
        _http(
            "PUT",
            f"{self.address}/v1/agent/service/register",
            payload,
            self._headers(),
        )

    def deregister_service(self, service_id: str) -> None:
        _http(
            "PUT",
            f"{self.address}/v1/agent/service/deregister/"
            + urllib.parse.quote(service_id),
            None,
            self._headers(),
        )

    def services(self) -> Dict[str, Any]:
        return (
            _http(
                "GET",
                f"{self.address}/v1/agent/services",
                None,
                self._headers(),
            )
            or {}
        )

    def kv_get(self, key: str) -> Optional[str]:
        try:
            out = _http(
                "GET",
                f"{self.address}/v1/kv/{urllib.parse.quote(key)}?raw=true",
                None,
                self._headers(),
            )
        except ExternalError:
            return None
        return out if isinstance(out, str) else json.dumps(out)

    def kv_put(self, key: str, value: str) -> None:
        _http(
            "PUT",
            f"{self.address}/v1/kv/{urllib.parse.quote(key)}",
            headers=self._headers(),
            raw_body=value.encode(),
        )


def _service_id(inst) -> str:
    """Stable Consul service id for a catalog instance — the reference
    uses a nomad-prefixed hash of alloc/task/service
    (command/agent/consul/client.go makeAllocServiceID)."""
    return f"_nomad-task-{inst.alloc_id}-{inst.task}-{inst.service}"


class ConsulSyncer:
    """Mirror the in-framework catalog into an external Consul agent.

    Hooks the same alloc-watcher feed the ServiceCatalog consumes;
    failures log and retry on the next alloc event rather than wedging
    the scheduler or client."""

    # quiet-cluster safety net: a register/deregister that failed
    # during a Consul outage must not stay stale until the next alloc
    # change — retry on a timer (the reference's syncer runs a
    # periodic sync loop, command/agent/consul/client.go Run)
    RESYNC_INTERVAL_S = 30.0

    def __init__(self, catalog, consul: ConsulClient) -> None:
        self.catalog = catalog
        self.consul = consul
        self._lock = threading.Lock()
        self._registered: Dict[str, str] = {}  # service_id -> alloc
        self._dirty = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_sync_failed = False

    def sync(self) -> None:
        instances = [
            inst
            for name in self.catalog.services()
            for inst in self.catalog.instances(name)
        ]
        want: Dict[str, Any] = {_service_id(i): i for i in instances}
        failed = False
        with self._lock:
            for sid in list(self._registered):
                if sid not in want:
                    try:
                        self.consul.deregister_service(sid)
                    except ExternalError as exc:
                        # keep tracking: retried on the next sync so a
                        # consul blip can't strand a stale registration
                        LOG.warning("consul deregister %s: %s", sid, exc)
                        failed = True
                        continue
                    self._registered.pop(sid, None)
            for sid, inst in want.items():
                if sid in self._registered:
                    continue
                try:
                    self.consul.register_service(
                        sid,
                        inst.service,
                        address=inst.address,
                        port=inst.port,
                        tags=list(inst.tags),
                    )
                    self._registered[sid] = inst.alloc_id
                except ExternalError as exc:
                    LOG.warning("consul register %s: %s", sid, exc)
                    failed = True
            self._last_sync_failed = failed

    def attach(self, store) -> None:
        """Alloc watchers fire under the store lock, so the callback
        only flags; the HTTP round trips run on this syncer's own
        thread — a slow or dead Consul can never stall state writes."""
        self._thread = threading.Thread(
            target=self._run, name="consul-syncer", daemon=True
        )
        self._thread.start()
        store.add_alloc_watcher(lambda _allocs: self._dirty.set())

    def _run(self) -> None:
        import time as _time

        last = _time.monotonic()
        while not self._stop.is_set():
            fired = self._dirty.wait(timeout=0.5)
            elapsed = _time.monotonic() - last
            # failed syncs retry on a short delay (not every tick — a
            # down Consul shouldn't be hammered), clean ones on the
            # periodic interval
            due = elapsed >= (
                2.0 if self._last_sync_failed
                else self.RESYNC_INTERVAL_S
            )
            if fired or due:
                self._dirty.clear()
                last = _time.monotonic()
                try:
                    self.sync()
                except Exception as exc:  # noqa: BLE001
                    LOG.warning("consul sync: %s", exc)
                    self._last_sync_failed = True

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


# ---------------------------------------------------------------------------
# Vault
# ---------------------------------------------------------------------------


class VaultClient:
    """Vault HTTP API subset (reference nomad/vault.go vaultClient:
    derive child tokens for tasks from the server's token, renew,
    revoke; client/vaultclient renews on the node)."""

    def __init__(self, address: str, token: str = "") -> None:
        self.address = address.rstrip("/")
        self.token = token

    def _headers(self) -> Dict[str, str]:
        return {"X-Vault-Token": self.token} if self.token else {}

    def derive_token(
        self,
        policies: List[str],
        ttl: str = "72h",
        renewable: bool = True,
        metadata: Optional[Dict[str, str]] = None,
    ) -> Dict[str, Any]:
        """Create a child token (reference vault.go CreateToken: role-
        scoped, renewable, per-task metadata for audit)."""
        out = _http(
            "POST",
            f"{self.address}/v1/auth/token/create",
            {
                "policies": policies,
                "ttl": ttl,
                "renewable": renewable,
                "display_name": "nomad-task",
                "meta": metadata or {},
            },
            self._headers(),
        )
        auth = (out or {}).get("auth") or {}
        if not auth.get("client_token"):
            raise ExternalError("vault returned no client_token")
        return auth

    def renew_self(self, token: str) -> Dict[str, Any]:
        out = _http(
            "POST",
            f"{self.address}/v1/auth/token/renew-self",
            {},
            {"X-Vault-Token": token},
        )
        return (out or {}).get("auth") or {}

    def revoke(self, token: str) -> None:
        _http(
            "POST",
            f"{self.address}/v1/auth/token/revoke",
            {"token": token},
            self._headers(),
        )

    def read_secret(self, path: str) -> Optional[Dict[str, Any]]:
        try:
            out = _http(
                "GET",
                f"{self.address}/v1/{path.lstrip('/')}",
                None,
                self._headers(),
            )
        except ExternalError:
            return None
        data = (out or {}).get("data")
        # KV v2 nests the payload one level deeper
        if isinstance(data, dict) and set(data) >= {"data", "metadata"}:
            return data["data"]
        return data


class VaultSecretsProvider:
    """SecretsProvider (client/templates.py protocol) backed by a real
    Vault — templates render `{{ secret "kv/web" "user" }}` through
    the external API, matching the reference's consul-template
    integration (taskrunner/template_hook)."""

    def __init__(self, vault: VaultClient) -> None:
        self.vault = vault

    def read(self, path: str) -> Optional[Dict[str, Any]]:
        return self.vault.read_secret(path)
