"""Deterministic accelerator fault injection.

Every DeviceSupervisor transition must be testable on a CPU-only box —
the whole point of the supervisor is surviving failure modes that only
real (wedged) hardware exhibits.  ``NOMAD_TPU_FAULT`` arms a fault plan
that the supervisor's guard/canary paths consult at well-defined
points:

  wedge_launch      the launch stage AND the canary block forever (a
                    wedged PJRT client: calls never return) — drives
                    watchdog trips and keeps the device LOST
  slow_fetch        the fetch stage sleeps past its watchdog budget but
                    eventually completes (a device stalling under
                    contention) — trips the deadline monitor while the
                    sacrificial thread finishes harmlessly
  init_block        the canary blocks forever (backend init hangs, the
                    BENCH_r05 rc=2 shape).  Like the real thing there
                    is no in-process recovery: backend init is
                    process-wide and memoized, so a parked init call
                    blocks every later attempt too (the supervisor's
                    single-flight canary models exactly that) — use
                    ``flaky`` for recoverable-failure scenarios
  flaky[:N]         the first N canary calls fail fast (default 3 —
                    exactly enough to walk HEALTHY -> DEGRADED -> LOST
                    with the default thresholds), then succeed, driving
                    the LOST -> RECOVERING -> HEALTHY round trip

Kinds compose as a comma list (``wedge_launch,flaky:2``).  Wedges park
on a shared stop event instead of a raw sleep so supervisor shutdown
releases every abandoned sacrificial thread promptly.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

# how long a "forever" wedge parks before giving up and erroring out
# (bounded only so abandoned threads cannot outlive long processes)
WEDGE_S = 3600.0
FAULT_ENV = "NOMAD_TPU_FAULT"
KNOWN_KINDS = ("wedge_launch", "slow_fetch", "init_block", "flaky")


class InjectedFault(Exception):
    """A deterministic injected failure (never raised in production)."""


class FaultPlan:
    """Parsed ``NOMAD_TPU_FAULT`` plan consulted by the supervisor."""

    def __init__(self, kinds: Optional[Dict[str, Optional[float]]] = None) -> None:
        self.kinds: Dict[str, Optional[float]] = dict(kinds or {})
        self._canary_calls = 0
        self._lock = threading.Lock()
        # wedges wait on this instead of sleeping so supervisor.stop()
        # releases every parked sacrificial thread
        self.stop_event = threading.Event()

    @classmethod
    def from_env(cls, env: Optional[dict] = None) -> "FaultPlan":
        raw = (env if env is not None else os.environ).get(
            FAULT_ENV, ""
        ).strip()
        kinds: Dict[str, Optional[float]] = {}
        for part in raw.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, param = part.partition(":")
            if name not in KNOWN_KINDS:
                # an unknown kind must be loud: a typo silently testing
                # nothing is worse than a crash in a test-only path
                raise ValueError(
                    f"unknown {FAULT_ENV} kind {name!r} "
                    f"(known: {', '.join(KNOWN_KINDS)})"
                )
            kinds[name] = float(param) if param else None
        return cls(kinds)

    @property
    def active(self) -> bool:
        return bool(self.kinds)

    def describe(self) -> List[str]:
        return [
            name if param is None else f"{name}:{param:g}"
            for name, param in sorted(self.kinds.items())
        ]

    def _wedge(self, what: str) -> None:
        """Park "forever" (until supervisor shutdown), then raise —
        the caller's sacrificial thread must never complete a wedged
        call successfully."""
        self.stop_event.wait(WEDGE_S)
        raise InjectedFault(f"injected wedge: {what}")

    # -- consultation points -------------------------------------------

    def stage_hook(self, stage: str, budget_s: float) -> None:
        """Called inside the sacrificial thread before the real stage
        work, while the pipeline targets the device backend."""
        if stage == "launch" and "wedge_launch" in self.kinds:
            self._wedge("launch")
        if stage == "fetch" and "slow_fetch" in self.kinds:
            # slow, not wedged: outlive the budget, then finish — the
            # deadline monitor must trip even though the call would
            # eventually have returned
            param = self.kinds["slow_fetch"]
            self.stop_event.wait(
                param if param else budget_s * 1.5 + 0.1
            )

    def canary_hook(self) -> None:
        """Called inside the canary's sacrificial thread before the
        probe kernel runs."""
        with self._lock:
            self._canary_calls += 1
            n = self._canary_calls
        if "wedge_launch" in self.kinds:
            # a wedged device wedges its canaries too — the supervisor
            # must stay LOST rather than flap back onto a dead chip
            self._wedge("canary")
        if "init_block" in self.kinds:
            self._wedge("canary init")
        if "flaky" in self.kinds:
            param = self.kinds["flaky"]
            limit = 3.0 if param is None else param
            if n <= limit:
                raise InjectedFault(f"injected flaky canary #{n}")
