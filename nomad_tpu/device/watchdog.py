"""Sacrificial-thread bounded calls + per-stage watchdog budgets.

A wedged PJRT client does not raise — it *blocks*, indefinitely, inside
a C extension call no Python-level timeout can interrupt (that is how
four bench rounds were lost: ``BENCH_r05.json`` rc=2, "backend init
blocked (no error raised)").  The only robust in-process containment is
to run the possibly-wedging call on a disposable thread and, when the
deadline passes, *abandon* the thread: the caller gets a
``DeviceTimeout`` and keeps scheduling; the sacrificial thread stays
parked inside the wedged call until process exit (it is a daemon and
holds no locks the pipeline needs).

Budgets come from an EWMA of the stage's own observed latency — a
launch that exceeds its historical cost by ``factor`` is wedged, not
slow — clamped to an operator-configurable [min, max] band so the first
launch (no history) and pathological EWMAs stay bounded.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

_UNSET = object()


class DeviceTimeout(Exception):
    """A guarded device call exceeded its watchdog budget."""

    def __init__(self, stage: str, budget_s: float) -> None:
        super().__init__(
            f"device stage {stage!r} exceeded its {budget_s:.2f}s "
            "watchdog budget (wedged accelerator?)"
        )
        self.stage = stage
        self.budget_s = budget_s


class _Runner:
    """One reusable sacrificial worker thread.  A healthy guarded call
    costs an Event handoff, not a thread spawn — the disposable-thread
    property is only needed when a deadline actually trips, at which
    point the runner is marked dead (its thread may be parked inside a
    wedged call forever) and the caller mints a replacement."""

    __slots__ = ("_submit", "_box", "dead", "_thread")

    def __init__(self, name: str) -> None:
        self._submit = threading.Event()
        self._box: Optional[dict] = None
        self.dead = False
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while True:
            self._submit.wait()
            self._submit.clear()
            box = self._box
            if box is None:
                continue
            try:
                box["result"] = box["fn"]()
            except BaseException as exc:  # noqa: BLE001 — re-raised
                box["error"] = exc
            finally:
                box["done"].set()

    def call(self, fn: Callable, timeout_s: float, stage: str):
        box: dict = {"fn": fn, "done": threading.Event()}
        self._box = box
        self._submit.set()
        if not box["done"].wait(timeout_s):
            # wedged mid-call: abandon this runner (never joined — the
            # thread may be stuck inside a blocked PJRT call forever)
            self.dead = True
            raise DeviceTimeout(stage, timeout_s)
        err = box.get("error", _UNSET)
        if err is not _UNSET:
            raise err
        return box.get("result")


_TLS = threading.local()


def bounded_call(
    fn: Callable, timeout_s: float, name: str = "device-bounded",
    stage: str = "call",
):
    """Run ``fn()`` on a sacrificial daemon thread, waiting at most
    ``timeout_s``.  On timeout the thread is abandoned (never joined —
    it may be stuck inside a wedged PJRT call forever) and
    ``DeviceTimeout`` is raised; otherwise the callable's result or
    exception propagates.

    The worker is per-calling-thread and REUSED across calls, so the
    hot pipeline path pays an Event handoff instead of a thread spawn;
    only a tripped deadline burns the thread (a new one is minted on
    the next call)."""
    runner: Optional[_Runner] = getattr(_TLS, "runner", None)
    if runner is None or runner.dead:
        runner = _Runner(name)
        _TLS.runner = runner
    return runner.call(fn, timeout_s, stage)


class BudgetTracker:
    """Per-stage EWMA latency -> watchdog deadline.

    ``budget(stage)`` returns ``clamp(factor * ewma, min_s, max_s)``;
    with no history yet the floor applies (a cold first launch must not
    trip on its own compile)."""

    def __init__(
        self,
        factor: float = 20.0,
        min_s: float = 5.0,
        max_s: float = 120.0,
        alpha: float = 0.2,
    ) -> None:
        self.factor = factor
        self.min_s = min_s
        self.max_s = max(max_s, min_s)
        self.alpha = alpha
        self._ewma: Dict[str, float] = {}
        self._lock = threading.Lock()

    def note(self, stage: str, dt_s: float) -> None:
        with self._lock:
            prev = self._ewma.get(stage)
            self._ewma[stage] = (
                dt_s
                if prev is None
                else (1.0 - self.alpha) * prev + self.alpha * dt_s
            )

    def ewma(self, stage: str) -> Optional[float]:
        with self._lock:
            return self._ewma.get(stage)

    def budget(self, stage: str) -> float:
        with self._lock:
            ewma = self._ewma.get(stage)
        if ewma is None:
            return self.min_s
        return min(self.max_s, max(self.min_s, self.factor * ewma))

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            stages = dict(self._ewma)
        return {
            stage: {
                "ewma_s": round(ewma, 6),
                "budget_s": round(self.budget(stage), 6),
            }
            for stage, ewma in stages.items()
        }
