"""Bounded accelerator preflight: ``python -m nomad_tpu.device.preflight``.

The supervisor's canary probe as a standalone check, absorbing the
ad-hoc preflight that used to live in ``bench.py`` and the raw retry
logic of the (since deleted) ``tools/tpu_retry_loop.sh`` wrapper:
take the cross-process device lock, then retry a bounded-time
backend-init + canary kernel until the accelerator answers or the
deadline passes.

Prints ONE machine-readable state line on stdout::

    DEVICE_PREFLIGHT {"state": "HEALTHY", "attempts": 1, ...}

and exits 0 when the device answered (or no accelerator is configured),
2 otherwise — the contract unattended retry loops script against
(``while ! python -m nomad_tpu.device.preflight; do sleep ...; done``).

Env knobs: ``NOMAD_TPU_PREFLIGHT_S`` (total budget, default 600; the
legacy ``BENCH_PREFLIGHT_S`` is honored as a fallback), plus the
supervisor's ``NOMAD_TPU_PROBE_TIMEOUT_S`` per-attempt deadline and the
device lock's ``NOMAD_TPU_DEVICE_LOCK_WAIT``.
"""
from __future__ import annotations

import json
import os
import sys
import time
from typing import Callable, Dict, Optional

from .supervisor import HEALTHY, DeviceSupervisor

# preflight verdicts beyond the supervisor's state machine
SKIPPED = "SKIPPED"  # explicit opt-out (budget <= 0)
LOCK_BUSY = "LOCK_BUSY"  # another process holds the accelerator
FATAL = "FATAL"  # permanent (e.g. jax not importable)
UNREACHABLE = "UNREACHABLE"  # deadline passed without a canary pass
# verdicts callers may proceed on
HEALTHY_STATES = (HEALTHY, SKIPPED)

_RETRY_SLEEP_S = 10.0


def _stderr(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def run_preflight(
    total_s: Optional[float] = None,
    log: Callable[[str], None] = _stderr,
) -> Dict:
    """Probe the accelerator until it answers or ``total_s`` passes.
    Returns the machine-readable result dict (the state line payload);
    never raises."""
    from ..device_lock import align_jax_platforms, ensure_device_lock

    # honor an explicit CPU-only env even under a tunnel sitecustomize
    # that pinned jax_platforms via config (config beats env)
    align_jax_platforms()
    if total_s is None:
        total_s = float(
            os.environ.get(
                "NOMAD_TPU_PREFLIGHT_S",
                os.environ.get("BENCH_PREFLIGHT_S", 600),
            )
        )
    if total_s <= 0:
        return {"state": SKIPPED, "attempts": 0}
    # exclusive accelerator lock FIRST: a second jax process against a
    # tunneled single-chip session wedges it for everyone
    if not ensure_device_lock("device preflight"):
        log("preflight: accelerator lock busy past deadline")
        return {"state": LOCK_BUSY, "attempts": 0}
    # a throwaway supervisor: its canary + bounded-call machinery IS
    # the preflight; expected=True even on CPU-only boxes (a CPU canary
    # passes instantly, preserving the old always-probe behavior).
    # init_grace_s=0: preflight attempts must be bounded by the probe
    # timeout alone — the OUTER total_s loop owns the slow-init wait
    # (the single-flight canary keeps retries from stacking threads on
    # the memoized init; once it completes, the next attempt passes)
    sup = DeviceSupervisor(metrics=None, expected=True, init_grace_s=0.0)
    deadline = time.monotonic() + total_s
    attempts = 0
    retried = False
    try:
        while True:
            attempts += 1
            t0 = time.monotonic()
            ok = sup.probe_once()
            if not ok and str(sup.last_error or "").startswith(
                ("ImportError", "ModuleNotFoundError")
            ):
                # permanent: no amount of waiting installs jax
                return {
                    "state": FATAL,
                    "attempts": attempts,
                    "error": sup.last_error,
                }
            if ok:
                if retried:
                    log("preflight: device ok after retrying")
                return {
                    "state": HEALTHY,
                    "attempts": attempts,
                    "latency_ms": round(
                        (time.monotonic() - t0) * 1000.0, 3
                    ),
                }
            retried = True
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            log(
                f"preflight: canary failed "
                f"({sup.last_error}); retrying "
                f"({remaining:.0f}s left)"
            )
            time.sleep(min(_RETRY_SLEEP_S, max(0.0, remaining)))
    finally:
        sup.stop()
    return {
        "state": UNREACHABLE,
        "attempts": attempts,
        "budget_s": total_s,
        "error": sup.last_error
        or "backend init blocked (no error raised)",
    }


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="nomad_tpu.device.preflight",
        description="bounded accelerator canary probe",
    )
    parser.add_argument(
        "--budget-s", type=float, default=None,
        help="total retry budget (default NOMAD_TPU_PREFLIGHT_S/600)",
    )
    args = parser.parse_args(argv)
    result = run_preflight(total_s=args.budget_s)
    # the ONE machine-readable line scripts key on
    print("DEVICE_PREFLIGHT " + json.dumps(result), flush=True)
    return 0 if result["state"] in HEALTHY_STATES else 2


if __name__ == "__main__":
    sys.exit(main())
