"""Accelerator supervisor: in-process device health, launch watchdogs
and hot CPU failover.

The device subsystem owns accelerator liveness for the whole server:

* ``supervisor``  — the DeviceSupervisor state machine
  (HEALTHY -> DEGRADED -> LOST -> RECOVERING) with canary health
  probes, EWMA-budgeted launch watchdogs and listener-driven failover;
* ``watchdog``    — sacrificial-thread bounded calls and per-stage
  deadline budgets (a wedged PJRT client is *abandoned*, never joined);
* ``faults``      — deterministic fault injection
  (``NOMAD_TPU_FAULT=wedge_launch|slow_fetch|init_block|flaky``) so
  every transition is testable on CPU;
* ``preflight``   — ``python -m nomad_tpu.device.preflight``, the
  bounded canary probe absorbing the ad-hoc checks that used to live
  in ``bench.py`` and the deleted ``tools/tpu_retry_loop.sh`` wrapper.
"""
from .faults import FaultPlan, InjectedFault
from .supervisor import (
    CPU_ONLY,
    DEGRADED,
    HEALTHY,
    LOST,
    RECOVERING,
    STATE_CODES,
    DeviceSupervisor,
)
from .watchdog import BudgetTracker, DeviceTimeout, bounded_call

__all__ = [
    "BudgetTracker",
    "CPU_ONLY",
    "DEGRADED",
    "DeviceSupervisor",
    "DeviceTimeout",
    "FaultPlan",
    "HEALTHY",
    "InjectedFault",
    "LOST",
    "RECOVERING",
    "STATE_CODES",
    "bounded_call",
]
