"""DeviceSupervisor: the accelerator liveness state machine.

One supervisor per server owns three jobs the batch pipeline must never
do inline:

1. **Health probes.**  A watchdog thread launches a tiny canary kernel
   (bounded backend init + ``a + 1`` on an 8-vector, executed on a
   sacrificial thread) on a configurable cadence, so a wedged PJRT
   client is *detected* as LOST instead of hanging whichever thread
   touches the device next.

2. **Launch watchdogs.**  ``guard(stage, fn)`` wraps the batch worker's
   assemble/launch/fetch stages with deadline monitors; a stage that
   exceeds its EWMA-derived budget by a large factor trips the
   supervisor (and raises ``DeviceTimeout`` into the worker's existing
   per-stage error handling, which routes the affected evals to the
   exact sequential path — zero dropped evals).

3. **The HEALTHY -> DEGRADED -> LOST -> RECOVERING state machine.**
   Entering LOST fails the pipeline over to the CPU JAX backend: the
   backend epoch bumps and every subscribed listener (the batch
   worker) flushes its backend-keyed caches, re-jits on CPU and
   disables the sharded mesh path.  The CPU kernels are bit-identical
   to the device kernels (the CPU-parity sweep in
   ``BENCH_CPU_PARITY_r05.json``), so failover preserves decision
   parity.  In LOST the canary keeps probing the *device*; a success
   moves to RECOVERING, and after ``recover_canaries`` consecutive
   passes the pipeline flips back; the registered re-warm hooks (the
   ``NOMAD_TPU_WARM_ON_START`` machinery) then recompile the launch
   shapes for the restored backend, the cold-compile shield covering
   the gap.

State is exported as the ``device.state`` gauge, ``/v1/device``, and —
for failover incidents — a flight-recorder trace
(``device:failover:<n>``) whose ``device.failover`` event names the
tripped watchdog.

Env knobs (config-file equivalents in ``config.DeviceConfig``):

  NOMAD_TPU_SUPERVISOR         1 forces supervision on (0 off) even on
                               CPU-only backends — the fault-injection
                               and soak tests run this way
  NOMAD_TPU_PROBE_INTERVAL_S   canary cadence (default 30)
  NOMAD_TPU_PROBE_TIMEOUT_S    canary deadline (default 10)
  NOMAD_TPU_INIT_GRACE_S       deadline floor until the FIRST canary
                               or guarded stage succeeds (default 600)
                               — real PJRT backend init takes tens of
                               seconds, and a cold start must not read
                               as a wedge
  NOMAD_TPU_WATCHDOG_FACTOR    budget = factor * stage EWMA (default 20)
  NOMAD_TPU_WATCHDOG_MIN_S     budget floor (default 5)
  NOMAD_TPU_WATCHDOG_MAX_S     budget ceiling (default 120)
  NOMAD_TPU_LOST_PROBES        consecutive canary failures past
                               DEGRADED before LOST (default 2)
  NOMAD_TPU_RECOVER_CANARIES   consecutive passes before flipping back
                               (default 3)
"""
from __future__ import annotations

import itertools
import logging
import os
import threading
import time
from collections import deque
from contextlib import nullcontext
from typing import Callable, Dict, List, Optional

LOG = logging.getLogger("nomad_tpu.device")

from ..telemetry import percentile as _percentile
from ..trace import TRACE
from .faults import FAULT_ENV, FaultPlan
from .watchdog import BudgetTracker, DeviceTimeout, bounded_call

# -- states -----------------------------------------------------------

CPU_ONLY = "CPU_ONLY"  # no accelerator expected; supervision idle
HEALTHY = "HEALTHY"
DEGRADED = "DEGRADED"
LOST = "LOST"
RECOVERING = "RECOVERING"

# the device.state gauge encoding (documented in docs/ARCHITECTURE.md)
STATE_CODES = {
    CPU_ONLY: 0,
    HEALTHY: 1,
    DEGRADED: 2,
    LOST: 3,
    RECOVERING: 4,
}

# pipeline-facing: in these states launches target the device backend
_DEVICE_STATES = frozenset({CPU_ONLY, HEALTHY, DEGRADED})

# -- metric registry ---------------------------------------------------
# every device.* name the supervisor emits, zero-registered at start so
# prometheus_text() exports the whole family before the first incident
# (tools/check_stage_accounting.py lints emissions against these)
METRIC_COUNTERS = frozenset(
    {
        "device.failover",
        "device.recovered",
        "device.canary_ok",
        "device.canary_fail",
        "device.watchdog_trips",
        "device.probe_timeouts",
    }
)
METRIC_GAUGES = frozenset(
    {
        "device.state",
        "device.backend_epoch",
    }
)
METRIC_SAMPLES = frozenset(
    {
        "device.probe_latency_ms",
        # failover detect-to-resume: LOST transition to the restored
        # HEALTHY flip, the latency the SLO engine's
        # failover_detect_to_resume objective grades
        "device.failover_resume_ms",
    }
)

# deadline for one post-recovery re-warm hook: generous (XLA compiles
# for every warmed shape), but bounded — a device that re-wedges
# mid-warm must not hang the probe thread that supervises it
REWARM_BUDGET_S = 600.0

# ring of recent probe latencies backing the /v1/device + bench
# percentile summaries (independent of any Metrics sink)
_PROBE_RING = 256
# transitions retained for /v1/device history
_HISTORY = 64

_INCIDENT_SEQ = itertools.count(1)


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        LOG.warning("invalid %s=%r; using %s", name, raw, default)
        return default


class DeviceSupervisor:
    """Owns accelerator liveness for one server process."""

    def __init__(
        self,
        metrics=None,
        config=None,
        canary: Optional[Callable[[], object]] = None,
        expected: Optional[bool] = None,
        probe_interval_s: Optional[float] = None,
        probe_timeout_s: Optional[float] = None,
        watchdog_factor: Optional[float] = None,
        watchdog_min_s: Optional[float] = None,
        watchdog_max_s: Optional[float] = None,
        lost_probes: Optional[int] = None,
        recover_canaries: Optional[int] = None,
        init_grace_s: Optional[float] = None,
    ) -> None:
        def opt(value, cfg_attr, env, default):
            if value is not None:
                return value
            if config is not None and getattr(
                config, cfg_attr, None
            ) is not None:
                return getattr(config, cfg_attr)
            return _env_float(env, default)

        self.metrics = metrics
        self.faults = FaultPlan.from_env()
        self.probe_interval_s = float(
            opt(probe_interval_s, "probe_interval_s",
                "NOMAD_TPU_PROBE_INTERVAL_S", 30.0)
        )
        self.probe_timeout_s = float(
            opt(probe_timeout_s, "probe_timeout_s",
                "NOMAD_TPU_PROBE_TIMEOUT_S", 10.0)
        )
        self.lost_probes = max(1, int(
            opt(lost_probes, "lost_probes", "NOMAD_TPU_LOST_PROBES", 2)
        ))
        self.recover_canaries = max(1, int(
            opt(recover_canaries, "recover_canaries",
                "NOMAD_TPU_RECOVER_CANARIES", 3)
        ))
        # deadline floor until the device has answered ONCE: first
        # contact pays full PJRT backend init (tens of seconds on real
        # hardware — this repo's own bench history budgeted 600s for
        # it), which must not read as a wedge
        self.init_grace_s = float(
            opt(init_grace_s, "init_grace_s",
                "NOMAD_TPU_INIT_GRACE_S", 600.0)
        )
        self._device_ready = False
        self.budgets = BudgetTracker(
            factor=float(
                opt(watchdog_factor, "watchdog_factor",
                    "NOMAD_TPU_WATCHDOG_FACTOR", 20.0)
            ),
            min_s=float(
                opt(watchdog_min_s, "watchdog_min_s",
                    "NOMAD_TPU_WATCHDOG_MIN_S", 5.0)
            ),
            max_s=float(
                opt(watchdog_max_s, "watchdog_max_s",
                    "NOMAD_TPU_WATCHDOG_MAX_S", 120.0)
            ),
        )
        self._canary = canary or self._default_canary
        self.expected = (
            expected
            if expected is not None
            else self._accelerator_expected()
        )
        self._state = HEALTHY if self.expected else CPU_ONLY
        self.backend_epoch = 0
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._listeners: List[Callable] = []
        self._warm_hooks: List[Callable] = []
        self._history: deque = deque(maxlen=_HISTORY)
        self._probe_ring: deque = deque(maxlen=_PROBE_RING)
        self._canary_fail_streak = 0
        self._recover_streak = 0
        # single-flight canary: backend init is process-wide and
        # memoized behind a lock, so parallel probe attempts against a
        # wedged device would only stack sacrificial threads on the
        # same blocked call (the old bench preflight kept ONE prober
        # for exactly this reason).  While a canary is still in
        # flight, later probes report the wedge instantly instead of
        # spawning another thread.
        self._canary_lock = threading.Lock()
        self._canary_inflight = False
        self._canary_started = 0.0
        # generation counter orphans a parked attempt when the
        # relaunch window passes, so its eventual finally-clear can't
        # clobber a newer attempt's in-flight flag
        self._canary_gen = 0
        self.failover_count = 0
        self.recovered_count = 0
        self.watchdog_trips = 0
        self.canary_ok = 0
        self.canary_fail = 0
        self.probe_timeouts = 0
        self.last_error: Optional[str] = None
        self._incident: Optional[str] = None
        self.last_incident: Optional[str] = None
        # detect-to-resume stopwatch: stamped at failover, read (and
        # cleared) when the restored flip samples
        # device.failover_resume_ms
        self._failover_at: Optional[float] = None
        # unhealthy-time accounting (bench time_degraded_s): cumulative
        # seconds spent outside HEALTHY/CPU_ONLY plus the live segment
        self._unhealthy_accum = 0.0
        self._unhealthy_since: Optional[float] = None
        self._since_wall = time.time()
        # the platform the canary probes: the first non-cpu platform
        # named in JAX_PLATFORMS (None = jax's default device, which on
        # CPU-only test boxes is the cpu backend the faults simulate)
        plats = [
            p.strip()
            for p in os.environ.get("JAX_PLATFORMS", "").split(",")
            if p.strip() and p.strip() != "cpu"
        ]
        self._probe_backend = plats[0] if plats else None
        self._cpu_device = None
        self._register_metrics()
        # happens-before sanitizer (NOMAD_TPU_TSAN=1)
        from ..tsan import maybe_instrument

        maybe_instrument(self, "DeviceSupervisor")

    # -- construction helpers ------------------------------------------

    @staticmethod
    def _accelerator_expected() -> bool:
        forced = os.environ.get("NOMAD_TPU_SUPERVISOR")
        if forced == "1":
            return True
        if forced == "0":
            return False
        if os.environ.get(FAULT_ENV, "").strip():
            # an armed fault plan simulates an accelerator: the
            # supervisor must be live for the faults to mean anything
            return True
        from ..device_lock import _cpu_only

        plats = os.environ.get("JAX_PLATFORMS", "")
        return bool(plats) and not _cpu_only(plats)

    def _register_metrics(self) -> None:
        metrics = self.metrics
        if metrics is None:
            return
        metrics.preregister(
            counters=METRIC_COUNTERS,
            gauges=METRIC_GAUGES,
            samples=METRIC_SAMPLES,
        )
        metrics.set_gauge("device.state", STATE_CODES[self._state])
        metrics.set_gauge("device.backend_epoch", 0.0)

    def _incr(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.incr(name)

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        """Start the probe thread (no-op when no accelerator is
        expected — CPU-only test servers must stay thread-free)."""
        if not self.expected:
            return
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self.faults.stop_event.clear()
            self._thread = threading.Thread(
                target=self._probe_loop,
                name="device-supervisor",
                daemon=True,
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # release every sacrificial thread parked on an injected wedge
        self.faults.stop_event.set()

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            try:
                self.probe_once()
            except Exception:  # noqa: BLE001 — supervision must survive
                LOG.exception("device probe crashed")

    # -- state queries -------------------------------------------------

    def state(self) -> str:
        return self._state

    def failed_over(self) -> bool:
        """True while the pipeline must target the CPU backend."""
        return self._state not in _DEVICE_STATES

    def device_available(self) -> bool:
        """True when launches may target the accelerator."""
        return self.expected and self._state in (HEALTHY, DEGRADED)

    def jax_device(self):
        """Explicit placement target for device_put: the CPU backend
        while failed over, None (jax's default device) otherwise."""
        if not self.failed_over():
            return None
        if self._cpu_device is None:
            try:
                import jax

                self._cpu_device = jax.devices("cpu")[0]
            except Exception:  # noqa: BLE001 — placement is best-effort
                return None
        return self._cpu_device

    def subscribe(self, fn: Callable) -> None:
        """Register a backend-transition listener
        ``fn(old_state, new_state, reason)`` (called synchronously on
        the transitioning thread, after the epoch bump)."""
        self._listeners.append(fn)

    def add_warm_hook(self, fn: Callable) -> None:
        """Register a re-warm hook run (best-effort) right after a
        recovered supervisor flips the pipeline back to the device —
        the NOMAD_TPU_WARM_ON_START machinery, reused so the restored
        backend's launch shapes recompile under the new epoch (until
        then the cold-compile shield routes evals to the exact
        sequential path).  Idempotent: leadership re-establishment
        re-registers the same hooks, and duplicates would multiply the
        post-recovery compile work."""
        if fn not in self._warm_hooks:
            self._warm_hooks.append(fn)

    # -- launch watchdogs ----------------------------------------------

    def _effective_budget(self, stage: str) -> float:
        """Stage deadline, floored to the init grace until the device
        has answered once — the first guarded call pays full backend
        init, which must not read as a wedge."""
        budget = self.budgets.budget(stage)
        if not self._device_ready:
            return max(budget, self.init_grace_s)
        return budget

    def guard(
        self, stage: str, fn: Callable, eval_id: Optional[str] = None
    ):
        """Run one pipeline stage under a deadline monitor.  While no
        accelerator is expected (or the pipeline is already failed over
        to CPU — the backend hot failover exists because CPU cannot
        wedge) the call passes straight through with zero overhead."""
        if not self.expected or self.failed_over():
            return fn()
        budget = self._effective_budget(stage)

        def wrapped():
            self.faults.stage_hook(stage, budget)
            return fn()

        t0 = time.monotonic()
        try:
            result = bounded_call(
                wrapped, budget, name=f"device-{stage}", stage=stage
            )
        except DeviceTimeout:
            self._watchdog_tripped(stage, budget, eval_id)
            raise
        self._device_ready = True
        self.budgets.note(stage, time.monotonic() - t0)
        return result

    def _watchdog_tripped(
        self, stage: str, budget_s: float, eval_id: Optional[str]
    ) -> None:
        self.watchdog_trips += 1
        self._incr("device.watchdog_trips")
        self.last_error = (
            f"watchdog: {stage} exceeded {budget_s:.2f}s budget"
        )
        if eval_id:
            # name the tripped watchdog on the eval that paid for it
            TRACE.event(
                eval_id, "device.watchdog_trip",
                stage=stage, budget_ms=budget_s * 1000.0,
            )
        LOG.warning(
            "device watchdog tripped: stage %s exceeded %.2fs budget",
            stage, budget_s,
        )
        from ..decisions import DECISIONS

        ewma = self.budgets.ewma(stage)
        DECISIONS.record(
            "watchdog_budget",
            "trip",
            inputs={
                "stage": stage,
                "budget_s": round(budget_s, 3),
                "ewma_s": round(ewma, 4) if ewma is not None else None,
                "factor": self.budgets.factor,
                "backend_epoch": self.backend_epoch,
            },
            alternatives=["keep_waiting"],
            outcome="lost",
            trace_id=eval_id or self._incident or "",
            metrics=self.metrics,
        )
        self._transition(LOST, f"watchdog:{stage}", stage=stage)

    def trip(self, stage: str = "manual") -> None:
        """Operator/test surface: force a LOST transition (and the
        failover it implies) as if a watchdog had tripped."""
        if not self.expected:
            return
        self._transition(LOST, f"watchdog:{stage}", stage=stage)

    # -- health probes -------------------------------------------------

    def _default_canary(self):
        """Bounded-init canary: put an 8-vector on the probed backend
        and run a jitted ``a + 1`` — exactly the kernel the old
        ``bench.py`` preflight used, small enough to be free and
        end-to-end enough (init + compile + execute + fetch) to catch
        every wedge mode seen so far."""
        import jax
        import jax.numpy as jnp

        device = (
            jax.devices(self._probe_backend)[0]
            if self._probe_backend
            else jax.devices()[0]
        )
        x = jax.device_put(jnp.ones(8), device)
        # nomadlint: disable=jit-purity -- deliberate per-probe retrace: the canary must exercise the FULL trace+compile+execute+fetch path each probe (a cached wrapper would skip the compile wedge mode)
        return float(jax.jit(lambda a: a + 1)(x).sum())

    def _canary_call(self):
        self.faults.canary_hook()
        return self._canary()

    def _canary_relaunch_s(self) -> float:
        """How long an in-flight (presumed wedged) canary attempt
        blocks new attempts.  Short enough that a device whose old
        parked RPC never returns is still re-probed (the documented
        LOST -> RECOVERING path must stay reachable), long enough that
        a persistent wedge leaks at most ~one abandoned thread per
        window instead of one per probe."""
        return max(60.0, 4.0 * self.probe_timeout_s)

    def _canary_bounded(self):
        """One bounded canary attempt, single-flight: while a previous
        attempt's sacrificial thread is still parked inside a wedged
        call, report the wedge immediately instead of stacking another
        thread behind the same process-wide memoized backend init —
        until the relaunch window passes, after which the parked
        attempt is orphaned and a fresh probe runs (device recovery
        must stay observable even when the old call never returns)."""
        now = time.monotonic()
        with self._canary_lock:
            if self._canary_inflight:
                if (
                    now - self._canary_started
                    < self._canary_relaunch_s()
                ):
                    raise DeviceTimeout(
                        "canary_inflight", self.probe_timeout_s
                    )
                # orphan the parked attempt: bump the generation so
                # its eventual finally-clear becomes a no-op
                self._canary_gen += 1
            self._canary_inflight = True
            self._canary_started = now
            gen = self._canary_gen

        def call():
            try:
                return self._canary_call()
            finally:
                with self._canary_lock:
                    if self._canary_gen == gen:
                        self._canary_inflight = False

        timeout = self.probe_timeout_s
        if not self._device_ready:
            timeout = max(timeout, self.init_grace_s)
        return bounded_call(
            call, timeout, name="device-canary", stage="canary"
        )

    def probe_once(self) -> bool:
        """Run one canary probe and feed the state machine.  Returns
        the probe verdict (True = device answered in time)."""
        if not self.expected:
            return True
        t0 = time.monotonic()
        ok = False
        timed_out = False
        measured = True
        err: Optional[str] = None
        try:
            self._canary_bounded()
            ok = True
        except DeviceTimeout as exc:
            timed_out = True
            err = str(exc)
            # an instant still-in-flight verdict is wedge evidence,
            # not a latency measurement
            measured = exc.stage != "canary_inflight"
        except Exception as exc:  # noqa: BLE001 — any failure counts
            err = f"{type(exc).__name__}: {exc}"
        dt = time.monotonic() - t0
        if measured:
            with self._lock:
                # status() sorts this ring from other threads; appends
                # must not race its iteration
                self._probe_ring.append(dt * 1000.0)
            if self.metrics is not None:
                self.metrics.add_sample(
                    "device.probe_latency_ms", dt * 1000.0
                )
        incident = self._incident
        if incident is not None:
            TRACE.add_span(
                incident, "device.probe", t0, dt,
                ok=ok, timeout=timed_out,
            )
        if ok:
            self._note_canary_ok()
        else:
            self._note_canary_fail(err, timed_out)
        return ok

    def _note_canary_ok(self) -> None:
        self.canary_ok += 1
        self._incr("device.canary_ok")
        self._canary_fail_streak = 0
        self._device_ready = True
        state = self._state
        if state == DEGRADED:
            self._transition(HEALTHY, "canary_ok")
        elif state == LOST:
            self._recover_streak = 1
            self._transition(RECOVERING, "canary_ok")
        elif state == RECOVERING:
            self._recover_streak += 1
            if self._recover_streak >= self.recover_canaries:
                self._transition(
                    HEALTHY,
                    f"recovered after {self._recover_streak} canaries",
                )
                # re-warm AFTER the flip: the hooks must compile for
                # the restored backend under the post-restore epoch
                # (before the flip they would target the CPU fallback
                # and the restore's cache flush would discard every
                # warmed shape).  Until they finish, the cold-compile
                # shield keeps evals on the exact sequential path.
                self._run_warm_hooks()

    def _note_canary_fail(
        self, err: Optional[str], timed_out: bool
    ) -> None:
        self.canary_fail += 1
        self._incr("device.canary_fail")
        self.last_error = err
        self._canary_fail_streak += 1
        state = self._state
        if timed_out:
            self.probe_timeouts += 1
            self._incr("device.probe_timeouts")
            # a canary that BLOCKS is a wedge, not a degradation — the
            # next pipeline launch would hang the same way
            if state not in (LOST,):
                self._transition(LOST, "probe_timeout")
            return
        if state == HEALTHY:
            self._transition(DEGRADED, f"canary_fail: {err}")
        elif state == DEGRADED:
            if self._canary_fail_streak >= 1 + self.lost_probes:
                self._transition(
                    LOST,
                    f"{self._canary_fail_streak} consecutive canary "
                    "failures",
                )
        elif state == RECOVERING:
            self._transition(LOST, f"canary_fail_in_recovery: {err}")

    def _run_warm_hooks(self) -> None:
        """Re-warm the launch shapes for the just-restored backend
        (best-effort: a warm failure only means the first
        post-recovery launches pay their compiles through the
        cold-compile shield).  Runs after the restore flip, so the
        spans land on the (already closed) incident trace via its
        retained id."""
        tid = self.last_incident
        for hook in self._warm_hooks:
            try:
                with TRACE.span(
                    tid or "", "device.rewarm"
                ) if tid else nullcontext():
                    # bounded: a device that re-wedges mid-warm must
                    # not hang the probe thread; the next canaries
                    # will re-detect it
                    bounded_call(
                        hook, REWARM_BUDGET_S,
                        name="device-rewarm", stage="rewarm",
                    )
            except Exception:  # noqa: BLE001
                LOG.exception("device re-warm hook failed")

    # -- transitions ---------------------------------------------------

    def _transition(
        self, new: str, reason: str, stage: Optional[str] = None
    ) -> None:
        with self._lock:
            old = self._state
            if old == new or old == CPU_ONLY:
                return
            self._state = new
            now = time.monotonic()
            self._since_wall = time.time()
            # unhealthy-time accounting
            if old == HEALTHY and new != HEALTHY:
                self._unhealthy_since = now
            elif new == HEALTHY and self._unhealthy_since is not None:
                self._unhealthy_accum += now - self._unhealthy_since
                self._unhealthy_since = None
            failover = new == LOST and old in (HEALTHY, DEGRADED)
            restored = new == HEALTHY and old == RECOVERING
            if failover or restored:
                self.backend_epoch += 1
            failover_at = None
            if failover:
                self.failover_count += 1
                # detect-to-resume stopwatch start: sampled (and
                # cleared) by the matching restored transition
                self._failover_at = now
            if restored:
                self.recovered_count += 1
                failover_at = self._failover_at
                self._failover_at = None
            self._history.append(
                {
                    "at": self._since_wall,
                    "from": old,
                    "to": new,
                    "reason": reason,
                }
            )
        LOG.warning(
            "device supervisor: %s -> %s (%s)", old, new, reason
        )
        if self.metrics is not None:
            self.metrics.set_gauge("device.state", STATE_CODES[new])
            self.metrics.set_gauge(
                "device.backend_epoch", float(self.backend_epoch)
            )
        if failover:
            self._incr("device.failover")
            self._open_incident(old, reason, stage)
        incident = self._incident
        if incident is not None:
            TRACE.event(
                incident, "device.state_change",
                state_from=old, state_to=new, reason=reason,
            )
        if failover or restored:
            # backend flip: listeners flush their backend-keyed caches
            # before any further launch can read stale device state
            span_ctx = (
                TRACE.span(incident, "device.flush", to=new)
                if incident is not None
                else nullcontext()
            )
            with span_ctx:
                for listener in list(self._listeners):
                    try:
                        listener(old, new, reason)
                    except Exception:  # noqa: BLE001
                        LOG.exception(
                            "device transition listener failed"
                        )
        if restored:
            self._incr("device.recovered")
            if failover_at is not None and self.metrics is not None:
                self.metrics.add_sample(
                    "device.failover_resume_ms",
                    (time.monotonic() - failover_at) * 1000.0,
                    exemplar=self._incident or "",
                )
            self._close_incident(reason)

    def _open_incident(
        self, old: str, reason: str, stage: Optional[str]
    ) -> None:
        tid = f"device:failover:{next(_INCIDENT_SEQ)}"
        self._incident = tid
        self.last_incident = tid
        TRACE.begin(tid, root_span="device.incident", kind="device")
        TRACE.event(
            tid, "device.failover",
            watchdog=stage or "", reason=reason, state_from=old,
        )

    def _close_incident(self, reason: str) -> None:
        tid = self._incident
        if tid is None:
            return
        TRACE.event(
            tid, "device.recover",
            reason=reason, canaries=self._recover_streak,
        )
        TRACE.finish(tid, "recovered")
        self._incident = None

    # -- status --------------------------------------------------------

    def time_degraded_s(self) -> float:
        accum = self._unhealthy_accum
        since = self._unhealthy_since
        if since is not None:
            accum += time.monotonic() - since
        return accum

    def status(self) -> Dict:
        """The /v1/device payload (also the bench's
        ``device_supervisor`` block source)."""
        with self._lock:
            ordered = sorted(self._probe_ring)
            history = list(self._history)
            return self._status_locked(ordered, history)

    def _status_locked(self, ordered, history) -> Dict:
        # the whole payload is read under self._lock (RLock): /v1/device
        # polls race the probe thread's transitions, and a torn
        # multi-field view (state from before a failover, epoch from
        # after) would mislead exactly the operator debugging it
        return {
            "enabled": self.expected,
            "state": self._state,
            "state_code": STATE_CODES[self._state],
            "backend": "cpu" if self.failed_over() else "device",
            "backend_epoch": self.backend_epoch,
            # False until the device answered once; deadlines are
            # floored to init_grace_s while it is
            "device_ready": self._device_ready,
            "since": self._since_wall,
            "failover_count": self.failover_count,
            "recovered_count": self.recovered_count,
            "watchdog_trips": self.watchdog_trips,
            "canary_ok": self.canary_ok,
            "canary_fail": self.canary_fail,
            "probe_timeouts": self.probe_timeouts,
            "time_degraded_s": round(self.time_degraded_s(), 3),
            "probe_latency_ms": {
                "count": len(ordered),
                "p50": round(_percentile(ordered, 0.50), 3),
                "p99": round(_percentile(ordered, 0.99), 3),
            },
            "budgets": self.budgets.snapshot(),
            "probe_interval_s": self.probe_interval_s,
            "probe_timeout_s": self.probe_timeout_s,
            "faults": self.faults.describe(),
            "last_error": self.last_error,
            "last_incident": self.last_incident,
            "history": history,
        }


