"""SLO engine: declared objectives + multi-window burn rates.

Declares what "meeting its objectives" means for this control plane —
interactive placement latency, zero lost evals, bounded shed rate,
bounded storm-fallback rate, failover detect-to-resume — and grades
each over the retained metric history ring
(``NOMAD_TPU_OBS_HISTORY``), SRE-alerting style: a **fast** window
(the last ``NOMAD_TPU_SLO_FAST_N`` snapshots — "is it happening
now?") and a **slow** window (``NOMAD_TPU_SLO_SLOW_N`` — "is it
material?").  Each objective's burn rate is its observed
badness divided by its error budget; status is

* ``BURNING`` when BOTH windows burn at >= ``NOMAD_TPU_SLO_BURN``
  (fast alone is noise, slow alone is history),
* ``WARN`` when EITHER window reaches ``NOMAD_TPU_SLO_WARN``,
* ``OK`` otherwise (including "not enough history yet": the engine
  never pages on an empty ring).

The engine is read-path only — ``status()`` folds over snapshot
windows already paid for by the history thread, so there is no
steady-state cost and nothing to instrument on the hot path.  The
decision ledger (``nomad_tpu/decisions.py``) is the matching write
path; together they are the flight data ROADMAP item 6's self-tuning
controller consumes: objectives to optimize, decisions to tune.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

__all__ = [
    "SLO_COUNTERS",
    "SLO_GAUGES",
    "SLOEngine",
    "slo_enabled",
]

# zero-registered at Server construction (slo-metrics lint): absence
# of a series must mean "never evaluated", not "not exported"
SLO_COUNTERS = ("slo.evaluations",)
SLO_GAUGES = ("slo.worst", "slo.burning", "slo.warn")

# a zero-tolerance objective with any violation burns at this rate —
# far past any sane threshold, finite so JSON stays plain
_ZERO_TOLERANCE_BURN = 1000.0

_STATUS_RANK = {"OK": 0, "WARN": 1, "BURNING": 2}


def slo_enabled() -> bool:
    return os.environ.get("NOMAD_TPU_SLO", "1") != "0"


def _knob_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _knob_int(name: str, default: int, lo: int) -> int:
    try:
        return max(lo, int(os.environ.get(name, str(default))))
    except ValueError:
        return default


class SLOEngine:
    """Grades declared objectives over the metric history ring."""

    def __init__(self, metrics, history) -> None:
        self.metrics = metrics
        self.history = history
        self.enabled = slo_enabled()
        self.fast_n = _knob_int("NOMAD_TPU_SLO_FAST_N", 6, 2)
        self.slow_n = _knob_int("NOMAD_TPU_SLO_SLOW_N", 30, 2)
        self.warn_at = _knob_float("NOMAD_TPU_SLO_WARN", 1.0)
        self.burn_at = _knob_float("NOMAD_TPU_SLO_BURN", 2.0)
        p99_ms = _knob_float("NOMAD_TPU_SLO_P99_MS", 250.0)
        failover_ms = _knob_float(
            "NOMAD_TPU_SLO_FAILOVER_MS", 60000.0
        )
        # The declared objectives.  "budget" is the error budget the
        # burn rate is normalized against: for latency objectives the
        # tolerated fraction of windows over target, for ratio
        # objectives the tolerated bad-event fraction; zero-tolerance
        # objectives have no budget (any violation burns at the cap).
        self.objectives: List[Dict[str, Any]] = [
            {
                "name": "interactive_placement_p99",
                "kind": "latency_p99",
                "sample": "batch_worker.eval_latency_ms",
                "target_ms": p99_ms,
                "budget": 0.05,
                "doc": "windowed eval-latency p99 stays within the "
                       "interactive placement budget",
            },
            {
                "name": "zero_lost_evals",
                "kind": "zero",
                "counter": "broker.delivery_failures",
                "doc": "no eval exhausts delivery and parks in the "
                       "failed queue",
            },
            {
                "name": "shed_rate",
                "kind": "ratio",
                "num": "overload.shed",
                "den": ("overload.shed", "overload.accepted"),
                "budget": 0.05,
                "doc": "overload ladder sheds a bounded fraction of "
                       "ingress writes",
            },
            {
                "name": "storm_fallback_rate",
                "kind": "ratio",
                "num": "storm.fallbacks",
                "den": ("storm.evals",),
                "budget": 0.10,
                "doc": "storm members solved in-wave, not demoted to "
                       "the serial fallback",
            },
            {
                "name": "failover_detect_to_resume",
                "kind": "latency_p99",
                "sample": "device.failover_resume_ms",
                "target_ms": failover_ms,
                "budget": 0.05,
                "doc": "device failover detect-to-resume stays "
                       "within budget",
            },
        ]

    # -- burn-rate math (pure folds over snapshot windows) ------------

    @staticmethod
    def _counter_delta(windows, name: str) -> int:
        if len(windows) < 2:
            return 0
        first = windows[0].get("counters", {}).get(name, 0)
        last = windows[-1].get("counters", {}).get(name, 0)
        return max(0, last - first)

    def _burn(self, obj: Dict[str, Any], windows) -> float:
        """One objective's burn rate over one window range."""
        if len(windows) < 2:
            return 0.0
        kind = obj["kind"]
        if kind == "latency_p99":
            bad = 0
            for w in windows:
                s = w.get("samples", {}).get(obj["sample"])
                if s and s.get("p99", 0.0) > obj["target_ms"]:
                    bad += 1
            return (bad / len(windows)) / obj["budget"]
        if kind == "zero":
            delta = self._counter_delta(windows, obj["counter"])
            return _ZERO_TOLERANCE_BURN if delta > 0 else 0.0
        if kind == "ratio":
            num = self._counter_delta(windows, obj["num"])
            den = sum(
                self._counter_delta(windows, n) for n in obj["den"]
            )
            if den <= 0:
                return 0.0
            return (num / den) / obj["budget"]
        raise ValueError(f"unknown objective kind {kind!r}")

    def _grade(self, burn_fast: float, burn_slow: float) -> str:
        if burn_fast >= self.burn_at and burn_slow >= self.burn_at:
            return "BURNING"
        if burn_fast >= self.warn_at or burn_slow >= self.warn_at:
            return "WARN"
        return "OK"

    # -- the /v1/slo payload ------------------------------------------

    def status(self) -> Dict[str, Any]:
        hist = self.history.to_dict() if self.history else {}
        windows = hist.get("windows", [])
        fast = windows[-self.fast_n:]
        slow = windows[-self.slow_n:]
        out: List[Dict[str, Any]] = []
        worst = "OK"
        for obj in self.objectives:
            if not self.enabled:
                burn_fast = burn_slow = 0.0
                state = "OK"
            else:
                burn_fast = self._burn(obj, fast)
                burn_slow = self._burn(obj, slow)
                state = self._grade(burn_fast, burn_slow)
            if _STATUS_RANK[state] > _STATUS_RANK[worst]:
                worst = state
            entry = {
                "name": obj["name"],
                "kind": obj["kind"],
                "doc": obj["doc"],
                "burn_fast": round(burn_fast, 4),
                "burn_slow": round(burn_slow, 4),
                "status": state,
            }
            if "target_ms" in obj:
                entry["target_ms"] = obj["target_ms"]
            if "budget" in obj:
                entry["budget"] = obj["budget"]
            out.append(entry)
        payload = {
            "enabled": self.enabled,
            "windows": {
                "retained": len(windows),
                "fast_n": self.fast_n,
                "slow_n": self.slow_n,
                "interval_s": hist.get("interval_s", 0),
            },
            "thresholds": {
                "warn": self.warn_at,
                "burning": self.burn_at,
            },
            "objectives": out,
            "worst": worst,
        }
        if self.metrics is not None:
            self.metrics.incr("slo.evaluations")
            self.metrics.set_gauge(
                "slo.worst", _STATUS_RANK[worst]
            )
            self.metrics.set_gauge(
                "slo.burning",
                sum(1 for o in out if o["status"] == "BURNING"),
            )
            self.metrics.set_gauge(
                "slo.warn",
                sum(1 for o in out if o["status"] == "WARN"),
            )
        return payload
