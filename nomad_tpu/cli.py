"""Command-line interface (reference command/commands.go registry).

    nomad-tpu agent -dev [-http-port N]        run a dev server+client
    nomad-tpu job run <file.hcl|file.json>     submit a job
    nomad-tpu job status [job_id]              list jobs / job detail
    nomad-tpu job stop [-purge] <job_id>       stop a job
    nomad-tpu job scale <job_id> <group> <n>   scale a group
    nomad-tpu node status [node_id]            list/inspect nodes
    nomad-tpu node drain -enable|-disable <id> drain a node
    nomad-tpu node eligibility -enable|-disable <id>
    nomad-tpu alloc status <alloc_id>
    nomad-tpu eval status <eval_id>
    nomad-tpu eval explain <eval_id>           placement explanation
    nomad-tpu deployment status [id] | promote <id> | fail <id>
    nomad-tpu operator scheduler get-config|set-config [...]
    nomad-tpu system gc
    nomad-tpu version

Talks to the HTTP API at $NOMAD_ADDR (default http://127.0.0.1:4646).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, Optional


def _addr() -> str:
    return os.environ.get("NOMAD_ADDR", "http://127.0.0.1:4646")


def _request(
    method: str, path: str, body: Optional[Dict] = None
) -> Any:
    url = _addr() + path
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    token = os.environ.get("NOMAD_TOKEN")
    if token:
        req.add_header("X-Nomad-Token", token)
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as exc:
        try:
            detail = json.loads(exc.read()).get("error", "")
        except Exception:  # noqa: BLE001
            detail = ""
        print(f"Error ({exc.code}): {detail or exc.reason}", file=sys.stderr)
        sys.exit(1)
    except urllib.error.URLError as exc:
        print(
            f"Error connecting to {_addr()}: {exc.reason}", file=sys.stderr
        )
        sys.exit(1)


class _TmplItem(dict):
    """Mapping for -t templates: case-tolerant key lookup plus dotted
    access inside ``{...}`` fields, so both ``{id}`` and ``{ID}`` hit
    the same API field regardless of the endpoint's casing."""

    def __missing__(self, key):
        for k in (key.lower(), key.upper()):
            if k in self:
                return self[k]
        lk = key.lower()
        for k, v in self.items():
            if str(k).lower() == lk:
                return v
        raise KeyError(key)

    def __getitem__(self, key):
        v = super().__getitem__(key) if key in self else self.__missing__(key)
        return _wrap_tmpl(v)


def _wrap_tmpl(v):
    """Keep case-tolerance alive through nested containers: dicts wrap
    as _TmplItem and lists wrap their dict elements, so
    ``{TaskGroups[0][name]}`` resolves regardless of casing."""
    if isinstance(v, dict):
        return _TmplItem(v)
    if isinstance(v, list):
        return [_wrap_tmpl(x) for x in v]
    return v


def _render_template(template: str, item) -> str:
    if not isinstance(item, dict):
        return template.format(item)
    return template.format_map(_TmplItem(item))


def _emit(args, data) -> bool:
    """Shared machine-readable output for status/list/inspect commands
    (reference command/job_status.go:22-40 -json/-t flags +
    command/helpers.go Format).  ``-json`` dumps the raw API payload;
    ``-t`` renders a Python format-string per item (lists render one
    line per element; ``{id}``/``{ID}`` are case-tolerant, nested
    fields via ``{resources[cpu]}``).  Returns True when it handled
    the output (the caller skips its human-readable rendering)."""
    if getattr(args, "json", False):
        print(json.dumps(data, indent=2, sort_keys=True, default=str))
        return True
    template = getattr(args, "template", None)
    if template:
        items = data if isinstance(data, list) else [data]
        try:
            for item in items:
                print(_render_template(template, item))
        except (KeyError, IndexError) as exc:
            print(
                f"Error rendering template: missing field {exc}",
                file=sys.stderr,
            )
            sys.exit(1)
        except (ValueError, TypeError, AttributeError) as exc:
            # malformed template (unbalanced braces, bad conversion):
            # a clean one-line error, not a traceback
            print(
                f"Error rendering template: {exc}", file=sys.stderr
            )
            sys.exit(1)
        return True
    return False


def _add_fmt(parser) -> None:
    """Register the -json / -t flags (every status/list/inspect
    command takes both, mirroring reference-wide support)."""
    parser.add_argument("-json", action="store_true", dest="json")
    parser.add_argument("-t", dest="template", default=None)


def _table(rows, headers) -> None:
    widths = [
        max(len(str(h)), *(len(str(r[i])) for r in rows)) if rows else len(h)
        for i, h in enumerate(headers)
    ]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    print(fmt.format(*headers))
    for row in rows:
        print(fmt.format(*[str(c) for c in row]))


# ---------------------------------------------------------------------------
# commands
# ---------------------------------------------------------------------------


def _dev_csi_plugin():
    from .client.csi import FakeCSIPlugin

    return FakeCSIPlugin()


def cmd_agent(args) -> None:
    from .api.http import start_http_server
    from .client import Client
    from .config import AgentConfig, load_config
    from .server import Server

    if getattr(args, "client_mode", False):
        # networked client mode (reference `agent -client
        # -servers=...`): delegate to the netclient entrypoint —
        # registration/heartbeats/alloc sync over HTTP, with the
        # callback endpoint servers proxy fs/exec/logs through
        servers = (
            args.client_mode
            if isinstance(args.client_mode, str)
            else ""
        ) or args.servers
        if not servers:
            raise SystemExit(
                "-client requires -servers=<http addr,...>"
            )
        if (
            args.dev
            or args.config
            or args.server_addr
            or args.http_port is not None
            or args.num_schedulers is not None
        ):
            raise SystemExit(
                "-client does not combine with -dev/-config/"
                "-server-addr/-http-port/-num-schedulers"
            )
        from .client.netclient import main as netclient_main

        argv = ["--servers", servers]
        if args.data_dir:
            argv += ["--data-dir", args.data_dir]
        if args.callback_host:
            argv += ["--callback-host", args.callback_host]
        raise SystemExit(netclient_main(argv))

    if getattr(args, "server_addr", None):
        # networked cluster-server mode: delegate to the netagent
        # entrypoint (framed-TCP raft/gossip/forwarding + HTTP API)
        if args.dev or args.config or args.num_schedulers is not None:
            raise SystemExit(
                "-server-addr does not support -dev/-config/"
                "-num-schedulers yet; configure via netagent flags"
            )
        from .server.netagent import main as netagent_main

        argv = [
            "--addr", args.server_addr,
            "--peers", args.peers or args.server_addr,
            "--http-port", str(args.http_port or 0),
        ]
        if args.join:
            argv += ["--join", args.join]
        raise SystemExit(netagent_main(argv))

    cfg = load_config(args.config) if args.config else AgentConfig()
    if args.dev:
        cfg.client.enabled = True
        if not cfg.data_dir:
            # dev mode needs a real alloc-dir root or the fs/logs
            # surface (alloc logs/fs/exec streaming) has nothing to
            # serve (reference -dev defaults a temp data dir too)
            import tempfile

            cfg.data_dir = tempfile.mkdtemp(prefix="nomad-tpu-dev-")
    if args.num_schedulers is not None:
        cfg.server.num_schedulers = args.num_schedulers
    if args.http_port is not None:
        cfg.http.port = args.http_port

    server = Server(
        num_schedulers=cfg.server.num_schedulers,
        heartbeat_ttl=cfg.server.heartbeat_ttl_s,
        seed=cfg.server.seed,
        acl_enabled=cfg.acl.enabled,
        batch_pipeline=cfg.server.batch_pipeline,
        device_config=cfg.device,
    )
    server.start()
    http = start_http_server(server, host=cfg.http.host, port=cfg.http.port)
    print(f"==> nomad-tpu agent started; HTTP on :{http.port}")
    # lifecycle lines feed /v1/agent/monitor (the logging handler only
    # sees `logging` records, not stdout prints)
    server.log_monitor.write_line(
        f"agent started; HTTP on :{http.port}"
    )
    bridge = None
    if cfg.bridge_port is not None:
        from .server.bridge_service import BridgeService

        bridge = BridgeService(server, port=cfg.bridge_port)
        bridge.start()
        print(f"==> TPU bridge on :{bridge.port}")
    # external Consul/Vault (reference command/agent: consul sync +
    # vault client wiring; opt-in by configured address)
    secrets = None
    if cfg.consul.address:
        from .external import ConsulClient, ConsulSyncer

        syncer = ConsulSyncer(
            server.catalog,
            ConsulClient(cfg.consul.address, cfg.consul.token),
        )
        syncer.attach(server.store)
        syncer.sync()
        print(f"==> consul sync to {cfg.consul.address}")
    if cfg.vault.address:
        from .external import VaultClient, VaultSecretsProvider

        secrets = VaultSecretsProvider(
            VaultClient(cfg.vault.address, cfg.vault.token)
        )
        print(f"==> vault secrets from {cfg.vault.address}")
    clients = []
    if cfg.client.enabled:
        from .structs import Node

        node = Node(datacenter=cfg.datacenter, name=cfg.name)
        client = Client(
            server,
            node=node,
            data_dir=cfg.data_dir,
            drivers=cfg.client.drivers,
            heartbeat_interval=cfg.client.heartbeat_interval_s,
            include_tpu_fingerprint=cfg.client.include_tpu_fingerprint,
            secrets=secrets,
            # dev mode ships an in-process CSI plugin so the volume
            # flow is drivable out of the box (reference -dev ships
            # the mock driver for the same reason)
            csi_plugins=(
                {"csi-dev": _dev_csi_plugin()} if args.dev else None
            ),
        )
        client.start()
        clients.append(client)
        print(f"==> client node {client.node.id[:8]} registered")
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        print("==> shutting down")
    finally:
        for c in clients:
            c.stop()
        if bridge is not None:
            bridge.stop()
        http.stop()
        server.stop()


def cmd_job_run(args) -> None:
    path = args.file
    if path.endswith(".json"):
        with open(path) as f:
            raw = json.load(f)
        job_payload = raw.get("Job") or raw.get("job") or raw
        from .api.codec import job_from_dict, job_to_dict

        job = job_from_dict(job_payload)
    else:
        from . import jobspec
        from .api.codec import job_to_dict

        job = jobspec.parse_file(path)
    from .api.codec import job_to_dict

    resp = _request("POST", "/v1/jobs", {"Job": job_to_dict(job)})
    print(f"==> Evaluation {resp.get('EvalID', '')[:8]} created")


def cmd_job_status(args) -> None:
    if not args.job_id:
        jobs = _request("GET", "/v1/jobs")
        if _emit(args, jobs):
            return
        if not jobs:
            print("No running jobs")
            return
        _table(
            [
                (j["ID"][:20], j["Type"], j["Priority"], j["Status"])
                for j in jobs
            ],
            ["ID", "Type", "Priority", "Status"],
        )
        return
    job = _request("GET", f"/v1/job/{args.job_id}")
    if _emit(args, job):
        return
    print(f"ID            = {job['id']}")
    print(f"Name          = {job['name']}")
    print(f"Type          = {job['type']}")
    print(f"Priority      = {job['priority']}")
    print(f"Status        = {job.get('status', '')}")
    print(f"Datacenters   = {','.join(job['datacenters'])}")
    allocs = _request("GET", f"/v1/job/{args.job_id}/allocations")
    if allocs:
        print("\nAllocations")
        _table(
            [
                (
                    a["id"][:8],
                    a["node_id"][:8],
                    a["task_group"],
                    a["desired_status"],
                    a["client_status"],
                )
                for a in allocs
            ],
            ["ID", "Node ID", "Task Group", "Desired", "Status"],
        )


def cmd_job_plan(args) -> None:
    path = args.file
    if path.endswith(".json"):
        with open(path) as f:
            raw = json.load(f)
        payload = raw.get("Job") or raw.get("job") or raw
        from .api.codec import job_from_dict, job_to_dict

        job = job_from_dict(payload)
    else:
        from . import jobspec
        job = jobspec.parse_file(path)
    from .api.codec import job_to_dict

    resp = _request(
        "POST", f"/v1/job/{job.id}/plan", {"Job": job_to_dict(job)}
    )
    diff = resp.get("Diff") or {}
    print(f"Job: {job.id!r} ({diff.get('Type', 'Added')})")
    for tg, changes in (resp.get("Annotations") or {}).items():
        parts = ", ".join(
            f"{k.lower()} {v}" for k, v in changes.items() if v
        )
        print(f"  group {tg!r}: {parts or 'no changes'}")
    failed = resp.get("FailedTGAllocs") or {}
    for tg, metric in failed.items():
        print(f"  WARNING group {tg!r} would fail placement: {metric}")


def cmd_job_dispatch(args) -> None:
    meta = {}
    for item in args.meta or []:
        key, _, value = item.partition("=")
        meta[key] = value
    resp = _request(
        "POST", f"/v1/job/{args.job_id}/dispatch", {"Meta": meta}
    )
    print(f"==> Dispatched {resp['DispatchedJobID']}")


def _stream_get(path: str):
    """GET a chunked streaming endpoint; yields raw byte frames
    (urllib reads chunked transfer transparently)."""
    url = _addr() + path
    req = urllib.request.Request(url, method="GET")
    token = os.environ.get("NOMAD_TOKEN")
    if token:
        req.add_header("X-Nomad-Token", token)
    resp = urllib.request.urlopen(req, timeout=3600)
    while True:
        data = resp.read1(65536)
        if not data:
            return
        yield data


def cmd_alloc_logs(args) -> None:
    kind = "stderr" if args.stderr else "stdout"
    path = (
        f"/v1/client/fs/logs/{args.alloc_id}?task={args.task}"
        f"&type={kind}"
    )
    if not getattr(args, "follow", False):
        data = _request("GET", path).get("Data", "")
        sys.stdout.write(data)
        return
    # -f: live chunked stream from the server (reference client fs
    # streaming frames)
    try:
        for frame in _stream_get(path + "&follow=true"):
            # raw bytes: a multibyte character straddling a chunk
            # boundary must not be mangled by per-chunk decoding
            sys.stdout.buffer.write(frame)
            sys.stdout.buffer.flush()
    except (KeyboardInterrupt, BrokenPipeError):
        pass
    except urllib.error.HTTPError as exc:
        print(f"Error ({exc.code}): {exc.reason}", file=sys.stderr)
        sys.exit(1)


def cmd_job_history(args) -> None:
    data = _request("GET", f"/v1/job/{args.job_id}/versions")
    if _emit(args, data.get("Versions", [])):
        return
    rows = [
        (
            j["version"],
            "true" if j.get("stable") else "false",
            time.strftime(
                "%Y-%m-%d %H:%M:%S",
                time.localtime(j.get("submit_time", 0)),
            ),
        )
        for j in data.get("Versions", [])
    ]
    _table(rows, ["Version", "Stable", "Submit Date"])


def cmd_job_revert(args) -> None:
    resp = _request(
        "POST",
        f"/v1/job/{args.job_id}/revert",
        {"JobVersion": int(args.version)},
    )
    print(f"==> Evaluation {resp.get('EvalID', '')[:8]} created")


def cmd_job_inspect(args) -> None:
    job = _request("GET", f"/v1/job/{args.job_id}")
    if _emit(args, job):
        return
    print(json.dumps(job, indent=2, sort_keys=True))


def cmd_job_validate(args) -> None:
    if args.file.endswith(".json"):
        with open(args.file) as f:
            raw = json.load(f)
        payload = {"Job": raw.get("Job") or raw.get("job") or raw}
    else:
        with open(args.file) as f:
            parsed = _request(
                "POST", "/v1/jobs/parse", {"JobHCL": f.read()}
            )
        payload = {"Job": parsed}
    resp = _request("POST", "/v1/validate/job", payload)
    errors = resp.get("ValidationErrors") or []
    if errors:
        for e in errors:
            print(f"Error: {e}", file=sys.stderr)
        sys.exit(1)
    print("Job validation successful")


def cmd_alloc_restart(args) -> None:
    _request(
        "POST",
        f"/v1/client/allocation/{args.alloc_id}/restart",
        {"TaskName": args.task or ""},
    )
    print(f"==> Restarted allocation {args.alloc_id[:8]}")


def cmd_alloc_signal(args) -> None:
    _request(
        "POST",
        f"/v1/client/allocation/{args.alloc_id}/signal",
        {"Signal": args.signal, "TaskName": args.task or ""},
    )
    print(f"==> Sent {args.signal} to allocation {args.alloc_id[:8]}")


def cmd_alloc_stop(args) -> None:
    resp = _request(
        "POST", f"/v1/allocation/{args.alloc_id}/stop", {}
    )
    print(f"==> Evaluation {resp.get('EvalID', '')[:8]} created")


def cmd_alloc_exec(args) -> None:
    if getattr(args, "interactive", False):
        sys.exit(_exec_interactive(args))
    resp = _request(
        "POST",
        f"/v1/client/allocation/{args.alloc_id}/exec",
        {
            "Task": args.task or "",
            "Cmd": args.cmd,
        },
    )
    sys.stdout.write(resp.get("Output", ""))
    sys.exit(int(resp.get("ExitCode", 0)))


def _exec_interactive(args) -> int:
    """Live exec session over the websocket transport (reference
    command/alloc_exec.go): stdin streams up, stdout/stderr stream
    down, exit code propagates."""
    import base64
    import threading
    import urllib.parse as _p

    from .api.ws import WebSocketClient

    addr = _p.urlparse(_addr())
    path = (
        f"/v1/client/allocation/{args.alloc_id}/exec"
        f"?task={_p.quote(args.task or '')}"
        f"&command={_p.quote(json.dumps(args.cmd))}"
    )
    headers = {}
    token = os.environ.get("NOMAD_TOKEN")
    if token:
        headers["X-Nomad-Token"] = token
    try:
        ws = WebSocketClient(
            addr.hostname, addr.port or 4646, path, headers
        )
    except (OSError, ConnectionError) as exc:
        print(f"Error connecting: {exc}", file=sys.stderr)
        return 1

    def pump_stdin() -> None:
        try:
            while True:
                data = sys.stdin.buffer.read1(4096)
                if not data:
                    ws.send_text(
                        json.dumps({"stdin": {"close": True}})
                    )
                    return
                ws.send_text(
                    json.dumps(
                        {
                            "stdin": {
                                "data": base64.b64encode(
                                    data
                                ).decode("ascii")
                            }
                        }
                    )
                )
        except (OSError, ValueError):
            pass

    threading.Thread(target=pump_stdin, daemon=True).start()
    code = 1
    try:
        while True:
            got = ws.recv(timeout=3600)
            if got is None:
                break
            _op, payload = got
            try:
                msg = json.loads(payload.decode("utf-8"))
            except ValueError:
                continue
            for stream, out in (
                ("stdout", sys.stdout),
                ("stderr", sys.stderr),
            ):
                frame = msg.get(stream) or {}
                if frame.get("data"):
                    out.buffer.write(
                        base64.b64decode(frame["data"])
                    )
                    out.flush()
            if msg.get("exited"):
                code = int(
                    (msg.get("result") or {}).get("exit_code", 0)
                )
    except KeyboardInterrupt:
        pass
    finally:
        ws.close()
    return code


def cmd_alloc_fs(args) -> None:
    path = args.path or ""
    if args.cat:
        resp = _request(
            "GET",
            f"/v1/client/fs/cat/{args.alloc_id}?path="
            + urllib.parse.quote(path),
        )
        sys.stdout.write(resp.get("Data", ""))
        return
    entries = _request(
        "GET",
        f"/v1/client/fs/ls/{args.alloc_id}?path="
        + urllib.parse.quote(path),
    )
    _table(
        [
            (
                "d" if e["IsDir"] else "-",
                e["Size"],
                e["Name"],
            )
            for e in entries
        ],
        ["Mode", "Size", "Name"],
    )


def cmd_monitor(args) -> None:
    """Follow the agent's logs (reference `nomad monitor`)."""
    if args.follow:
        # chunked live stream (reference agent monitor streaming)
        try:
            buf = b""
            for frame in _stream_get(
                "/v1/agent/monitor?follow=true"
            ):
                buf += frame
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    try:
                        print(json.loads(line)["Line"])
                    except (ValueError, KeyError):
                        pass
        except KeyboardInterrupt:
            pass
        except urllib.error.HTTPError as exc:
            print(
                f"Error ({exc.code}): {exc.reason}", file=sys.stderr
            )
            sys.exit(1)
        return
    index = -1
    try:
        while True:
            resp = _request(
                "GET", f"/v1/agent/monitor?index={index}&wait=2"
            )
            for line in resp.get("Lines", []):
                print(line)
            index = resp.get("Index", index)
            if not args.follow:
                break
    except KeyboardInterrupt:
        pass


def cmd_operator_autopilot(args) -> None:
    if args.action == "get-config":
        cfg = _request("GET", "/v1/operator/autopilot/configuration")
        if _emit(args, cfg):
            return
        for k, v in cfg.items():
            print(f"{k} = {v}")
    elif args.action == "set-config":
        body = {}
        if args.cleanup_dead_servers is not None:
            body["CleanupDeadServers"] = (
                args.cleanup_dead_servers == "true"
            )
        _request(
            "POST", "/v1/operator/autopilot/configuration", body
        )
        print("Configuration updated!")
    elif args.action == "health":
        h = _request("GET", "/v1/operator/autopilot/health")
        if _emit(args, h):
            return
        print(
            f"Healthy = {h['Healthy']}  Servers = {h['NumServers']}  "
            f"FailureTolerance = {h['FailureTolerance']}"
        )
        _table(
            [
                (s["Name"], s["Address"],
                 "alive" if s["Healthy"] else "failed",
                 s["Voter"])
                for s in h.get("Servers", [])
            ],
            ["Name", "Address", "Health", "Voter"],
        )


def cmd_operator_debug(args) -> None:
    """Collect a diagnostic bundle (reference `nomad operator debug`:
    pprof profiles, agent info, metrics, recent logs into an archive)."""
    import tarfile
    import tempfile

    captures = {
        "agent-self.json": ("GET", "/v1/agent/self"),
        "members.json": ("GET", "/v1/agent/members"),
        "metrics.json": ("GET", "/v1/metrics"),
        # accelerator supervisor: state machine + failover/canary
        # history, so a bundle from a degraded server shows WHEN the
        # device was lost and what tripped it
        "device.json": ("GET", "/v1/device"),
        # eval flight recorder: recent full traces, so a bundle from a
        # misbehaving server carries per-eval stage/conflict evidence
        "traces.json": ("GET", "/v1/traces?full=1&limit=256"),
        # metric time-series history: the last N snapshot windows, so
        # the bundle shows "p99 over the last ten minutes", not just
        # the instant the operator finally ran the capture
        "metrics-history.json": ("GET", "/v1/metrics/history"),
        # cluster-scope views (leader fan-in over every peer; on a
        # single-process server these answer with the local share):
        # stitched cross-server traces and every server's metrics,
        # with unreachable peers marked rather than omitted silently
        "cluster-traces.json": (
            "GET", "/v1/cluster/traces?full=1&limit=256"
        ),
        "cluster-metrics.json": ("GET", "/v1/cluster/metrics"),
        "cluster-metrics-history.json": (
            "GET", "/v1/cluster/metrics/history"
        ),
        # placement explainability: recent per-eval score
        # decompositions + filter attributions, cross-referenced with
        # traces.json by eval id
        "placements.json": ("GET", "/v1/placements?limit=256"),
        # control-loop flight data: SLO burn-rate status plus the
        # adaptive-decision ledger, cross-referenced with traces.json
        # by trace id — a bundle from a misbehaving server says WHAT
        # objective is burning and WHY each control loop chose what
        # it chose
        "slo.json": ("GET", "/v1/slo"),
        "decisions.json": ("GET", "/v1/decisions?limit=256"),
        "cluster-slo.json": ("GET", "/v1/cluster/slo"),
        "cluster-decisions.json": (
            "GET", "/v1/cluster/decisions?limit=256"
        ),
        "monitor.json": ("GET", "/v1/agent/monitor"),
        "pprof-goroutine.json": ("GET", "/v1/agent/pprof/goroutine"),
        "pprof-heap.json": ("GET", "/v1/agent/pprof/heap"),
        "jobs.json": ("GET", "/v1/jobs"),
        "nodes.json": ("GET", "/v1/nodes"),
        "scheduler-config.json": (
            "GET", "/v1/operator/scheduler/configuration"
        ),
    }
    out_path = args.output or "nomad-debug.tar.gz"
    with tempfile.TemporaryDirectory() as td:
        names = []
        for name, (method, path) in captures.items():
            try:
                data = _request(method, path)
            except SystemExit:
                # endpoint unavailable (e.g. cluster-only): skip
                continue
            p = os.path.join(td, name)
            with open(p, "w") as f:
                json.dump(data, f, indent=2)
            names.append((p, name))
        with tarfile.open(out_path, "w:gz") as tar:
            for p, name in names:
                tar.add(p, arcname=f"nomad-debug/{name}")
    print(f"==> Wrote debug bundle to {out_path} "
          f"({len(names)} captures)")


def cmd_device_status(args) -> None:
    """Accelerator supervisor status (GET /v1/device)."""
    st = _request("GET", "/v1/device")
    if _emit(args, st):
        return
    if not st.get("enabled"):
        print("Device supervision idle (no accelerator expected)")
        return
    lat = st.get("probe_latency_ms", {})
    _table(
        [
            (
                st.get("state", "?"),
                st.get("backend", "?"),
                st.get("failover_count", 0),
                st.get("recovered_count", 0),
                st.get("watchdog_trips", 0),
                f"{st.get('canary_ok', 0)}/{st.get('canary_fail', 0)}",
                f"{lat.get('p50', 0)}/{lat.get('p99', 0)}",
            )
        ],
        [
            "State", "Backend", "Failovers", "Recovered",
            "WatchdogTrips", "Canary ok/fail", "Probe p50/p99 ms",
        ],
    )
    if st.get("last_error"):
        print(f"Last error: {st['last_error']}")
    history = st.get("history", [])
    if history:
        print("Recent transitions:")
        for h in history[-8:]:
            print(
                f"  {h.get('from')} -> {h.get('to')}: "
                f"{h.get('reason')}"
            )


def cmd_slo_status(args) -> None:
    """SLO burn-rate status (GET /v1/slo)."""
    st = _request("GET", "/v1/slo")
    if _emit(args, st):
        return
    if not st.get("enabled"):
        print("SLO engine disabled (NOMAD_TPU_SLO=0)")
        return
    win = st.get("windows", {})
    print(
        f"Worst: {st.get('worst', 'OK')}  "
        f"(windows fast={win.get('fast_n')} slow={win.get('slow_n')} "
        f"x {win.get('interval_s')}s, retained={win.get('retained')})"
    )
    _table(
        [
            (
                o.get("name", "?"),
                o.get("status", "?"),
                o.get("burn_fast", 0),
                o.get("burn_slow", 0),
                o.get("target_ms", "-"),
                o.get("budget", "-"),
            )
            for o in st.get("objectives", [])
        ],
        [
            "Objective", "Status", "BurnFast", "BurnSlow",
            "Target ms", "Budget",
        ],
    )


def cmd_decisions(args) -> None:
    """Adaptive-decision ledger (GET /v1/decisions)."""
    qs = []
    for key in ("site", "outcome", "trace"):
        val = getattr(args, key, None)
        if val:
            qs.append(f"{key}={urllib.parse.quote(val)}")
    qs.append(f"limit={getattr(args, 'limit', None) or 32}")
    st = _request("GET", "/v1/decisions?" + "&".join(qs))
    if _emit(args, st):
        return
    if not st.get("enabled"):
        print("Decision ledger disabled (NOMAD_TPU_DECISIONS=0)")
        return
    ring = st.get("ring", {})
    print(
        f"Ring: {ring.get('depth', 0)}/{ring.get('cap', 0)} "
        f"(evicted {ring.get('evicted', 0)})"
    )
    rows = []
    for rec in st.get("decisions", []):
        inputs = rec.get("inputs", {})
        brief = " ".join(
            f"{k}={inputs[k]}" for k in sorted(inputs)[:3]
        )
        rows.append(
            (
                rec.get("seq", 0),
                rec.get("site", "?"),
                rec.get("action", "?"),
                rec.get("outcome", "?"),
                rec.get("trace_id") or "-",
                brief,
            )
        )
    _table(
        rows,
        ["Seq", "Site", "Action", "Outcome", "Trace", "Inputs"],
    )


def cmd_operator_raft(args) -> None:
    if getattr(args, "action", "list-peers") == "remove-peer":
        _request(
            "DELETE",
            "/v1/operator/raft/peer?address="
            + urllib.parse.quote(args.address or ""),
        )
        print(f"==> Removed raft peer {args.address}")
        return
    cfg = _request("GET", "/v1/operator/raft/configuration")
    if _emit(args, cfg.get("Servers", [])):
        return
    _table(
        [
            (s["ID"], s["Address"], s["Leader"], s["Voter"])
            for s in cfg.get("Servers", [])
        ],
        ["ID", "Address", "Leader", "Voter"],
    )


def cmd_job_allocs(args) -> None:
    """(reference command/job_allocs.go)"""
    allocs = _request("GET", f"/v1/job/{args.job_id}/allocations")
    if _emit(args, allocs):
        return
    _table(
        [
            (
                (a.get("ID") or a.get("id", ""))[:8],
                (a.get("NodeID") or a.get("node_id", ""))[:8],
                a.get("TaskGroup") or a.get("task_group", ""),
                a.get("DesiredStatus")
                or a.get("desired_status", ""),
                a.get("ClientStatus")
                or a.get("client_status", ""),
            )
            for a in allocs
        ],
        ["ID", "Node ID", "Task Group", "Desired", "Status"],
    )


def cmd_volume_detach(args) -> None:
    """(reference command/volume_detach.go)"""
    resp = _request(
        "PUT",
        f"/v1/volume/csi/{args.volume_id}/detach?node="
        + urllib.parse.quote(args.node_id),
        {},
    )
    print(
        f"==> Detached {resp.get('DetachedClaims', 0)} claim(s) "
        f"from {args.node_id[:8]}"
    )


def cmd_server_force_leave(args) -> None:
    """(reference command/server_force_leave.go)"""
    _request(
        "PUT",
        "/v1/agent/force-leave?node="
        + urllib.parse.quote(args.name),
        {},
    )
    print(f"==> Force-left {args.name}")


def cmd_license(args) -> None:
    """(reference command/license_get.go / license_put.go; OSS gates
    the feature to Enterprise — surfacing the server's error is the
    parity behavior)"""
    if args.license_cmd == "get":
        _request("GET", "/v1/operator/license")
    else:
        _request("PUT", "/v1/operator/license", {"License": ""})


def cmd_enterprise_gate(args) -> None:
    """sentinel/quota command families (reference registers them in
    OSS builds; the feature itself is Enterprise-gated server-side)"""
    family = args.family
    _request("GET", f"/v1/{family}s" if family == "quota" else
             "/v1/sentinel/policies")


def cmd_keyring(args) -> None:
    """(reference command/operator_keyring.go: -install/-use/-remove/
    -list against the serf keyring)"""
    if args.install:
        resp = _request(
            "PUT", "/v1/operator/keyring",
            {"Operation": "install", "Key": args.install},
        )
    elif args.use:
        resp = _request(
            "PUT", "/v1/operator/keyring",
            {"Operation": "use", "Key": args.use},
        )
    elif args.remove:
        resp = _request(
            "PUT", "/v1/operator/keyring",
            {"Operation": "remove", "Key": args.remove},
        )
    else:
        resp = _request("GET", "/v1/operator/keyring")
    keys = resp.get("Keys", {})
    primary = set(resp.get("PrimaryKeys", {}))
    for key in keys:
        marker = " (primary)" if key in primary else ""
        print(f"{key}{marker}")


def cmd_check(args) -> None:
    """Agent health probe (reference command/check.go: exit 0 when
    the agent answers)"""
    _request("GET", "/v1/agent/self")
    print("ok")


def cmd_ui(args) -> None:
    """(reference command/ui.go: print/open the web UI URL)"""
    url = _addr() + "/ui/"
    print(url)
    if getattr(args, "open", False):
        import webbrowser

        webbrowser.open(url)


def cmd_job_stop(args) -> None:
    purge = "?purge=true" if args.purge else ""
    resp = _request("DELETE", f"/v1/job/{args.job_id}{purge}")
    print(f"==> Evaluation {resp.get('EvalID', '')[:8]} created")


def cmd_job_scale(args) -> None:
    resp = _request(
        "POST",
        f"/v1/job/{args.job_id}/scale",
        {"Target": {"Group": args.group}, "Count": args.count},
    )
    print(f"==> Evaluation {resp.get('EvalID', '')[:8]} created")


def cmd_volume_register(args) -> None:
    """(reference command/volume_register.go; accepts a JSON volume
    spec file)"""
    with open(args.file) as fh:
        spec = json.load(fh)
    vol_id = spec.get("ID") or spec.get("id")
    if not vol_id:
        print("error: volume spec requires an ID", file=sys.stderr)
        raise SystemExit(1)
    resp = _request("POST", f"/v1/volume/csi/{vol_id}", spec)
    print(f"==> Volume {vol_id} registered")


def cmd_volume_status(args) -> None:
    """(reference command/volume_status.go)"""
    if getattr(args, "volume_id", None):
        v = _request("GET", f"/v1/volume/csi/{args.volume_id}")
        if _emit(args, v):
            return
        print(json.dumps(v, indent=2))
        return
    vols = _request("GET", "/v1/volumes")
    if _emit(args, vols):
        return
    _table(
        [
            (
                v["ID"],
                v["Name"],
                v["PluginID"],
                v["Schedulable"],
                v["AccessMode"],
                f"{v['CurrentReaders']}r/{v['CurrentWriters']}w",
            )
            for v in vols
        ],
        ("ID", "Name", "Plugin", "Schedulable", "Access", "Claims"),
    )


def cmd_volume_deregister(args) -> None:
    """(reference command/volume_deregister.go)"""
    force = "?force=true" if args.force else ""
    _request("DELETE", f"/v1/volume/csi/{args.volume_id}{force}")
    print(f"==> Volume {args.volume_id} deregistered")


def cmd_plugin_status(args) -> None:
    """(reference command/plugin_status.go)"""
    plugins = _request("GET", "/v1/plugins")
    if _emit(args, plugins):
        return
    _table(
        [
            (p["ID"], f"{p['NodesHealthy']}/{p['NodesExpected']}")
            for p in plugins
        ],
        ("ID", "Nodes Healthy"),
    )


def cmd_scaling_policies(args) -> None:
    """(reference command/scaling_policy_list.go)"""
    path = "/v1/scaling/policies"
    if getattr(args, "job_id", None):
        path += f"?job={args.job_id}"
    pols = _request("GET", path)
    if _emit(args, pols):
        return
    _table(
        [
            (
                p["ID"][:8],
                p["Enabled"],
                p["Type"],
                p["Target"].get("Job", ""),
                p["Target"].get("Group", ""),
            )
            for p in pols
        ],
        ("ID", "Enabled", "Type", "Job", "Group"),
    )


def cmd_scaling_policy_info(args) -> None:
    """(reference command/scaling_policy_info.go)"""
    p = _request("GET", f"/v1/scaling/policy/{args.policy_id}")
    if _emit(args, p):
        return
    print(json.dumps(p, indent=2))


def cmd_server_members(args) -> None:
    """(reference command/server_members.go)"""
    info = _request("GET", "/v1/agent/members")
    if _emit(args, info["Members"]):
        return
    _table(
        [
            (
                m["Name"],
                m["Region"],
                m["Role"],
                m["Status"],
                m["Incarnation"],
            )
            for m in info["Members"]
        ],
        ["Name", "Region", "Role", "Status", "Incarnation"],
    )


def cmd_node_status(args) -> None:
    if not args.node_id:
        nodes = _request("GET", "/v1/nodes")
        if _emit(args, nodes):
            return
        _table(
            [
                (
                    n["ID"][:8],
                    n["Name"],
                    n["Datacenter"],
                    n["SchedulingEligibility"],
                    n["Status"],
                )
                for n in nodes
            ],
            ["ID", "Name", "DC", "Eligibility", "Status"],
        )
        return
    node = _request("GET", f"/v1/node/{args.node_id}")
    if _emit(args, node):
        return
    print(f"ID          = {node['id']}")
    print(f"Name        = {node['name']}")
    print(f"Datacenter  = {node['datacenter']}")
    print(f"Status      = {node['status']}")
    print(f"Eligibility = {node['scheduling_eligibility']}")
    print(f"Drain       = {node['drain']}")
    res = node["node_resources"]
    print(
        f"Resources   = cpu {res['cpu']} MHz, mem {res['memory_mb']} MiB,"
        f" disk {res['disk_mb']} MiB"
    )
    allocs = _request("GET", f"/v1/node/{args.node_id}/allocations")
    if allocs:
        print("\nAllocations")
        _table(
            [
                (a["id"][:8], a["job_id"][:20], a["client_status"])
                for a in allocs
            ],
            ["ID", "Job", "Status"],
        )


def cmd_node_drain(args) -> None:
    body = {}
    if args.enable:
        body = {"DrainSpec": {"Deadline": int(args.deadline * 1e9)}}
    _request("POST", f"/v1/node/{args.node_id}/drain", body)
    print(
        f"==> Node {args.node_id[:8]} drain "
        f"{'enabled' if args.enable else 'disabled'}"
    )
    if not (args.enable and getattr(args, "monitor", False)):
        return
    # -monitor: follow until every alloc has migrated off the node
    # (reference command/node_drain.go monitorDrain)
    seen = set()
    while True:
        allocs = _request(
            "GET", f"/v1/node/{args.node_id}/allocations"
        )
        live = [
            a
            for a in allocs
            if a.get("desired_status") == "run"
            and a.get("client_status") in ("pending", "running")
        ]
        for a in allocs:
            key = (a["id"], a.get("desired_status"))
            if key not in seen and a.get("desired_status") != "run":
                seen.add(key)
                print(
                    f"    alloc {a['id'][:8]} ({a.get('job_id')}) "
                    f"-> {a.get('desired_status')}"
                )
        node = _request("GET", f"/v1/node/{args.node_id}")
        if not live and not node.get("Drain", False):
            print("==> Drain complete")
            return
        time.sleep(1.0)


def cmd_node_eligibility(args) -> None:
    elig = "eligible" if args.enable else "ineligible"
    _request(
        "POST",
        f"/v1/node/{args.node_id}/eligibility",
        {"Eligibility": elig},
    )
    print(f"==> Node {args.node_id[:8]} marked {elig}")


def cmd_alloc_status(args) -> None:
    alloc = _request("GET", f"/v1/allocation/{args.alloc_id}")
    if _emit(args, alloc):
        return
    print(f"ID           = {alloc['id']}")
    print(f"Name         = {alloc['name']}")
    print(f"Node ID      = {alloc['node_id']}")
    print(f"Job ID       = {alloc['job_id']}")
    print(f"Desired      = {alloc['desired_status']}")
    print(f"Status       = {alloc['client_status']}")
    for task, state in (alloc.get("task_states") or {}).items():
        print(f"\nTask {task!r}: {state['state']}"
              f"{' (failed)' if state.get('failed') else ''}")


def cmd_eval_status(args) -> None:
    ev = _request("GET", f"/v1/evaluation/{args.eval_id}")
    if _emit(args, ev):
        return
    print(f"ID           = {ev['id']}")
    print(f"Type         = {ev['type']}")
    print(f"TriggeredBy  = {ev['triggered_by']}")
    print(f"Job ID       = {ev['job_id']}")
    print(f"Status       = {ev['status']}")
    if ev.get("blocked_eval"):
        print(f"BlockedEval  = {ev['blocked_eval']}")


def cmd_eval_explain(args) -> None:
    """Render an eval's placement explanation
    (GET /v1/evaluation/<id>/placement): winner, runners-up with
    per-component score terms, and the top filter reasons."""
    rec = _request(
        "GET", f"/v1/evaluation/{args.eval_id}/placement"
    )
    if _emit(args, rec):
        return
    print(f"Eval         = {rec['EvalID']}")
    print(f"Job ID       = {rec['JobID']}")
    print(f"Type         = {rec['Type']} ({rec['TriggeredBy']})")
    if rec.get("served_by"):
        # follower-planned eval: the record came back through the
        # cluster fan-in from the server that ran the scheduler
        print(f"Served by    = {rec['served_by']}")
    if rec.get("TraceID"):
        print(f"Trace        = /v1/traces/{rec['EvalID']}")
    storm = rec.get("Storm")
    if storm:
        # placements came from the global storm solve, not the
        # per-eval greedy walk: show the auction round, the aggregate
        # assignment score and how many rows diverged from the walk
        print(
            f"Storm        = solved round {storm.get('Round')}, "
            f"score {storm.get('AssignmentScore')}, "
            f"{storm.get('DivergentRows', 0)}/{storm.get('Rows', 0)}"
            " rows diverged from the greedy walk"
        )
    for tg, g in (rec.get("TaskGroups") or {}).items():
        metric = g.get("Metric") or {}
        status = "FAILED" if g.get("Failed") else "placed"
        print(
            f"\nTask group {tg!r}: {g.get('Placed', 0)} {status}, "
            f"{metric.get('NodesEvaluated', 0)} evaluated / "
            f"{metric.get('NodesFiltered', 0)} filtered / "
            f"{metric.get('NodesExhausted', 0)} exhausted"
            + (
                f" ({metric.get('CoalescedFailures')} coalesced)"
                if metric.get("CoalescedFailures")
                else ""
            )
        )
        avail = metric.get("NodesAvailable") or {}
        if avail:
            print(
                "Available    = "
                + ", ".join(
                    f"{dc}:{n}" for dc, n in sorted(avail.items())
                )
            )
        if metric.get("AllocationTime"):
            print(
                f"AllocTime    = "
                f"{metric['AllocationTime'] * 1000.0:.2f} ms"
            )
        winner = g.get("Winner", "")
        meta = sorted(
            metric.get("ScoreMetaData") or [],
            key=lambda m: -m.get("NormScore", 0.0),
        )
        if meta:
            rows = []
            for m in meta:
                scores = m.get("Scores") or {}
                terms = ", ".join(
                    f"{k}={v:.4f}"
                    for k, v in sorted(scores.items())
                    if k != "normalized-score"
                )
                rows.append(
                    (
                        ("*" if m["NodeID"] == winner else " ")
                        + m["NodeID"][:8],
                        f"{m.get('NormScore', 0.0):.4f}",
                        terms,
                    )
                )
            _table(rows, ["Node", "NormScore", "Score terms"])
        reasons = sorted(
            (metric.get("ConstraintFiltered") or {}).items(),
            key=lambda kv: -kv[1],
        )
        exhausted = sorted(
            (metric.get("DimensionExhausted") or {}).items(),
            key=lambda kv: -kv[1],
        )
        if reasons or exhausted:
            _table(
                [
                    (reason, n, "filtered")
                    for reason, n in reasons[:8]
                ]
                + [
                    (dim, n, "exhausted")
                    for dim, n in exhausted[:8]
                ],
                ["Reason", "Nodes", "Kind"],
            )


def cmd_deployment(args) -> None:
    if args.action == "status":
        if args.id:
            d = _request("GET", f"/v1/deployment/{args.id}")
            if _emit(args, d):
                return
            print(json.dumps(d, indent=2))
        else:
            ds = _request("GET", "/v1/deployments")
            if _emit(args, ds):
                return
            _table(
                [
                    (d["id"][:8], d["job_id"][:20], d["status"])
                    for d in ds
                ],
                ["ID", "Job", "Status"],
            )
    elif args.action == "list":
        ds = _request("GET", "/v1/deployments")
        if _emit(args, ds):
            return
        _table(
            [(d["id"][:8], d["job_id"][:20], d["status"]) for d in ds],
            ["ID", "Job", "Status"],
        )
    elif args.action == "promote":
        _request("POST", f"/v1/deployment/promote/{args.id}", {})
        print("==> Deployment promoted")
    elif args.action == "fail":
        _request("POST", f"/v1/deployment/fail/{args.id}", {})
        print("==> Deployment failed")
    elif args.action == "pause":
        _request(
            "POST", f"/v1/deployment/pause/{args.id}", {"Pause": True}
        )
        print("==> Deployment paused")
    elif args.action == "resume":
        _request(
            "POST", f"/v1/deployment/pause/{args.id}", {"Pause": False}
        )
        print("==> Deployment resumed")
    elif args.action == "unblock":
        # multiregion deployment coordination is the enterprise no-op
        # in the reference OSS tree (deploymentwatcher/
        # multiregion_oss.go); the command exists for surface parity
        print(
            "Error: deployment unblock applies to multiregion "
            "deployments, which follow the OSS no-op coordination "
            "(deployments never enter the blocked state)",
            file=sys.stderr,
        )
        sys.exit(1)


def cmd_operator_snapshot(args) -> None:
    if args.action == "save":
        resp = _request(
            "POST", "/v1/operator/snapshot/save", {"Path": args.path}
        )
        print(f"==> Snapshot saved to {resp['Saved']}")
    elif args.action == "inspect":
        # local file inspection, no API round trip (reference
        # command/operator_snapshot_inspect.go)
        import gzip
        import pickle

        with open(args.path, "rb") as f:
            raw = f.read()
        try:
            payload = pickle.loads(gzip.decompress(raw))
        except OSError:
            payload = pickle.loads(raw)
        print(f"Version       = {payload.get('version')}")
        print(f"Index         = {payload.get('index')}")
        for table in (
            "nodes", "jobs", "allocs", "evals", "deployments",
            "csi_volumes", "scaling_policies", "namespaces",
            "acl_policies", "acl_tokens",
        ):
            if table in payload:
                print(f"{table:<14}= {len(payload[table])}")
    else:
        resp = _request(
            "POST", "/v1/operator/snapshot/restore", {"Path": args.path}
        )
        print(f"==> Snapshot restored (index {resp['Index']})")


def cmd_namespace(args) -> None:
    if args.ns_cmd == "list":
        nss = _request("GET", "/v1/namespaces")
        if _emit(args, nss):
            return
        _table(
            [(n["Name"], n["Description"]) for n in nss],
            ["Name", "Description"],
        )
    elif args.ns_cmd in ("status", "inspect"):
        n = _request("GET", f"/v1/namespace/{args.name}")
        if _emit(args, n):
            return
        if args.ns_cmd == "inspect":
            print(json.dumps(n, indent=2))
        else:
            print(f"Name        = {n['Name']}")
            print(f"Description = {n['Description']}")
    elif args.ns_cmd == "apply":
        _request(
            "POST",
            "/v1/namespaces",
            {"Name": args.name, "Description": args.description or ""},
        )
        print(f'==> Namespace "{args.name}" applied')
    elif args.ns_cmd == "delete":
        _request("DELETE", f"/v1/namespace/{args.name}")
        print(f'==> Namespace "{args.name}" deleted')


def cmd_acl(args) -> None:
    if args.acl_cmd == "bootstrap":
        resp = _request("POST", "/v1/acl/bootstrap", {})
        print(f"Accessor ID = {resp['AccessorID']}")
        print(f"Secret ID   = {resp['SecretID']}")
        print(f"Type        = {resp.get('Type', 'management')}")
        return
    if args.acl_cmd == "policy":
        if args.action == "list":
            ps = _request("GET", "/v1/acl/policies")
            if _emit(args, ps):
                return
            _table([(p["Name"],) for p in ps], ["Name"])
        elif args.action == "info":
            p = _request("GET", f"/v1/acl/policy/{args.name}")
            if _emit(args, p):
                return
            print(json.dumps(p, indent=2))
        elif args.action == "apply":
            with open(args.file) as f:
                rules = json.load(f)
            _request("POST", f"/v1/acl/policy/{args.name}", rules)
            print(f'==> Policy "{args.name}" applied')
        elif args.action == "delete":
            _request("DELETE", f"/v1/acl/policy/{args.name}")
            print(f'==> Policy "{args.name}" deleted')
        return
    # token family
    if args.action == "list":
        ts = _request("GET", "/v1/acl/tokens")
        if _emit(args, ts):
            return
        _table(
            [
                (
                    t["AccessorID"][:8],
                    t["Name"],
                    t["Type"],
                    ",".join(t.get("Policies") or []),
                )
                for t in ts
            ],
            ["Accessor", "Name", "Type", "Policies"],
        )
    elif args.action == "create":
        resp = _request(
            "POST",
            "/v1/acl/tokens",
            {
                "Name": args.name or "",
                "Type": args.type,
                "Policies": args.policy or [],
            },
        )
        print(f"Accessor ID = {resp['AccessorID']}")
        print(f"Secret ID   = {resp['SecretID']}")
    elif args.action == "info":
        t = _request("GET", f"/v1/acl/token/{args.accessor}")
        if _emit(args, t):
            return
        print(json.dumps(t, indent=2))
    elif args.action == "self":
        t = _request("GET", "/v1/acl/token/self")
        if _emit(args, t):
            return
        print(json.dumps(t, indent=2))
    elif args.action == "update":
        body = {}
        if args.name:
            body["Name"] = args.name
        if args.policy:
            body["Policies"] = args.policy
        _request("POST", f"/v1/acl/token/{args.accessor}", body)
        print(f"==> Token {args.accessor[:8]} updated")
    elif args.action == "delete":
        _request("DELETE", f"/v1/acl/token/{args.accessor}")
        print(f"==> Token {args.accessor[:8]} deleted")


def cmd_job_deployments(args) -> None:
    ds = _request("GET", f"/v1/job/{args.job_id}/deployments")
    if _emit(args, ds):
        return
    _table(
        [
            (d["id"][:8], d.get("job_version", 0), d["status"])
            for d in ds
        ],
        ["ID", "Job Version", "Status"],
    )


def cmd_job_eval(args) -> None:
    resp = _request("POST", f"/v1/job/{args.job_id}/evaluate", {})
    print(f"==> Created eval {resp['EvalID']}")


def cmd_job_promote(args) -> None:
    ds = _request("GET", f"/v1/job/{args.job_id}/deployments")
    live = [d for d in ds if d["status"] == "running"]
    if not live:
        print("No running deployment to promote", file=sys.stderr)
        sys.exit(1)
    _request("POST", f"/v1/deployment/promote/{live[0]['id']}", {})
    print(f"==> Promoted deployment {live[0]['id'][:8]}")


def cmd_job_periodic(args) -> None:
    resp = _request(
        "POST", f"/v1/job/{args.job_id}/periodic/force", {}
    )
    print(f"==> Forced launch: {resp['JobID']}")


EXAMPLE_JOB_HCL = '''job "example" {
  datacenters = ["dc1"]
  type        = "service"

  group "cache" {
    count = 1

    task "redis" {
      driver = "exec"

      config {
        command = "/usr/bin/redis-server"
        args    = ["--port", "6379"]
      }

      resources {
        cpu    = 500
        memory = 256
      }
    }
  }
}
'''


def cmd_job_init(args) -> None:
    path = args.filename or "example.nomad"
    if os.path.exists(path):
        print(f"File {path!r} already exists", file=sys.stderr)
        sys.exit(1)
    with open(path, "w") as f:
        f.write(EXAMPLE_JOB_HCL)
    print(f"==> Example job file written to {path}")


def cmd_server_join(args) -> None:
    resp = _request(
        "POST", "/v1/agent/join", {"address": args.address}
    )
    print(f"==> Joined {resp.get('num_joined', 0)} server(s)")


def cmd_node_config(args) -> None:
    n = _request("GET", f"/v1/node/{args.node_id}")
    if _emit(args, n):
        return
    print(json.dumps(n, indent=2))


def cmd_operator_keygen(args) -> None:
    # 32 random bytes, base64 (reference command/operator_keygen.go);
    # usable as cluster key material (e.g. seeding TLS cert passphrases
    # or gossip keys in external tooling)
    import base64
    import secrets

    print(base64.b64encode(secrets.token_bytes(32)).decode())


def cmd_status(args) -> None:
    """Generic status: dispatch an identifier to the right family by
    prefix search (reference command/status.go resolves jobs, allocs,
    nodes, evals, deployments through the search endpoint)."""
    if not args.job_id:
        return cmd_job_status(args)
    ident = args.job_id
    matches = _request(
        "GET", f"/v1/search?prefix={urllib.parse.quote(ident)}&context=all"
    ).get("Matches", {})
    for context, handler in (
        ("jobs", cmd_job_status),
        ("allocs", None),
        ("nodes", None),
        ("evals", None),
        ("deployments", None),
    ):
        hits = matches.get(context) or []
        if ident in hits or (len(hits) == 1 and hits[0].startswith(ident)):
            full = ident if ident in hits else hits[0]
            if context == "jobs":
                args.job_id = full
                return cmd_job_status(args)
            if context == "allocs":
                args.alloc_id = full
                return cmd_alloc_status(args)
            if context == "nodes":
                args.node_id = full
                return cmd_node_status(args)
            if context == "evals":
                args.eval_id = full
                return cmd_eval_status(args)
            if context == "deployments":
                args.action, args.id = "status", full
                return cmd_deployment(args)
    # fall through: treat as a job id (matches reference behavior of
    # erroring with the most likely family)
    return cmd_job_status(args)


def cmd_system(args) -> None:
    if args.action == "gc":
        _request("POST", "/v1/system/gc", {})
        print("==> GC triggered")
    elif args.action == "reconcile":
        _request("POST", "/v1/system/reconcile/summaries", {})
        print("==> Job summaries reconciled")


def cmd_operator_scheduler(args) -> None:
    if args.action == "get-config":
        cfg = _request("GET", "/v1/operator/scheduler/configuration")
        if _emit(args, cfg):
            return
        print(json.dumps(cfg, indent=2))
    else:
        cfg = _request("GET", "/v1/operator/scheduler/configuration")
        if args.algorithm:
            cfg["SchedulerAlgorithm"] = args.algorithm
        if args.tpu is not None:
            cfg["TPUSchedulerEnabled"] = args.tpu == "true"
        _request("POST", "/v1/operator/scheduler/configuration", cfg)
        print("==> Scheduler configuration updated")


def cmd_system_gc(args) -> None:
    _request("POST", "/v1/system/gc", {})
    print("==> GC triggered")


def cmd_agent_info(args) -> None:
    info = _request("GET", "/v1/agent/self")
    if _emit(args, info):
        return
    print(json.dumps(info, indent=2))


def cmd_version(args) -> None:
    from . import __version__

    print(f"nomad-tpu v{__version__}")


# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="nomad-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    agent = sub.add_parser("agent")
    agent.add_argument("-dev", action="store_true", dest="dev")
    agent.add_argument(
        "-server-addr", default=None, dest="server_addr",
        help="host:port RPC bind — runs a TCP cluster server "
        "(multi-process control plane; see nomad_tpu.server.netagent)",
    )
    agent.add_argument(
        "-peers", default=None, dest="peers",
        help="comma-separated raft peer addresses incl. self",
    )
    agent.add_argument(
        "-join", default=None, dest="join",
        help="gossip seed address of a live server",
    )
    agent.add_argument(
        "-client", nargs="?", const=True, default=False,
        dest="client_mode", metavar="SERVERS",
        help="run a standalone CLIENT agent; server addresses come "
        "from -servers (reference agent -client -servers=...) or "
        "inline as -client=ADDR[,ADDR]",
    )
    agent.add_argument(
        "-servers", default="", dest="servers",
        help="comma-separated server HTTP addresses for -client",
    )
    agent.add_argument(
        "-callback-host", default="", dest="callback_host",
        help="address the SERVERS can reach this client on for "
        "fs/exec/logs proxying (cross-host clients must set it; "
        "default 127.0.0.1 only works same-box)",
    )
    agent.add_argument(
        "-data-dir", default="", dest="data_dir",
    )
    agent.add_argument("-http-port", type=int, default=None,
                       dest="http_port")
    agent.add_argument("-num-schedulers", type=int, default=None,
                       dest="num_schedulers")
    agent.add_argument("-config", default=None, dest="config")
    agent.set_defaults(fn=cmd_agent)

    job = sub.add_parser("job")
    job_sub = job.add_subparsers(dest="job_cmd", required=True)
    jr = job_sub.add_parser("run")
    jr.add_argument("file")
    jr.set_defaults(fn=cmd_job_run)
    jp = job_sub.add_parser("plan")
    jp.add_argument("file")
    jp.set_defaults(fn=cmd_job_plan)
    jd = job_sub.add_parser("dispatch")
    jd.add_argument("job_id")
    jd.add_argument("-meta", action="append", dest="meta")
    jd.set_defaults(fn=cmd_job_dispatch)
    js = job_sub.add_parser("status")
    js.add_argument("job_id", nargs="?")
    _add_fmt(js)
    js.set_defaults(fn=cmd_job_status)
    jst = job_sub.add_parser("stop")
    jst.add_argument("-purge", action="store_true", dest="purge")
    jst.add_argument("job_id")
    jst.set_defaults(fn=cmd_job_stop)
    jsc = job_sub.add_parser("scale")
    jsc.add_argument("job_id")
    jsc.add_argument("group")
    jsc.add_argument("count", type=int)
    jsc.set_defaults(fn=cmd_job_scale)
    jh = job_sub.add_parser("history")
    jh.add_argument("job_id")
    _add_fmt(jh)
    jh.set_defaults(fn=cmd_job_history)
    jrev = job_sub.add_parser("revert")
    jrev.add_argument("job_id")
    jrev.add_argument("version", type=int)
    jrev.set_defaults(fn=cmd_job_revert)
    jin = job_sub.add_parser("inspect")
    jin.add_argument("job_id")
    _add_fmt(jin)
    jin.set_defaults(fn=cmd_job_inspect)
    jv = job_sub.add_parser("validate")
    jv.add_argument("file")
    jv.set_defaults(fn=cmd_job_validate)
    jdep = job_sub.add_parser("deployments")
    jdep.add_argument("job_id")
    _add_fmt(jdep)
    jdep.set_defaults(fn=cmd_job_deployments)
    jev = job_sub.add_parser("eval")
    jev.add_argument("job_id")
    jev.set_defaults(fn=cmd_job_eval)
    jpr = job_sub.add_parser("promote")
    jpr.add_argument("job_id")
    jpr.set_defaults(fn=cmd_job_promote)
    jpf = job_sub.add_parser("periodic")
    jpf_sub = jpf.add_subparsers(
        dest="periodic_action", required=True
    )
    jpff = jpf_sub.add_parser("force")
    jpff.add_argument("job_id")
    jpff.set_defaults(fn=cmd_job_periodic)
    jini = job_sub.add_parser("init")
    jini.add_argument("filename", nargs="?", default="")
    jini.set_defaults(fn=cmd_job_init)
    jal = job_sub.add_parser("allocs")
    _add_fmt(jal)
    jal.add_argument("job_id")
    jal.set_defaults(fn=cmd_job_allocs)

    volume = sub.add_parser("volume")
    volume_sub = volume.add_subparsers(dest="volume_cmd", required=True)
    vr = volume_sub.add_parser("register")
    vr.add_argument("file")
    vr.set_defaults(fn=cmd_volume_register)
    vs = volume_sub.add_parser("status")
    vs.add_argument("volume_id", nargs="?", default=None)
    _add_fmt(vs)
    vs.set_defaults(fn=cmd_volume_status)
    vd = volume_sub.add_parser("deregister")
    vd.add_argument("volume_id")
    vd.add_argument("-force", dest="force", action="store_true")
    vd.set_defaults(fn=cmd_volume_deregister)
    vdet = volume_sub.add_parser("detach")
    vdet.add_argument("volume_id")
    vdet.add_argument("node_id")
    vdet.set_defaults(fn=cmd_volume_detach)

    plugin = sub.add_parser("plugin")
    plugin_sub = plugin.add_subparsers(dest="plugin_cmd", required=True)
    ps = plugin_sub.add_parser("status")
    _add_fmt(ps)
    ps.set_defaults(fn=cmd_plugin_status)

    scaling = sub.add_parser("scaling")
    scaling_sub = scaling.add_subparsers(dest="scaling_cmd", required=True)
    scp = scaling_sub.add_parser("policies")
    scp.add_argument("-job", dest="job_id", default=None)
    _add_fmt(scp)
    scp.set_defaults(fn=cmd_scaling_policies)
    sci = scaling_sub.add_parser("policy")
    sci.add_argument("policy_id")
    _add_fmt(sci)
    sci.set_defaults(fn=cmd_scaling_policy_info)

    server = sub.add_parser("server")
    server_sub = server.add_subparsers(dest="server_cmd", required=True)
    sm = server_sub.add_parser("members")
    _add_fmt(sm)
    sm.set_defaults(fn=cmd_server_members)
    sj = server_sub.add_parser("join")
    sj.add_argument("address")
    sj.set_defaults(fn=cmd_server_join)
    sfl = server_sub.add_parser("force-leave")
    sfl.add_argument("name")
    sfl.set_defaults(fn=cmd_server_force_leave)

    node = sub.add_parser("node")
    node_sub = node.add_subparsers(dest="node_cmd", required=True)
    ns = node_sub.add_parser("status")
    ns.add_argument("node_id", nargs="?")
    _add_fmt(ns)
    ns.set_defaults(fn=cmd_node_status)
    nd = node_sub.add_parser("drain")
    nd_group = nd.add_mutually_exclusive_group(required=True)
    nd_group.add_argument("-enable", action="store_true", dest="enable")
    nd_group.add_argument("-disable", action="store_false", dest="enable")
    nd.add_argument("-deadline", type=float, default=3600.0,
                    dest="deadline")
    nd.add_argument("-monitor", action="store_true", dest="monitor")
    nd.add_argument("node_id")
    nd.set_defaults(fn=cmd_node_drain)
    nc = node_sub.add_parser("config")
    nc.add_argument("node_id")
    _add_fmt(nc)
    nc.set_defaults(fn=cmd_node_config)
    ne = node_sub.add_parser("eligibility")
    ne_group = ne.add_mutually_exclusive_group(required=True)
    ne_group.add_argument("-enable", action="store_true", dest="enable")
    ne_group.add_argument("-disable", action="store_false", dest="enable")
    ne.add_argument("node_id")
    ne.set_defaults(fn=cmd_node_eligibility)

    alloc = sub.add_parser("alloc")
    alloc_sub = alloc.add_subparsers(dest="alloc_cmd", required=True)
    als = alloc_sub.add_parser("status")
    als.add_argument("alloc_id")
    _add_fmt(als)
    als.set_defaults(fn=cmd_alloc_status)
    all_ = alloc_sub.add_parser("logs")
    all_.add_argument("-stderr", action="store_true", dest="stderr")
    all_.add_argument("-f", action="store_true", dest="follow")
    all_.add_argument("alloc_id")
    all_.add_argument("task")
    all_.set_defaults(fn=cmd_alloc_logs)
    alr = alloc_sub.add_parser("restart")
    alr.add_argument("alloc_id")
    alr.add_argument("task", nargs="?", default="")
    alr.set_defaults(fn=cmd_alloc_restart)
    alsg = alloc_sub.add_parser("signal")
    alsg.add_argument("-s", dest="signal", default="SIGTERM")
    alsg.add_argument("alloc_id")
    alsg.add_argument("task", nargs="?", default="")
    alsg.set_defaults(fn=cmd_alloc_signal)
    alst = alloc_sub.add_parser("stop")
    alst.add_argument("alloc_id")
    alst.set_defaults(fn=cmd_alloc_stop)
    alex = alloc_sub.add_parser("exec")
    alex.add_argument("-task", dest="task", default="")
    alex.add_argument(
        "-i", action="store_true", dest="interactive",
        help="interactive session over the websocket stream",
    )
    alex.add_argument("alloc_id")
    # REMAINDER so the command's own flags (e.g. sh -c) pass through
    alex.add_argument("cmd", nargs=argparse.REMAINDER)
    alex.set_defaults(fn=cmd_alloc_exec)
    alfs = alloc_sub.add_parser("fs")
    alfs.add_argument("-cat", action="store_true", dest="cat")
    alfs.add_argument("alloc_id")
    alfs.add_argument("path", nargs="?", default="")
    alfs.set_defaults(fn=cmd_alloc_fs)

    ev = sub.add_parser("eval")
    ev_sub = ev.add_subparsers(dest="eval_cmd", required=True)
    evs = ev_sub.add_parser("status")
    evs.add_argument("eval_id")
    _add_fmt(evs)
    evs.set_defaults(fn=cmd_eval_status)
    eve = ev_sub.add_parser("explain")
    eve.add_argument("eval_id")
    _add_fmt(eve)
    eve.set_defaults(fn=cmd_eval_explain)

    dep = sub.add_parser("deployment")
    dep_sub = dep.add_subparsers(dest="action", required=True)
    for name in (
        "status", "list", "promote", "fail", "pause", "resume",
        "unblock",
    ):
        dp = dep_sub.add_parser(name)
        if name in ("status", "list"):
            _add_fmt(dp)
            dp.add_argument("id", nargs="?")
        else:
            # promote/fail/pause/resume/unblock act on ONE
            # deployment: a missing id is a usage error, not a
            # request to /v1/deployment/<action>/None
            dp.add_argument("id")
        dp.set_defaults(fn=cmd_deployment)

    nsp = sub.add_parser("namespace")
    nsp_sub = nsp.add_subparsers(dest="ns_cmd", required=True)
    nsl = nsp_sub.add_parser("list")
    _add_fmt(nsl)
    nsl.set_defaults(fn=cmd_namespace)
    for name in ("status", "inspect", "delete"):
        sp = nsp_sub.add_parser(name)
        if name != "delete":
            _add_fmt(sp)
        sp.add_argument("name")
        sp.set_defaults(fn=cmd_namespace)
    nsa = nsp_sub.add_parser("apply")
    nsa.add_argument("-description", dest="description", default="")
    nsa.add_argument("name")
    nsa.set_defaults(fn=cmd_namespace)

    acl = sub.add_parser("acl")
    acl_sub = acl.add_subparsers(dest="acl_cmd", required=True)
    aclb = acl_sub.add_parser("bootstrap")
    aclb.set_defaults(fn=cmd_acl)
    aclp = acl_sub.add_parser("policy")
    aclp_sub = aclp.add_subparsers(dest="action", required=True)
    app_ = aclp_sub.add_parser("apply")
    app_.add_argument("name")
    app_.add_argument("file")
    app_.set_defaults(fn=cmd_acl)
    apl = aclp_sub.add_parser("list")
    _add_fmt(apl)
    apl.set_defaults(fn=cmd_acl)
    for name in ("info", "delete"):
        sp = aclp_sub.add_parser(name)
        if name == "info":
            _add_fmt(sp)
        sp.add_argument("name")
        sp.set_defaults(fn=cmd_acl)
    aclt = acl_sub.add_parser("token")
    aclt_sub = aclt.add_subparsers(dest="action", required=True)
    atc = aclt_sub.add_parser("create")
    atc.add_argument("-name", dest="name", default="")
    atc.add_argument("-type", dest="type", default="client")
    atc.add_argument("-policy", action="append", dest="policy")
    atc.set_defaults(fn=cmd_acl)
    atl = aclt_sub.add_parser("list")
    _add_fmt(atl)
    atl.set_defaults(fn=cmd_acl)
    ats = aclt_sub.add_parser("self")
    _add_fmt(ats)
    ats.set_defaults(fn=cmd_acl)
    for name in ("info", "delete"):
        sp = aclt_sub.add_parser(name)
        if name == "info":
            _add_fmt(sp)
        sp.add_argument("accessor")
        sp.set_defaults(fn=cmd_acl)
    atu = aclt_sub.add_parser("update")
    atu.add_argument("-name", dest="name", default="")
    atu.add_argument("-policy", action="append", dest="policy")
    atu.add_argument("accessor")
    atu.set_defaults(fn=cmd_acl)

    op = sub.add_parser("operator")
    op_sub = op.add_subparsers(dest="op_cmd", required=True)
    osch = op_sub.add_parser("scheduler")
    osch.add_argument("action", choices=["get-config", "set-config"])
    osch.add_argument("-algorithm", choices=["binpack", "spread"],
                      default=None)
    osch.add_argument("-tpu", choices=["true", "false"], default=None)
    _add_fmt(osch)
    osch.set_defaults(fn=cmd_operator_scheduler)
    osnap = op_sub.add_parser("snapshot")
    osnap_sub = osnap.add_subparsers(dest="action", required=True)
    for name in ("save", "restore", "inspect"):
        sp_p = osnap_sub.add_parser(name)
        sp_p.add_argument("path")
        sp_p.set_defaults(fn=cmd_operator_snapshot)
    oap = op_sub.add_parser("autopilot")
    oap_sub = oap.add_subparsers(dest="action", required=True)
    for name in ("get-config", "set-config", "health"):
        ap_p = oap_sub.add_parser(name)
        if name == "set-config":
            ap_p.add_argument(
                "-cleanup-dead-servers",
                dest="cleanup_dead_servers",
                choices=["true", "false"], default=None,
            )
        else:
            _add_fmt(ap_p)
        ap_p.set_defaults(fn=cmd_operator_autopilot)
    oraft = op_sub.add_parser("raft")
    oraft_sub = oraft.add_subparsers(dest="action", required=True)
    orl = oraft_sub.add_parser("list-peers")
    _add_fmt(orl)
    orl.set_defaults(fn=cmd_operator_raft)
    orr = oraft_sub.add_parser("remove-peer")
    orr.add_argument(
        "-peer-address", dest="address", default=""
    )
    orr.set_defaults(fn=cmd_operator_raft)
    okg = op_sub.add_parser("keygen")
    okg.set_defaults(fn=cmd_operator_keygen)
    okr = op_sub.add_parser("keyring")
    okr_group = okr.add_mutually_exclusive_group()
    okr_group.add_argument("-install", dest="install", default="")
    okr_group.add_argument("-use", dest="use", default="")
    okr_group.add_argument("-remove", dest="remove", default="")
    okr_group.add_argument(
        "-list", action="store_true", dest="list_keys"
    )
    okr.set_defaults(fn=cmd_keyring)
    odbg = op_sub.add_parser("debug")
    odbg.add_argument("-output", dest="output", default="")
    odbg.set_defaults(fn=cmd_operator_debug)

    devp = sub.add_parser("device")
    devp_sub = devp.add_subparsers(dest="action", required=True)
    dst = devp_sub.add_parser("status")
    _add_fmt(dst)
    dst.set_defaults(fn=cmd_device_status)

    slop = sub.add_parser("slo")
    slop_sub = slop.add_subparsers(dest="action", required=True)
    sst = slop_sub.add_parser("status")
    _add_fmt(sst)
    sst.set_defaults(fn=cmd_slo_status)

    decp = sub.add_parser("decisions")
    decp.add_argument("-site", dest="site", default="")
    decp.add_argument("-outcome", dest="outcome", default="")
    decp.add_argument("-trace", dest="trace", default="")
    decp.add_argument(
        "-limit", dest="limit", type=int, default=32
    )
    _add_fmt(decp)
    decp.set_defaults(fn=cmd_decisions)

    mon = sub.add_parser("monitor")
    mon.add_argument(
        "-no-follow", action="store_false", dest="follow",
        default=True,
    )
    mon.set_defaults(fn=cmd_monitor)

    system = sub.add_parser("system")
    system_sub = system.add_subparsers(dest="action", required=True)
    sg = system_sub.add_parser("gc")
    sg.set_defaults(fn=cmd_system)
    sr = system_sub.add_parser("reconcile")
    sr_sub = sr.add_subparsers(dest="target", required=False)
    srs = sr_sub.add_parser("summaries")
    srs.set_defaults(fn=cmd_system, target="summaries")
    sr.set_defaults(fn=cmd_system, target="summaries")

    lic = sub.add_parser("license")
    lic_sub = lic.add_subparsers(dest="license_cmd", required=True)
    for name in ("get", "put"):
        lp = lic_sub.add_parser(name)
        lp.add_argument("file", nargs="?", default="")
        lp.set_defaults(fn=cmd_license)

    # sentinel/quota: registered like the reference OSS build; the
    # server gates the features to Enterprise (command/commands.go
    # registers them unconditionally)
    sentinel = sub.add_parser("sentinel")
    sentinel_sub = sentinel.add_subparsers(
        dest="sentinel_cmd", required=True
    )
    for name in ("apply", "delete", "list", "read"):
        sn = sentinel_sub.add_parser(name)
        sn.add_argument("args", nargs=argparse.REMAINDER)
        sn.set_defaults(fn=cmd_enterprise_gate, family="sentinel")
    quota = sub.add_parser("quota")
    quota_sub = quota.add_subparsers(
        dest="quota_cmd", required=True
    )
    for name in ("apply", "delete", "init", "inspect", "list",
                 "status"):
        qp = quota_sub.add_parser(name)
        qp.add_argument("args", nargs=argparse.REMAINDER)
        qp.set_defaults(fn=cmd_enterprise_gate, family="quota")

    kg = sub.add_parser("keygen")
    kg.set_defaults(fn=cmd_operator_keygen)
    kr = sub.add_parser("keyring")
    kr_group = kr.add_mutually_exclusive_group()
    kr_group.add_argument("-install", dest="install", default="")
    kr_group.add_argument("-use", dest="use", default="")
    kr_group.add_argument("-remove", dest="remove", default="")
    kr_group.add_argument(
        "-list", action="store_true", dest="list_keys"
    )
    kr.set_defaults(fn=cmd_keyring)

    chk = sub.add_parser("check")
    chk.set_defaults(fn=cmd_check)
    ui = sub.add_parser("ui")
    ui.add_argument("-open", action="store_true", dest="open")
    ui.set_defaults(fn=cmd_ui)
    dbg = sub.add_parser("debug")
    dbg.add_argument("-output", dest="output", default="")
    dbg.set_defaults(fn=cmd_operator_debug)

    # top-level aliases (reference registers e.g. "run" -> job run,
    # "status" -> job status; command/commands.go)
    tr = sub.add_parser("run")
    tr.add_argument("file")
    tr.set_defaults(fn=cmd_job_run)
    tp = sub.add_parser("plan")
    tp.add_argument("file")
    tp.set_defaults(fn=cmd_job_plan)
    tst = sub.add_parser("status")
    tst.add_argument("job_id", nargs="?")
    _add_fmt(tst)
    tst.set_defaults(fn=cmd_status)
    tstop = sub.add_parser("stop")
    tstop.add_argument("-purge", action="store_true", dest="purge")
    tstop.add_argument("job_id")
    tstop.set_defaults(fn=cmd_job_stop)
    tv = sub.add_parser("validate")
    tv.add_argument("file")
    tv.set_defaults(fn=cmd_job_validate)
    ti = sub.add_parser("init")
    ti.add_argument("filename", nargs="?", default="")
    ti.set_defaults(fn=cmd_job_init)
    tl = sub.add_parser("logs")
    tl.add_argument("-stderr", action="store_true", dest="stderr")
    tl.add_argument("-f", action="store_true", dest="follow")
    tl.add_argument("alloc_id")
    tl.add_argument("task")
    tl.set_defaults(fn=cmd_alloc_logs)
    tex = sub.add_parser("exec")
    tex.add_argument("-task", dest="task", default="")
    tex.add_argument("alloc_id")
    tex.add_argument("cmd", nargs=argparse.REMAINDER)
    tex.set_defaults(fn=cmd_alloc_exec)
    tin = sub.add_parser("inspect")
    tin.add_argument("job_id")
    _add_fmt(tin)
    tin.set_defaults(fn=cmd_job_inspect)
    tfs = sub.add_parser("fs")
    tfs.add_argument("-cat", action="store_true", dest="cat")
    tfs.add_argument("alloc_id")
    tfs.add_argument("path", nargs="?", default="")
    tfs.set_defaults(fn=cmd_alloc_fs)

    ai = sub.add_parser("agent-info")
    _add_fmt(ai)
    ai.set_defaults(fn=cmd_agent_info)

    # hyphenated legacy aliases (the reference registers both forms,
    # command/commands.go: "node-status", "server-members", ...)
    # deprecated alias for `node config` (reference commands.go:755
    # registers client-config as the Old form of node config)
    hcc = sub.add_parser("client-config")
    _add_fmt(hcc)
    hcc.add_argument("node_id")
    hcc.set_defaults(fn=cmd_node_config)
    hns = sub.add_parser("node-status")
    hns.add_argument("node_id", nargs="?")
    _add_fmt(hns)
    hns.set_defaults(fn=cmd_node_status)
    hnd = sub.add_parser("node-drain")
    hnd_group = hnd.add_mutually_exclusive_group(required=True)
    hnd_group.add_argument(
        "-enable", action="store_true", dest="enable"
    )
    hnd_group.add_argument(
        "-disable", action="store_false", dest="enable"
    )
    hnd.add_argument(
        "-deadline", type=float, default=3600.0, dest="deadline"
    )
    hnd.add_argument(
        "-monitor", action="store_true", dest="monitor"
    )
    hnd.add_argument("node_id")
    hnd.set_defaults(fn=cmd_node_drain)
    has = sub.add_parser("alloc-status")
    has.add_argument("alloc_id")
    _add_fmt(has)
    has.set_defaults(fn=cmd_alloc_status)
    hes = sub.add_parser("eval-status")
    hes.add_argument("eval_id")
    _add_fmt(hes)
    hes.set_defaults(fn=cmd_eval_status)
    hsj = sub.add_parser("server-join")
    hsj.add_argument("address")
    hsj.set_defaults(fn=cmd_server_join)
    hsm = sub.add_parser("server-members")
    _add_fmt(hsm)
    hsm.set_defaults(fn=cmd_server_members)
    hsfl = sub.add_parser("server-force-leave")
    hsfl.add_argument("name")
    hsfl.set_defaults(fn=cmd_server_force_leave)

    version = sub.add_parser("version")
    version.set_defaults(fn=cmd_version)
    return p


def main(argv=None) -> None:
    parser = build_parser()
    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
