"""Agent monitoring surfaces (reference command/agent/monitor/monitor.go
live log streaming + command/agent/http.go:303 /v1/agent/pprof).

* ``LogMonitor`` — a ring-buffer logging handler; ``/v1/agent/monitor``
  serves its tail and clients long-poll with an offset cursor, the
  in-process shape of the reference's hclog SinkAdapter streaming.
* ``thread_dump`` / ``runtime_profile`` — the Python analogs of the
  goroutine and heap pprof endpoints (threads via
  ``sys._current_frames``, memory via ``gc`` stats).
"""
from __future__ import annotations

import gc as _gc
import logging
import sys
import threading
import traceback
from collections import deque
from typing import Dict, List, Optional, Tuple

DEFAULT_BUFFER_LINES = 512


class LogMonitor(logging.Handler):
    """Ring buffer of formatted log lines with a monotonically
    increasing cursor, so pollers can resume where they left off."""

    def __init__(
        self,
        capacity: int = DEFAULT_BUFFER_LINES,
        level: int = logging.INFO,
    ) -> None:
        super().__init__(level)
        self.setFormatter(
            logging.Formatter(
                "%(asctime)s [%(levelname)s] %(name)s: %(message)s"
            )
        )
        self._lock2 = threading.Lock()
        self._buf: deque = deque(maxlen=capacity)
        self._next_seq = 0
        self._cv = threading.Condition(self._lock2)

    def emit(self, record: logging.LogRecord) -> None:
        try:
            line = self.format(record)
        except Exception:  # noqa: BLE001
            return
        with self._cv:
            self._buf.append((self._next_seq, line))
            self._next_seq += 1
            self._cv.notify_all()

    def write_line(self, line: str) -> None:
        """Direct injection for components not routed through
        `logging` (the agent's own lifecycle messages)."""
        with self._cv:
            self._buf.append((self._next_seq, line))
            self._next_seq += 1
            self._cv.notify_all()

    def tail(
        self,
        after: int = -1,
        wait: float = 0.0,
    ) -> Tuple[List[str], int]:
        """Lines with seq > after; blocks up to `wait` seconds when
        nothing new is available (the long-poll used by
        /v1/agent/monitor).  Returns (lines, newest_seq)."""
        with self._cv:
            if wait > 0 and not any(
                seq > after for seq, _line in self._buf
            ):
                self._cv.wait(wait)
            lines = [line for seq, line in self._buf if seq > after]
            return lines, self._next_seq - 1

    def install(self, logger_name: str = "") -> "LogMonitor":
        lg = logging.getLogger(logger_name)
        lg.addHandler(self)
        # without this, INFO records die at the root's WARNING default
        # before any handler sees them
        if lg.getEffectiveLevel() > self.level:
            lg.setLevel(self.level)
        return self

    def uninstall(self, logger_name: str = "") -> None:
        logging.getLogger(logger_name).removeHandler(self)


def thread_dump() -> str:
    """All thread stacks (the goroutine-pprof analog)."""
    frames = sys._current_frames()
    names: Dict[int, str] = {
        t.ident: t.name for t in threading.enumerate()
    }
    out = []
    for ident, frame in frames.items():
        out.append(
            f"thread {ident} ({names.get(ident, 'unknown')}):"
        )
        out.extend(
            line.rstrip()
            for line in traceback.format_stack(frame)
        )
        out.append("")
    return "\n".join(out)


def runtime_profile() -> Dict:
    """Allocator/GC counters (the heap-pprof analog)."""
    counts = _gc.get_count()
    stats = _gc.get_stats()
    return {
        "Threads": threading.active_count(),
        "GCCounts": list(counts),
        "GCCollections": [s.get("collections", 0) for s in stats],
        "GCCollected": [s.get("collected", 0) for s in stats],
        "Objects": len(_gc.get_objects()),
    }
