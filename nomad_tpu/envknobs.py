"""Central registry of every ``NOMAD_TPU_*`` environment knob.

One row per knob: its default, the module that owns (reads) it, and a
one-line description.  This registry — together with the knob table
in docs/ARCHITECTURE.md — is enforced by the ``config-drift`` rule of
``python -m tools.nomadlint``: a knob read anywhere in ``nomad_tpu/``,
``bench.py`` or ``tests/`` must appear here AND in the docs table, a
registered knob must still be read somewhere, and a documented knob
must still be registered.  New knobs therefore cannot ship
undocumented, and removed ones cannot haunt the docs.

The registry is data, not plumbing: call sites keep reading
``os.environ`` directly (many are hot-path or import-time reads with
bespoke parsing/clamping); this module exists so operators and the
lint have ONE place to look.
"""
from __future__ import annotations

from typing import Dict, NamedTuple


class EnvKnob(NamedTuple):
    default: str  # human-readable default ("" = unset)
    owner: str  # repo-relative owning module
    doc: str  # one-line description


ENV_KNOBS: Dict[str, EnvKnob] = {
    # -- batch pipeline (server/batch_worker.py) ----------------------
    "NOMAD_TPU_BATCH_MAX": EnvKnob(
        "64", "nomad_tpu/server/batch_worker.py",
        "max evals per prescore gulp (clamped to [1, 64])",
    ),
    "NOMAD_TPU_PARALLEL_REPLAY": EnvKnob(
        "1", "nomad_tpu/server/batch_worker.py",
        "0 restores the serial replay loop",
    ),
    "NOMAD_TPU_REPLAY_STRICT": EnvKnob(
        "0", "nomad_tpu/server/batch_worker.py",
        "1 serializes every wave-contended eval (full score-metric "
        "bit-identity)",
    ),
    "NOMAD_TPU_REPLAY_WORKERS": EnvKnob(
        "0", "nomad_tpu/server/batch_worker.py",
        "replay pool size (0 = auto)",
    ),
    "NOMAD_TPU_LATENCY_BUDGET_MS": EnvKnob(
        "250", "nomad_tpu/server/batch_worker.py",
        "adaptive gulp cap: keep last-eval latency within this "
        "budget when the worker keeps up (0 disables)",
    ),
    "NOMAD_TPU_ADMIT": EnvKnob(
        "1", "nomad_tpu/server/batch_worker.py",
        "0 restores flush-boundary gulps (no mid-chain admission)",
    ),
    "NOMAD_TPU_PIPELINE_DEPTH": EnvKnob(
        "2", "nomad_tpu/server/batch_worker.py",
        "chunk launches in flight before the host blocks on a fetch",
    ),
    "NOMAD_TPU_MESH": EnvKnob(
        "0", "nomad_tpu/server/batch_worker.py",
        "1 shards prescore launches over the node-axis device mesh",
    ),
    "NOMAD_TPU_MESH_DEVICES": EnvKnob(
        "0", "nomad_tpu/server/batch_worker.py",
        "cap on the node-axis mesh device count (0 = all devices; "
        "bench sweeps and deployments reserving chips set this)",
    ),
    "NOMAD_TPU_STORM": EnvKnob(
        "0", "nomad_tpu/server/batch_worker.py",
        "1 coalesces same-family eval storms into one global "
        "device assignment solve (serial equivalence explicitly "
        "relaxed; divergences audited via the explain ring)",
    ),
    "NOMAD_TPU_STORM_MIN": EnvKnob(
        "16", "nomad_tpu/server/batch_worker.py",
        "storm trigger threshold: minimum contiguous same-family "
        "broker backlog before a coalesced solve engages",
    ),
    "NOMAD_TPU_STORM_MAX": EnvKnob(
        "256", "nomad_tpu/server/batch_worker.py",
        "max evals drained into one storm solve (clamped to "
        "[STORM_MIN, 1024])",
    ),
    "NOMAD_TPU_STORM_ROUNDS": EnvKnob(
        "0", "nomad_tpu/server/batch_worker.py",
        "cap on storm auction rounds (0 = auto: the padded row "
        "bucket, the solver's convergence bound)",
    ),
    # -- policy-weighted scoring (sched/policy.py) --------------------
    "NOMAD_TPU_POLICY": EnvKnob(
        "1", "nomad_tpu/sched/policy.py",
        "0 disables the policy-weighted scoring layer (jobs carrying "
        "a policy stanza score as policy-less)",
    ),
    "NOMAD_TPU_POLICY_TPUT_COEF": EnvKnob(
        "", "nomad_tpu/sched/policy.py",
        "operator override for every job's throughput coefficient "
        "(unset = per-job spec value)",
    ),
    "NOMAD_TPU_POLICY_MIG_COEF": EnvKnob(
        "", "nomad_tpu/sched/policy.py",
        "operator override for every job's migration stickiness "
        "coefficient (unset = per-job spec value)",
    ),
    "NOMAD_TPU_POLICY_CACHE": EnvKnob(
        "64", "nomad_tpu/sched/policy.py",
        "LRU capacity of the assembled throughput-tensor cache "
        "(keyed by table epoch / job version / topo generation)",
    ),
    # -- multi-host mesh (nomad_tpu/parallel/mesh.py) -----------------
    "NOMAD_TPU_DIST": EnvKnob(
        "0", "nomad_tpu/parallel/mesh.py",
        "1 opts this process into the multi-host pod mesh "
        "(jax.distributed init; single-process stays the "
        "zero-config default)",
    ),
    "NOMAD_TPU_DIST_COORD": EnvKnob(
        "127.0.0.1:8476", "nomad_tpu/parallel/mesh.py",
        "coordinator address (process 0's host:port) for the "
        "distributed init",
    ),
    "NOMAD_TPU_DIST_PROCS": EnvKnob(
        "1", "nomad_tpu/parallel/mesh.py",
        "total processes in the multi-host world (<=1 keeps "
        "distributed init off)",
    ),
    "NOMAD_TPU_DIST_ID": EnvKnob(
        "0", "nomad_tpu/parallel/mesh.py",
        "this process's id in [0, NOMAD_TPU_DIST_PROCS)",
    ),
    "NOMAD_TPU_DIST_NS": EnvKnob(
        "", "nomad_tpu/parallel/mesh.py",
        "world namespace suffix: with NS set, "
        "NOMAD_TPU_DIST_<KNOB>_<NS> overrides the bare knob, so N "
        "follower-headed worlds can coexist in one env block "
        "(composed fan-out topologies)",
    ),
    "NOMAD_TPU_POD_PORT": EnvKnob(
        "", "nomad_tpu/server/batch_worker.py",
        "pod-head stream port: process 0 of a multi-host world "
        "serves the mesh-operation stream (parallel/pod.py) that "
        "peer processes replay in FIFO order",
    ),
    "NOMAD_TPU_POD_CHECK": EnvKnob(
        "0", "nomad_tpu/parallel/pod.py",
        "1 makes every pod chain/storm launch round-trip a result "
        "digest from every peer — the head/peer bit-parity gate",
    ),
    "NOMAD_TPU_SMOKE_NODES": EnvKnob(
        "12", "nomad_tpu/parallel/dist_smoke.py",
        "dist_smoke world size: registered nodes",
    ),
    "NOMAD_TPU_SMOKE_JOBS": EnvKnob(
        "12", "nomad_tpu/parallel/dist_smoke.py",
        "dist_smoke chain-phase eval count",
    ),
    "NOMAD_TPU_SMOKE_FAMILY": EnvKnob(
        "16", "nomad_tpu/parallel/dist_smoke.py",
        "dist_smoke storm-phase family size",
    ),
    "NOMAD_TPU_TSAN": EnvKnob(
        "0", "nomad_tpu/tsan.py",
        "1 turns on the happens-before sanitizer: shared-singleton "
        "attribute accesses and lock ops are vector-clock logged, "
        "and the tier-1 soak asserts conflicts stay inside the "
        "static SHARED_STATE_ALLOWLIST",
    ),
    "NOMAD_TPU_SYNC_COMPILE": EnvKnob(
        "0", "nomad_tpu/server/batch_worker.py",
        "1 makes cold kernel compiles block (deterministic tests) "
        "instead of background-compiling behind the shield",
    ),
    # -- cluster / failover (server/cluster.py, raft/chaos.py) --------
    "NOMAD_TPU_FORWARD_RETRIES": EnvKnob(
        "4", "nomad_tpu/server/cluster.py",
        "leader-forward retry budget after the first attempt; each "
        "retry rediscovers the leader (command ids keep retries "
        "idempotent)",
    ),
    "NOMAD_TPU_FORWARD_BACKOFF_S": EnvKnob(
        "0.05", "nomad_tpu/server/cluster.py",
        "initial leader-forward retry backoff, doubling per attempt "
        "(capped at 1s)",
    ),
    "NOMAD_TPU_CLUSTER_FAULT": EnvKnob(
        "", "nomad_tpu/raft/chaos.py",
        "deterministic cluster fault plan "
        "(leader_kill|partition[:a,b]|msg_drop[:pct]|slow_wire[:ms]) "
        "for the chaos harness",
    ),
    # -- follower scheduling fan-out (server/fanout.py) ---------------
    "NOMAD_TPU_FANOUT": EnvKnob(
        "0", "nomad_tpu/server/fanout.py",
        "1 turns followers into schedulers: each runs the full TPU "
        "batch pipeline against its local replicated state, leasing "
        "evals from the leader's broker over RPC with commit "
        "serialized on the leader's plan queue",
    ),
    "NOMAD_TPU_FANOUT_WORKERS": EnvKnob(
        "1", "nomad_tpu/server/fanout.py",
        "fan-out batch workers per follower server",
    ),
    "NOMAD_TPU_FANOUT_LEASE_N": EnvKnob(
        "8", "nomad_tpu/server/fanout.py",
        "max broker leases granted per remote dequeue RPC (the "
        "surplus buffers locally, so gulp fills are buffer pops, "
        "not round trips)",
    ),
    "NOMAD_TPU_FANOUT_MESH": EnvKnob(
        "0", "nomad_tpu/server/batch_worker.py",
        "1 reserves the device mesh (and the pod head) for the "
        "follower fan-out worker — main workers in the same process "
        "stay meshless instead of racing it for the world",
    ),
    "NOMAD_TPU_FANOUT_REFRESH_WAIT_S": EnvKnob(
        "5", "nomad_tpu/server/fanout.py",
        "budget a follower waits for its local FSM apply to catch "
        "up (eval modify-index fence at the gulp boundary, "
        "refresh-index after a partial commit, own-commit "
        "alloc-index catch-up); past it the leases nack for "
        "redelivery",
    ),
    # -- multi-region federation (server/federation.py) ---------------
    "NOMAD_TPU_FED_RETRIES": EnvKnob(
        "4", "nomad_tpu/server/federation.py",
        "cross-region forward retry budget after the first attempt; "
        "each retry re-resolves the target region's membership from "
        "gossip (fan-out command ids keep retries idempotent)",
    ),
    "NOMAD_TPU_FED_BACKOFF_S": EnvKnob(
        "0.05", "nomad_tpu/server/federation.py",
        "initial cross-region retry backoff, doubling per attempt "
        "(capped at 1s)",
    ),
    "NOMAD_TPU_REGION_PROBE_S": EnvKnob(
        "0.5", "nomad_tpu/server/federation.py",
        "federation router cadence: how often the gossip-derived "
        "region health/routing snapshot (and the federation.* "
        "gauges) refresh",
    ),
    "NOMAD_TPU_FED_PROXY_TIMEOUT_S": EnvKnob(
        "2", "nomad_tpu/api/http.py",
        "deadline for a ?region= HTTP read proxied to another "
        "region's advertised HTTP address (the explicit WAN-read "
        "escape hatch)",
    ),
    # -- overload control plane (server/overload.py, server.py) -------
    "NOMAD_TPU_OVERLOAD": EnvKnob(
        "1", "nomad_tpu/server/overload.py",
        "0 disables ingress backpressure (every request admitted, "
        "mode pinned NORMAL)",
    ),
    "NOMAD_TPU_OVERLOAD_DEPTH": EnvKnob(
        "512", "nomad_tpu/server/overload.py",
        "broker pending-depth threshold for SHEDDING (EMERGENCY "
        "engages at 4x)",
    ),
    "NOMAD_TPU_OVERLOAD_AGE_S": EnvKnob(
        "30", "nomad_tpu/server/overload.py",
        "oldest-ready-eval age threshold for SHEDDING (EMERGENCY "
        "at 4x) — the measured commit-wave lag signal",
    ),
    "NOMAD_TPU_OVERLOAD_P99_MS": EnvKnob(
        "0", "nomad_tpu/server/overload.py",
        "flight-recorder eval-latency p99 threshold for SHEDDING "
        "(EMERGENCY at 4x); 0 disables the latency signal",
    ),
    "NOMAD_TPU_OVERLOAD_SHED_FLOOR": EnvKnob(
        "2", "nomad_tpu/server/overload.py",
        "lowest priority class SHEDDING may shed (2 = job "
        "submissions only; 1 also sheds queries; heartbeats are "
        "never shed)",
    ),
    "NOMAD_TPU_OVERLOAD_WAVE_MIN": EnvKnob(
        "8", "nomad_tpu/server/server.py",
        "TTL expiries per sweep that count as a correlated mass "
        "node-death (smaller waves transition immediately)",
    ),
    "NOMAD_TPU_OVERLOAD_WAVE_GATHER_S": EnvKnob(
        "auto", "nomad_tpu/server/server.py",
        "max time a detected mass-death wave gathers straggler TTL "
        "expiries before the batched down transition commits "
        "(auto = heartbeat_ttl/3 clamped to [2.5, 10]s, so the "
        "budget always exceeds the 2s quiet-stream settle)",
    ),
    # -- server / broker ----------------------------------------------
    "NOMAD_TPU_WARM_ON_START": EnvKnob(
        "0", "nomad_tpu/server/server.py",
        "1 pre-compiles prescore launch shapes off the scheduling "
        "path once the node-join wave settles",
    ),
    "NOMAD_TPU_BROKER_WATCHDOG": EnvKnob(
        "0", "nomad_tpu/server/eval_broker.py",
        "1 makes the broker sweeper notify_all() every tick "
        "(sandbox workaround for parked Condition waits)",
    ),
    # -- observability ------------------------------------------------
    "NOMAD_TPU_TRACE": EnvKnob(
        "1", "nomad_tpu/trace.py",
        "0 turns the eval flight recorder into no-ops",
    ),
    "NOMAD_TPU_EXPLAIN": EnvKnob(
        "1", "nomad_tpu/explain.py",
        "0 turns placement-explanation capture into no-ops",
    ),
    "NOMAD_TPU_OBS_HISTORY": EnvKnob(
        "1", "nomad_tpu/telemetry.py",
        "0 disables the periodic metric time-series history ring "
        "(snapshot thread never starts, /v1/metrics/history empty)",
    ),
    "NOMAD_TPU_OBS_HISTORY_N": EnvKnob(
        "60", "nomad_tpu/telemetry.py",
        "metric history depth: how many snapshot windows the ring "
        "retains (min 2)",
    ),
    "NOMAD_TPU_OBS_HISTORY_S": EnvKnob(
        "10", "nomad_tpu/telemetry.py",
        "metric history cadence: seconds between snapshot windows "
        "(default N*S = a 10-minute rolling view)",
    ),
    "NOMAD_TPU_SLO": EnvKnob(
        "1", "nomad_tpu/slo.py",
        "0 disables SLO burn-rate grading (/v1/slo reports every "
        "objective OK with zero burn)",
    ),
    "NOMAD_TPU_SLO_FAST_N": EnvKnob(
        "6", "nomad_tpu/slo.py",
        "fast burn window: newest history snapshots graded for "
        "'is it happening now' (min 2)",
    ),
    "NOMAD_TPU_SLO_SLOW_N": EnvKnob(
        "30", "nomad_tpu/slo.py",
        "slow burn window: newest history snapshots graded for "
        "'is it material' (min 2)",
    ),
    "NOMAD_TPU_SLO_WARN": EnvKnob(
        "1.0", "nomad_tpu/slo.py",
        "WARN threshold: either window burning at >= this rate",
    ),
    "NOMAD_TPU_SLO_BURN": EnvKnob(
        "2.0", "nomad_tpu/slo.py",
        "BURNING threshold: BOTH windows burning at >= this rate",
    ),
    "NOMAD_TPU_SLO_P99_MS": EnvKnob(
        "250", "nomad_tpu/slo.py",
        "interactive_placement_p99 objective target: windowed "
        "eval-latency p99 budget",
    ),
    "NOMAD_TPU_SLO_FAILOVER_MS": EnvKnob(
        "60000", "nomad_tpu/slo.py",
        "failover_detect_to_resume objective target: device "
        "failover-to-restored p99 budget",
    ),
    "NOMAD_TPU_DECISIONS": EnvKnob(
        "1", "nomad_tpu/decisions.py",
        "0 turns the adaptive-decision ledger into no-ops (sites "
        "skip record assembly entirely)",
    ),
    "NOMAD_TPU_DECISIONS_RING": EnvKnob(
        "512", "nomad_tpu/decisions.py",
        "decision-ledger ring depth: newest-wins retention bound "
        "(min 16)",
    ),
    "NOMAD_TPU_OBS_FANIN_TIMEOUT_S": EnvKnob(
        "2.0", "nomad_tpu/server/cluster.py",
        "per-query wall budget for the leader's /v1/cluster/* "
        "fan-in: peers not answered within it are marked "
        "unreachable in the merged (partial) result",
    ),
    # -- accelerator supervisor (nomad_tpu/device) --------------------
    "NOMAD_TPU_SUPERVISOR": EnvKnob(
        "auto", "nomad_tpu/device/supervisor.py",
        "1 forces device supervision on, 0 off (default: on when "
        "JAX_PLATFORMS names a non-cpu backend or a fault is armed)",
    ),
    "NOMAD_TPU_PROBE_INTERVAL_S": EnvKnob(
        "30", "nomad_tpu/device/supervisor.py",
        "canary probe cadence",
    ),
    "NOMAD_TPU_PROBE_TIMEOUT_S": EnvKnob(
        "10", "nomad_tpu/device/supervisor.py",
        "canary probe deadline",
    ),
    "NOMAD_TPU_LOST_PROBES": EnvKnob(
        "2", "nomad_tpu/device/supervisor.py",
        "consecutive canary failures past DEGRADED before LOST",
    ),
    "NOMAD_TPU_RECOVER_CANARIES": EnvKnob(
        "3", "nomad_tpu/device/supervisor.py",
        "consecutive canary passes before flipping back HEALTHY",
    ),
    "NOMAD_TPU_INIT_GRACE_S": EnvKnob(
        "600", "nomad_tpu/device/supervisor.py",
        "deadline floor until the device answers once (cold PJRT "
        "init must not read as a wedge)",
    ),
    "NOMAD_TPU_WATCHDOG_FACTOR": EnvKnob(
        "20", "nomad_tpu/device/supervisor.py",
        "launch-watchdog budget = factor x stage EWMA",
    ),
    "NOMAD_TPU_WATCHDOG_MIN_S": EnvKnob(
        "5", "nomad_tpu/device/supervisor.py",
        "launch-watchdog budget floor",
    ),
    "NOMAD_TPU_WATCHDOG_MAX_S": EnvKnob(
        "120", "nomad_tpu/device/supervisor.py",
        "launch-watchdog budget ceiling",
    ),
    "NOMAD_TPU_FAULT": EnvKnob(
        "", "nomad_tpu/device/faults.py",
        "deterministic CPU fault plan "
        "(wedge_launch|slow_fetch|init_block|flaky[:N])",
    ),
    "NOMAD_TPU_PREFLIGHT_S": EnvKnob(
        "600", "nomad_tpu/device/preflight.py",
        "total preflight retry budget for "
        "`python -m nomad_tpu.device.preflight`",
    ),
    # -- device lock (nomad_tpu/device_lock.py) -----------------------
    "NOMAD_TPU_DEVICE_LOCK": EnvKnob(
        "/tmp/nomad_tpu_device.lock", "nomad_tpu/device_lock.py",
        "cross-process accelerator lockfile path",
    ),
    "NOMAD_TPU_DEVICE_LOCK_WAIT": EnvKnob(
        "block", "nomad_tpu/device_lock.py",
        "seconds to wait for the device lock before giving up "
        "(default: block forever)",
    ),
    # -- client -------------------------------------------------------
    "NOMAD_TPU_EXEC_ISOLATION": EnvKnob(
        "1", "nomad_tpu/client/drivers/exec.py",
        "0 forces the in-process restricted-env spawn instead of "
        "the isolated executor process",
    ),
    "NOMAD_TPU_FINGERPRINT_TIMEOUT_S": EnvKnob(
        "20", "nomad_tpu/client/fingerprint.py",
        "bounded TPU device-probe deadline during fingerprinting",
    ),
    "NOMAD_TPU_EXECUTOR_STATE": EnvKnob(
        "auto", "nomad_tpu/client/executor.py",
        "executor state directory (default: per-user temp dir)",
    ),
    # -- tests --------------------------------------------------------
    "NOMAD_TPU_SOAK": EnvKnob(
        "0", "tests/test_soak.py",
        "1 opts in to the long-running soak tests",
    ),
}
