"""Swarm-scale SLO smoke: overload + mass node-death against the
real HTTP API.

The control plane's production claim is not "fast when polite" — it
is "inside its SLO when thousands of clients arrive at once AND a
rack dies mid-storm".  This harness plays that day (ROADMAP item 2's
load-harness half) against ONE real server over HTTP:

* a **heartbeat storm**: every registered node heartbeats on period
  over the API (the liveness plane the overload ladder must never
  shed);
* a **submitter swarm**: ``--submitters`` logical clients registering
  jobs concurrently, honoring 429 + Retry-After — the traffic that
  MUST overload the default-sized broker and be shed, not queued into
  p99 oblivion;
* a **blocking-query fan-out** long-polling state (degrades to
  non-blocking under SHEDDING);
* a **rolling drain** of a few nodes (operator maintenance under
  load);
* an injected **mass node-death**: ``--death`` nodes go silent at
  once; the heartbeat sweeper must gather their TTL expiries into ONE
  batched down-transition whose replan evals ride ONE storm family
  through the global assignment solver.

SLO gates (exit 0 = all held, 2 = the JSON names the violation):

* **zero lost evals** — every base job and every accepted submission
  ends fully placed; no pending/blocked evals; empty failed queue;
* **zero false node-downs** — no node that kept heartbeating was
  ever marked down (an overloaded leader shedding heartbeats would
  trip exactly this);
* **heartbeat success >= 99.9%**;
* **<= --max-solves storm solves** replan the death wave (storms are
  impossible elsewhere: submission jobs are single-eval families);
* **bounded sheds** — overload engaged (sheds > 0) and every shed
  submitter eventually succeeded;
* **p99 within budget** — the flight-recorder eval-latency p99 (with
  trace exemplars) stays under ``--p99-budget-ms``.

Usage::

    python -m nomad_tpu.loadgen.swarm_smoke [--nodes N]
        [--submitters S] [--death D] [--ttl SEC] [--json PATH]

The result is the bench ``swarm`` block (bench.py embeds it under
``BENCH_SWARM=1``).
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time
from typing import Dict, List, Optional

# knob defaults for the smoke, applied BEFORE nomad_tpu imports so
# construction-time reads see them; explicit operator env wins
_SMOKE_ENV = {
    # the death wave must coalesce into one global solve
    "NOMAD_TPU_STORM": "1",
    "NOMAD_TPU_STORM_MIN": "8",
    "NOMAD_TPU_STORM_MAX": "1024",
    # overload must ENGAGE under the submitter swarm
    "NOMAD_TPU_OVERLOAD": "1",
    "NOMAD_TPU_OVERLOAD_AGE_S": "15",
}


def _apply_env(submitters: int) -> None:
    for key, value in _SMOKE_ENV.items():
        os.environ.setdefault(key, value)
    # depth threshold far below the swarm size so shedding (not an
    # unbounded backlog) absorbs the burst, at every --submitters
    # scale; explicit operator env wins
    os.environ.setdefault(
        "NOMAD_TPU_OVERLOAD_DEPTH", str(max(24, submitters // 8))
    )
    # the wave gather budget stays on its "auto" default
    # (heartbeat_ttl/3): the smoke's heartbeat phases spread a rack
    # death's expiries across one hb period (ttl/4), which auto
    # covers


def _percentile(vals: List[float], q: float) -> float:
    if not vals:
        return 0.0
    ordered = sorted(vals)
    idx = min(len(ordered) - 1, int(round(q * (len(ordered) - 1))))
    return ordered[idx]


def _base_job(job_id: str):
    """One-alloc service job shaped for the storm solver's capacity
    model (single TG, cpu/mem only) with immediate reschedule, so a
    node death replans it in the node-update eval itself instead of
    parking a delayed follow-up outside the storm family."""
    from .. import mock
    from ..structs import ReschedulePolicy

    job = mock.job(id=job_id)
    job.task_groups[0].count = 1
    for tg in job.task_groups:
        tg.reschedule_policy = ReschedulePolicy(
            attempts=0,
            interval_s=0,
            delay_s=0,
            delay_function="constant",
            max_delay_s=0,
            unlimited=True,
        )
        for task in tg.tasks:
            task.resources.cpu = 50
            task.resources.memory_mb = 32
    return job


def _submit_job_dict(i: int) -> dict:
    """Wire-form submission job (what a real client POSTs)."""
    return {
        "ID": f"swarm-sub-{i:05d}",
        "Name": f"swarm-sub-{i:05d}",
        "Type": "service",
        # below the base jobs' priority 50: death-wave replans jump
        # the submission backlog, like production node recovery should
        "Priority": 40,
        "Datacenters": ["dc1"],
        "TaskGroups": [
            {
                "Name": "g",
                "Count": 1,
                "Tasks": [
                    {
                        "Name": "t",
                        "Driver": "mock_driver",
                        "Config": {"run_for": -1},
                        "Resources": {"CPU": 50, "MemoryMB": 32},
                    }
                ],
            }
        ],
    }


def _fully_placed(store, namespace: str, job_id: str, count: int):
    live = [
        a
        for a in store.allocs_by_job(namespace, job_id)
        if not a.terminal_status()
    ]
    return len(live) == count


def run_swarm(
    nodes: int = 2200,
    submitters: int = 1100,
    death: int = 500,
    ttl_s: float = 15.0,
    drains: int = 6,
    base_jobs: Optional[int] = None,
    max_solves: int = 2,
    # generous by design: under a deliberate 1k-client overload the
    # p99 carries the bounded shed/queue delay plus the storm solve's
    # one-off XLA compile on cold CPU backends — the gate is
    # "bounded", not "fast while being deliberately drowned"
    p99_budget_ms: float = 30000.0,
    seed: int = 0,
    # the liveness plane must be provisioned for the load: with too
    # few generator threads a busy run delays heartbeats past the
    # TTL and manufactures transient false node-downs — the exact
    # failure the harness exists to catch server-side
    hb_threads: int = 32,
    submit_threads: int = 16,
    settle_timeout_s: float = 300.0,
) -> Dict:
    """Run the swarm scenario; returns the bench ``swarm`` block
    (``ok`` = every SLO held, ``violations`` names what didn't)."""
    _apply_env(submitters)

    from .. import mock
    from ..api import start_http_server
    from ..server import Server
    from ..structs import ALLOC_CLIENT_STATUS_RUNNING, NODE_STATUS_DOWN
    from .swarm import (
        BlockingFanout,
        HeartbeatStorm,
        SubmitterSwarm,
        rolling_drain,
    )

    rng = random.Random(seed)
    if base_jobs is None:
        base_jobs = max(64, death + death // 5)
    t_start = time.monotonic()
    violations: List[str] = []

    server = Server(
        num_schedulers=1,
        heartbeat_ttl=ttl_s,
        seed=seed,
        # a mass-death wave leases hundreds of members in one
        # drain_family; the serial-fallback tail of a 500-node wave
        # must not outlive its lease, or at-least-once redelivery
        # re-coalesces still-in-progress members into EXTRA storm
        # solves (observed at 30s under deliberate overload)
        nack_timeout=180.0,
    )
    server.start()
    # spread placement: the base workload must cover the node
    # population, or the injected rack death hits empty nodes and the
    # replan wave is vacuous
    from ..structs import SchedulerConfiguration

    server.store.set_scheduler_config(
        SchedulerConfiguration(scheduler_algorithm="spread")
    )
    http = start_http_server(server, port=0)
    host, port = "127.0.0.1", http.port

    phase_s: Dict[str, float] = {}
    storm = fanout = swarm = None
    try:
        # -- setup: nodes + base workload (direct calls; the LOAD
        # goes over HTTP, the fixture doesn't have to) ---------------
        t0 = time.monotonic()
        node_ids = []
        for _ in range(nodes):
            node = mock.node()
            server.register_node(node)
            node_ids.append(node.id)
        for i in range(base_jobs):
            server.register_job(_base_job(f"swarm-base-{i:05d}"))
        if not server.drain_to_idle(timeout=240.0):
            violations.append("base workload did not settle")
        # mark running so a node death registers as alloc loss
        running = []
        for i in range(base_jobs):
            for alloc in server.store.allocs_by_job(
                "default", f"swarm-base-{i:05d}"
            ):
                if not alloc.terminal_status():
                    alloc.client_status = ALLOC_CLIENT_STATUS_RUNNING
                    running.append(alloc)
        server.store.upsert_allocs(running)
        phase_s["setup"] = time.monotonic() - t0

        # victims: nodes actually hosting base allocs first (the
        # death must force replans), padded with empty nodes
        hosting = list(
            {
                a.node_id
                for a in running
            }
        )
        rng.shuffle(hosting)
        victims = hosting[:death]
        if len(victims) < death:
            spare = [n for n in node_ids if n not in set(victims)]
            rng.shuffle(spare)
            victims += spare[: death - len(victims)]
        victim_set = set(victims)
        affected_jobs = {
            (a.namespace, a.job_id)
            for a in running
            if a.node_id in victim_set
        }

        # -- swarm on: heartbeat storm + blocking fan-out ------------
        storm = HeartbeatStorm(
            host, port, node_ids,
            period_s=ttl_s / 4.0, threads=hb_threads,
        )
        fanout = BlockingFanout(host, port, threads=8)

        # transient false-down monitor: a live node marked down and
        # revived before the end-state check is STILL a false
        # node-down (the SLO is "never", not "not at the end")
        transient_false_downs: set = set()
        monitor_stop = threading.Event()

        def monitor_downs() -> None:
            while not monitor_stop.is_set():
                for node in server.store.iter_nodes():
                    if (
                        node.id not in victim_set
                        and node.status == NODE_STATUS_DOWN
                    ):
                        transient_false_downs.add(node.id)
                monitor_stop.wait(0.5)

        threading.Thread(
            target=monitor_downs, name="down-monitor", daemon=True
        ).start()

        solves_before = server.metrics.get_counter("storm.solves")
        waves_before = server.metrics.get_counter(
            "overload.node_down_waves"
        )

        # rolling drain of a few live non-victim nodes under the
        # heartbeat storm, BEFORE the submitter swarm: node drain is
        # an operator write (submit class), so once overload engages
        # it would be shed — correctly, but then nothing drains
        drain_candidates = [
            n for n in node_ids if n not in victim_set
        ][:drains]
        drained = rolling_drain(host, port, drain_candidates)

        # -- submitter swarm (the overload) --------------------------
        t0 = time.monotonic()
        swarm = SubmitterSwarm(
            host, port, submitters,
            make_job=_submit_job_dict,
            threads=submit_threads,
        )

        # -- mass death, injected while the swarm is still loud ------
        time.sleep(1.0)
        t_kill = time.monotonic()
        storm.kill(victims)

        # the wave: every victim down, in few batched transitions
        deadline = time.monotonic() + ttl_s * 3 + 30.0
        while time.monotonic() < deadline:
            down = sum(
                1
                for nid in victims
                if (n := server.store.node_by_id(nid)) is not None
                and n.status == NODE_STATUS_DOWN
            )
            if down == len(victims):
                break
            time.sleep(0.25)
        down = sum(
            1
            for nid in victims
            if (n := server.store.node_by_id(nid)) is not None
            and n.status == NODE_STATUS_DOWN
        )
        detect_s = time.monotonic() - t_kill
        if down != len(victims):
            violations.append(
                f"mass death incomplete: {down}/{len(victims)} down"
            )
        phase_s["death_detect"] = detect_s

        # -- drain: swarm done, backlog empty, overload recovered ----
        deadline = time.monotonic() + settle_timeout_s
        while time.monotonic() < deadline:
            if swarm.done():
                break
            time.sleep(0.5)
        if not swarm.done():
            swarm.stop()
            violations.append("submitter swarm wedged")
        phase_s["submit"] = time.monotonic() - t0

        t0 = time.monotonic()
        deadline = time.monotonic() + settle_timeout_s
        while time.monotonic() < deadline:
            pending = [
                ev
                for ev in list(server.store.evals.values())
                if ev.status in ("pending", "blocked")
            ]
            if not pending and server.drain_to_idle(timeout=2.0):
                break
            time.sleep(0.5)
        phase_s["settle"] = time.monotonic() - t0
        monitor_stop.set()
    finally:
        for gen in (storm, fanout, swarm):
            if gen is not None:
                gen.stop()

    # -- collect + gate ----------------------------------------------
    store = server.store
    metrics = server.metrics.dump()
    counters = metrics["counters"]
    solves = counters.get("storm.solves", 0.0) - solves_before
    waves = (
        counters.get("overload.node_down_waves", 0.0) - waves_before
    )

    # zero lost evals
    nonterminal = [
        ev.id
        for ev in list(store.evals.values())
        if ev.status in ("pending", "blocked")
    ]
    failed_queue = len(server.broker.failed())
    lost_jobs: List[str] = []
    for i in range(base_jobs):
        job_id = f"swarm-base-{i:05d}"
        if not _fully_placed(store, "default", job_id, 1):
            lost_jobs.append(job_id)
    accepted_missing = 0
    for i in range(submitters):
        job_id = f"swarm-sub-{i:05d}"
        if store.job_by_id("default", job_id) is None:
            continue  # never accepted (counted via swarm.failed)
        if not _fully_placed(store, "default", job_id, 1):
            accepted_missing += 1
            lost_jobs.append(job_id)
    if nonterminal:
        violations.append(
            f"{len(nonterminal)} non-terminal evals after settle"
        )
    if failed_queue:
        violations.append(f"{failed_queue} evals in the failed queue")
    if lost_jobs:
        violations.append(
            f"{len(lost_jobs)} jobs not fully placed"
        )
    if swarm is not None and swarm.failed:
        violations.append(
            f"{len(swarm.failed)} submitters never succeeded"
        )

    # zero false node-downs: every non-victim node kept heartbeating
    # and must never have been marked down — transients included
    # (the monitor sampled the whole run)
    false_downs = sorted(
        transient_false_downs
        | {
            n.id
            for n in store.iter_nodes()
            if n.id not in victim_set
            and n.status == NODE_STATUS_DOWN
        }
    )
    if false_downs:
        violations.append(
            f"{len(false_downs)} false node-downs (overload shed "
            "heartbeats?)"
        )

    # heartbeat SLO
    hb_ok, hb_fail = storm.counts() if storm is not None else (0, 0)
    hb_total = hb_ok + hb_fail
    hb_success = hb_ok / hb_total if hb_total else 0.0
    if hb_total == 0 or hb_success < 0.999:
        violations.append(
            f"heartbeat success {hb_success:.4%} < 99.9%"
        )

    # the death wave rode the storm solver, in <= max_solves solves
    if solves > max_solves:
        violations.append(
            f"death wave took {solves:.0f} storm solves "
            f"(> {max_solves})"
        )
    if affected_jobs and solves < 1:
        violations.append(
            "death wave never reached the storm solver"
        )

    # overload engaged and stayed bounded
    sheds = counters.get("overload.shed", 0.0)
    if sheds <= 0:
        violations.append(
            "overload never engaged (no sheds) — the swarm did not "
            "exercise backpressure"
        )

    # flight-recorder p99 + exemplars
    lat = metrics["samples"].get("batch_worker.eval_latency_ms", {})
    p99 = lat.get("p99", 0.0)
    exemplars = lat.get("exemplars", [])
    if p99 > p99_budget_ms:
        violations.append(
            f"eval latency p99 {p99:.0f}ms > budget "
            f"{p99_budget_ms:.0f}ms"
        )

    submit_lat = swarm.latencies_ms if swarm is not None else []
    block = {
        "ok": not violations,
        "violations": violations,
        "nodes": nodes,
        "submitters": submitters,
        "death_nodes": death,
        "base_jobs": base_jobs,
        "ttl_s": ttl_s,
        "drained": drained,
        "affected_jobs": len(affected_jobs),
        "down_waves": waves,
        "storm_solves": solves,
        "storm_evals": counters.get("storm.evals", 0.0),
        "storm_fallbacks": counters.get("storm.fallbacks", 0.0),
        "death_detect_s": round(phase_s.get("death_detect", 0.0), 2),
        "heartbeats_ok": hb_ok,
        "heartbeats_failed": hb_fail,
        "heartbeat_success": round(hb_success, 6),
        "false_node_downs": len(false_downs),
        "sheds": sheds,
        "accepted": counters.get("overload.accepted", 0.0),
        "deferred": counters.get("overload.deferred", 0.0),
        "submit_sheds": swarm.sheds if swarm is not None else 0,
        "submit_errors": swarm.errors if swarm is not None else 0,
        "retry_after_honored": (
            swarm.retry_after_honored if swarm is not None else 0
        ),
        "submit_p50_ms": round(_percentile(submit_lat, 0.50), 1),
        "submit_p99_ms": round(_percentile(submit_lat, 0.99), 1),
        "eval_latency_p50_ms": round(lat.get("p50", 0.0), 1),
        "eval_latency_p99_ms": round(p99, 1),
        "p99_budget_ms": p99_budget_ms,
        "p99_exemplars": exemplars,
        "blocking_responses": (
            fanout.responses if fanout is not None else 0
        ),
        "overload_mode_final": server.overload.mode,
        "phase_s": {k: round(v, 2) for k, v in phase_s.items()},
        "elapsed_s": round(time.monotonic() - t_start, 2),
    }
    http.stop()
    server.stop()
    return block


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="swarm-scale overload + mass-death SLO smoke"
    )
    parser.add_argument("--nodes", type=int, default=2200)
    parser.add_argument("--submitters", type=int, default=1100)
    parser.add_argument("--death", type=int, default=500)
    parser.add_argument("--ttl", type=float, default=15.0)
    parser.add_argument("--drains", type=int, default=6)
    parser.add_argument("--base-jobs", type=int, default=None)
    parser.add_argument("--max-solves", type=int, default=2)
    parser.add_argument(
        "--p99-budget-ms", type=float, default=30000.0
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--json", default="", help="also write the block to this path"
    )
    args = parser.parse_args(argv)
    block = run_swarm(
        nodes=args.nodes,
        submitters=args.submitters,
        death=args.death,
        ttl_s=args.ttl,
        drains=args.drains,
        base_jobs=args.base_jobs,
        max_solves=args.max_solves,
        p99_budget_ms=args.p99_budget_ms,
        seed=args.seed,
    )
    out = {"swarm": block}
    print(json.dumps(out, indent=2, default=str))
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(out, fh, indent=2, default=str)
    if not block["ok"]:
        print("SWARM_SMOKE: FAIL", file=sys.stderr)
        return 2
    print(
        "SWARM_SMOKE: ok — %d nodes stormed, %d submitters, "
        "%d-node death in %.0f solve(s), hb %.3f%%, %d sheds"
        % (
            block["nodes"],
            block["submitters"],
            block["death_nodes"],
            block["storm_solves"],
            block["heartbeat_success"] * 100.0,
            int(block["sheds"]),
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
