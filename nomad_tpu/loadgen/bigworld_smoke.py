"""Composed-topology bigworld smoke/bench: fan-out followers × pods.

The million-node deployment shape this module drives end to end:

* N ``netagent`` server processes form one raft cluster over TCP
  (``--num-schedulers 0``: the leader brokers and commits but plans
  nothing itself);
* EVERY server also heads its own private ``jax.distributed`` world
  (per-server ``NOMAD_TPU_DIST_COORD`` / ``NOMAD_TPU_POD_PORT``) with
  one pod-peer process (``python -m nomad_tpu.parallel.pod``) as the
  second world member — whichever servers are followers run one
  fan-out batch worker (``NOMAD_TPU_FANOUT_MESH=1``) that plans
  through a live 2-process sharded mesh, streaming its launch
  sequence to the peer (``parallel/pod.py``);
* the world itself is synthesized by the ``seed_world`` raft command
  (``loadgen/bigworld.py``): the log carries a tiny spec, every
  replica expands it deterministically to the same bulk-registered
  nodes + array-backed allocation ballast.

Measured/asserted:

* ``placements_per_s`` — jobs driven over HTTP until fully placed;
* ``bytes_per_flush_per_host`` — each follower's
  ``mesh.bytes_per_flush`` gauge (the O(dirty rows) wire accounting);
* ``catchup_s`` — SIGKILL one follower (and its pod peer), restart
  both, time until the seeded sentinel node is queryable again;
* zero lost evals, both followers reporting a ``mesh.hosts`` pod of
  the expected width, at least one mesh launch, and (reduced scale)
  placement parity against an in-process single-server oracle that
  seeds the same spec and replays the same job sequence.  With
  ``NOMAD_TPU_POD_CHECK=1`` pinned in the child env, every mesh
  launch additionally round-trips a result digest from the pod peer,
  so a parity failure between head and peer aborts the drive itself.

Defaults are CI-sized (the ``tools/ci_check.sh`` gate); bench.py's
``bigworld`` block scales the same harness to the >=1M-node /
>=10M-alloc world.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request
from typing import Dict, List, Optional, Set, Tuple

from .bigworld import normalize_spec, world_datacenters

# settle slack applied on top of per-phase deadlines: first mesh
# launches block on XLA compiles (SYNC_COMPILE) on every world member
COMPILE_SLACK_S = 240.0


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http(port: int, path: str, payload=None, timeout: float = 30.0):
    url = f"http://127.0.0.1:{port}{path}"
    if payload is None:
        req = urllib.request.Request(url)
    else:
        req = urllib.request.Request(
            url,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _wait(predicate, what: str, timeout: float, poll: float = 0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = predicate()
        if out:
            return out
        time.sleep(poll)
    raise AssertionError(f"timeout waiting for {what} ({timeout}s)")


def _wait_leader(http_ports: List[int], timeout: float) -> str:
    """Every live server agrees on one leader address."""

    def probe():
        views = set()
        for port in http_ports:
            try:
                views.add(_http(port, "/v1/status/leader"))
            except Exception:  # noqa: BLE001 — booting
                return None
        if len(views) == 1 and None not in views:
            (leader,) = views
            return leader or None
        return None

    return _wait(probe, "agreed raft leader", timeout, poll=0.3)


def _log_has(path: str, needle: str) -> bool:
    try:
        with open(path, "r", errors="replace") as fh:
            return needle in fh.read()
    except OSError:
        return False


def _chain_job(spec: dict, i: int, count: int):
    from .. import mock

    job = mock.job(id=f"bw-chain-{i:04d}")
    job.type = "batch"
    job.datacenters = world_datacenters(spec)
    job.task_groups[0].count = count
    job.task_groups[0].tasks[0].resources.cpu = 500
    job.task_groups[0].tasks[0].resources.memory_mb = 1024
    return job


def _storm_job(spec: dict, i: int):
    from .. import mock

    # dispatch-family id shape: the broker's family detector
    # coalesces the contiguous prefix into one global storm solve
    job = mock.job(id=f"bwfam-000/dispatch-{i:04d}")
    job.type = "batch"
    job.datacenters = world_datacenters(spec)
    job.task_groups[0].count = 1
    job.task_groups[0].tasks[0].resources.cpu = 250
    job.task_groups[0].tasks[0].resources.memory_mb = 512
    return job


def _job_allocs(port: int, job_id: str) -> List[dict]:
    if "/" in job_id:
        # dispatch-style ids (bwfam-000/dispatch-NNNN) break the
        # /v1/job/<id>/... route; the flat list is cheap here — the
        # seeded 10M-alloc ballast is array-backed, never Allocation
        # objects, so store.allocs holds only the driven jobs
        allocs = [
            a
            for a in _http(port, "/v1/allocations")
            if a.get("job_id") == job_id
        ]
    else:
        allocs = _http(port, f"/v1/job/{job_id}/allocations")
    return [a for a in allocs if a.get("desired_status") == "run"]


def _placement_keys(
    allocs: List[dict], with_node: bool
) -> Set[Tuple]:
    out: Set[Tuple] = set()
    for a in allocs:
        key = (a["job_id"], a["task_group"], a["name"])
        if with_node:
            key += (a["node_id"],)
        out.add(key)
    return out


class _Fleet:
    """The spawned processes of one composed topology: per server
    index a netagent child and its pod-peer child, plus their log
    files (READY/SEEDED markers are polled from the logs — PIPEs
    would deadlock on chatty jax stderr)."""

    def __init__(self, log_dir: str, cwd: str) -> None:
        self.log_dir = log_dir
        self.cwd = cwd
        self.servers: Dict[int, subprocess.Popen] = {}
        self.peers: Dict[int, subprocess.Popen] = {}

    def log_path(self, kind: str, i: int, gen: int = 0) -> str:
        return os.path.join(self.log_dir, f"{kind}{i}.{gen}.log")

    def spawn(
        self, kind: str, i: int, cmd: List[str], env: dict,
        gen: int = 0,
    ) -> subprocess.Popen:
        out = open(self.log_path(kind, i, gen), "w")
        proc = subprocess.Popen(
            cmd, env=env, stdout=out, stderr=subprocess.STDOUT,
            cwd=self.cwd,
        )
        out.close()  # child holds the fd
        (self.servers if kind == "server" else self.peers)[i] = proc
        return proc

    def kill_pair(self, i: int) -> None:
        for group in (self.servers, self.peers):
            proc = group.get(i)
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)

    def shutdown(self) -> None:
        for proc in self.servers.values():
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in list(self.servers.values()) + list(
            self.peers.values()
        ):
            if proc.poll() is None:
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=10)

    def tails(self, limit: int = 3000) -> str:
        chunks = []
        for name in sorted(os.listdir(self.log_dir)):
            try:
                with open(
                    os.path.join(self.log_dir, name),
                    "r", errors="replace",
                ) as fh:
                    chunks.append(
                        f"--- {name} ---\n{fh.read()[-limit:]}"
                    )
            except OSError:
                pass
        return "\n".join(chunks)


def _child_env(
    repo_root: str,
    coord_port: int,
    pod_port: int,
    rank: int,
    procs: int,
    devices_per_proc: int,
) -> dict:
    from ..device_lock import scrub_accelerator_env

    env = scrub_accelerator_env()
    # hermetic world: the parent shell's knobs must not reshape the
    # gate — children see ONLY the pinned set below
    for key in [k for k in env if k.startswith("NOMAD_TPU_")]:
        del env[key]
    env.update(
        {
            "PYTHONPATH": repo_root
            + os.pathsep
            + env.get("PYTHONPATH", ""),
            "JAX_PLATFORMS": "cpu",
            "JAX_ENABLE_X64": "1",
            "XLA_FLAGS": (
                "--xla_force_host_platform_device_count="
                f"{devices_per_proc}"
            ),
            "NOMAD_TPU_DIST": "1",
            "NOMAD_TPU_DIST_COORD": f"127.0.0.1:{coord_port}",
            "NOMAD_TPU_DIST_PROCS": str(procs),
            "NOMAD_TPU_DIST_ID": str(rank),
            "NOMAD_TPU_MESH": "1",
            # only the follower fan-out worker may head the mesh/pod
            "NOMAD_TPU_FANOUT": "1",
            "NOMAD_TPU_FANOUT_WORKERS": "1",
            "NOMAD_TPU_FANOUT_MESH": "1",
            "NOMAD_TPU_POD_PORT": str(pod_port),
            # parity gate: every chain/storm launch round-trips a
            # result digest from the pod peer
            "NOMAD_TPU_POD_CHECK": "1",
            "NOMAD_TPU_STORM": "1",
            "NOMAD_TPU_STORM_MIN": "8",
            # determinism: no admission shaping, no overload ladder
            # (single-core compiles make eval age trip SHEDDING and
            # 429 the harness polls), compiles block inline
            "NOMAD_TPU_ADMIT": "0",
            "NOMAD_TPU_OVERLOAD": "0",
            "NOMAD_TPU_LATENCY_BUDGET_MS": "0",
            "NOMAD_TPU_SYNC_COMPILE": "1",
            "NOMAD_TPU_BROKER_WATCHDOG": "1",
        }
    )
    return env


def _oracle_placements(
    spec: dict, jobs: int, count: int, storm_jobs: int,
    timeout: float,
) -> Tuple[Set[Tuple], Set[Tuple]]:
    """Single-server in-process oracle: seed the SAME world spec,
    replay the SAME job sequence (sequential chain phase, then the
    storm family as one burst), return (chain keys with node ids,
    storm keys without)."""
    from ..server.cluster import TestCluster

    pinned = {
        "NOMAD_TPU_ADMIT": "0",
        "NOMAD_TPU_OVERLOAD": "0",
        "NOMAD_TPU_LATENCY_BUDGET_MS": "0",
        "NOMAD_TPU_STORM": "1",
        "NOMAD_TPU_STORM_MIN": "8",
    }
    saved = {k: os.environ.get(k) for k in pinned}
    os.environ.update(pinned)
    cluster = TestCluster(
        1, heartbeat_ttl=600.0, name_prefix="bworacle"
    )
    try:
        cluster.start()
        leader = cluster.wait_for_leader(timeout=30.0)
        # seed the store directly — the body of the seed_world FSM
        # command (bigworld.seed_world IS _apply_seed_world), without
        # the raft apply timeout that a minutes-long full-scale
        # expansion would trip
        from .bigworld import seed_world

        seed_world(leader.store, spec)

        def placed(job_id: str, want: int) -> bool:
            allocs = [
                a
                for a in leader.store.allocs_by_job(
                    "default", job_id
                )
                if not a.terminal_status()
            ]
            return len(allocs) >= want

        chain_ids = []
        for i in range(jobs):
            job = _chain_job(spec, i, count)
            chain_ids.append(job.id)
            leader.register_job(job)
            _wait(
                lambda j=job.id: placed(j, count),
                f"oracle placement of {job.id}",
                timeout,
            )
        storm_ids = []
        for i in range(storm_jobs):
            job = _storm_job(spec, i)
            storm_ids.append(job.id)
            leader.register_job(job)
        for job_id in storm_ids:
            _wait(
                lambda j=job_id: placed(j, 1),
                f"oracle placement of {job_id}",
                timeout,
            )
        leader.drain_to_idle(timeout=10.0)

        def keys(ids, with_node: bool) -> Set[Tuple]:
            out: Set[Tuple] = set()
            for job_id in ids:
                for a in leader.store.allocs_by_job(
                    "default", job_id
                ):
                    if a.terminal_status():
                        continue
                    key = (a.job_id, a.task_group, a.name)
                    if with_node:
                        key += (a.node_id,)
                    out.add(key)
            return out

        # name-level keys on BOTH phases: every node pick goes through
        # the placement shuffle (EvalContext's seeded rng), and worker
        # seeds differ across topologies — the repo's oracle-parity
        # contract (chaos_smoke, fanout_bench) is the placement SET
        # (job, group, name), while per-launch numeric identity is
        # covered by the POD_CHECK digest gate
        return keys(chain_ids, False), keys(storm_ids, False)
    finally:
        cluster.stop()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def run_bigworld(
    nodes: int = 256,
    allocs: int = 2048,
    jobs: int = 4,
    count: int = 2,
    storm_jobs: int = 8,
    servers: int = 3,
    procs_per_follower: int = 2,
    devices_per_proc: int = 2,
    dcs: int = 2,
    seed: int = 0,
    oracle: bool = True,
    timeout: float = 600.0,
) -> dict:
    """Drive the composed topology once; returns the bench block.
    Raises on any correctness-gate failure (lost evals, missing pod,
    parity mismatch, catch-up timeout) with the children's log tails
    attached."""
    import tempfile

    spec = normalize_spec(
        {
            "nodes": nodes,
            "allocs": allocs,
            "dcs": dcs,
            "seed": seed,
            "prefix": "bw",
        }
    )
    sentinel = f"{spec['prefix']}-{spec['nodes'] - 1:08d}"
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    rpc_ports = [_free_port() for _ in range(servers)]
    http_ports = [_free_port() for _ in range(servers)]
    coord_ports = [_free_port() for _ in range(servers)]
    pod_ports = [_free_port() for _ in range(servers)]
    addrs = [f"127.0.0.1:{p}" for p in rpc_ports]
    peers_arg = ",".join(addrs)
    # worlds seeded through raft can take minutes to expand at full
    # scale; elections stay calm because the FSM applies off the raft
    # driver thread, but forwarding retries need headroom
    seed_budget = max(60.0, spec["nodes"] / 4000.0)
    log_dir = tempfile.mkdtemp(prefix="bigworld_")
    fleet = _Fleet(log_dir, cwd=repo_root)

    def server_cmd(i: int, join_to: Optional[str]) -> List[str]:
        cmd = [
            sys.executable, "-m", "nomad_tpu.server.netagent",
            "--addr", addrs[i],
            "--peers", peers_arg,
            "--http-port", str(http_ports[i]),
            "--heartbeat-ttl", "600",
            "--election-timeout", "2.0",
            "--heartbeat-interval", "0.3",
            "--num-schedulers", "0",
        ]
        if join_to:
            cmd += ["--join", join_to]
        return cmd

    def spawn_pair(i: int, join_to: Optional[str],
                   seed_world: bool, gen: int = 0) -> None:
        cmd = server_cmd(i, join_to)
        if seed_world:
            cmd += ["--seed-world", json.dumps(spec)]
        fleet.spawn(
            "server", i, cmd,
            _child_env(
                repo_root, coord_ports[i], pod_ports[i], 0,
                procs_per_follower, devices_per_proc,
            ),
            gen=gen,
        )
        fleet.spawn(
            "peer", i,
            [
                sys.executable, "-m", "nomad_tpu.parallel.pod",
                "--head-port", str(pod_ports[i]),
                "--connect-timeout", str(timeout + seed_budget),
            ],
            _child_env(
                repo_root, coord_ports[i], pod_ports[i], 1,
                procs_per_follower, devices_per_proc,
            ),
            gen=gen,
        )

    try:
        t_boot = time.monotonic()
        for i in range(servers):
            spawn_pair(
                i, addrs[0] if i else None, seed_world=(i == 0)
            )
        for i in range(servers):
            _wait(
                lambda i=i: _log_has(
                    fleet.log_path("server", i), "READY "
                ),
                f"server {i} READY",
                timeout,
            )
        leader_addr = _wait_leader(http_ports, timeout)
        leader_i = addrs.index(leader_addr)
        follower_is = [i for i in range(servers) if i != leader_i]

        # -- seed + replicate the synthetic world -----------------------
        _wait(
            lambda: _log_has(
                fleet.log_path("server", 0), "SEEDED "
            ),
            "seed_world commit",
            seed_budget + timeout,
            poll=1.0,
        )

        def node_visible(port: int) -> bool:
            try:
                _http(port, f"/v1/node/{sentinel}")
                return True
            except Exception:  # noqa: BLE001 — 404 until applied
                return False

        for port in http_ports:
            _wait(
                lambda p=port: node_visible(p),
                "seeded world visible on every replica",
                seed_budget + timeout,
                poll=1.0,
            )
        seed_s = time.monotonic() - t_boot

        # -- drive: sequential chain phase, then the storm family -------
        drive_deadline = timeout + COMPILE_SLACK_S
        t_drive = time.monotonic()
        chain_ids = []
        for i in range(jobs):
            job = _chain_job(spec, i, count)
            chain_ids.append(job.id)
            from ..api.codec import job_to_dict

            out = _http(
                http_ports[leader_i], "/v1/jobs",
                {"Job": job_to_dict(job)},
            )
            assert out.get("EvalID"), out
            _wait(
                lambda j=job.id: len(
                    _job_allocs(http_ports[leader_i], j)
                )
                >= count,
                f"placement of {job.id}",
                drive_deadline,
            )
        storm_ids = []
        for i in range(storm_jobs):
            job = _storm_job(spec, i)
            storm_ids.append(job.id)
            from ..api.codec import job_to_dict

            out = _http(
                http_ports[leader_i], "/v1/jobs",
                {"Job": job_to_dict(job)},
            )
            assert out.get("EvalID"), out
        for job_id in storm_ids:
            _wait(
                lambda j=job_id: len(
                    _job_allocs(http_ports[leader_i], j)
                )
                >= 1,
                f"placement of {job_id}",
                drive_deadline,
            )
        drive_s = time.monotonic() - t_drive

        # -- zero lost + placement sets ---------------------------------
        chain_keys: Set[Tuple] = set()
        storm_keys: Set[Tuple] = set()
        lost = 0
        for job_id in chain_ids:
            allocs_j = _job_allocs(http_ports[leader_i], job_id)
            lost += max(0, count - len(allocs_j))
            chain_keys |= _placement_keys(allocs_j, with_node=False)
        for job_id in storm_ids:
            allocs_j = _job_allocs(http_ports[leader_i], job_id)
            lost += max(0, 1 - len(allocs_j))
            storm_keys |= _placement_keys(allocs_j, with_node=False)
        placements_total = len(chain_keys) + len(storm_keys)
        assert lost == 0, f"lost {lost} placements"

        # -- follower pod accounting ------------------------------------
        mesh_hosts: Dict[str, float] = {}
        mesh_launches: Dict[str, float] = {}
        bytes_per_flush: Dict[str, float] = {}
        for i in follower_is:
            dump = _http(http_ports[i], "/v1/metrics")
            gauges = dump.get("gauges", {})
            counters = dump.get("counters", {})
            mesh_hosts[addrs[i]] = gauges.get("mesh.hosts", 0.0)
            mesh_launches[addrs[i]] = counters.get(
                "mesh.launches", 0.0
            )
            bytes_per_flush[addrs[i]] = gauges.get(
                "mesh.bytes_per_flush", 0.0
            )
        assert all(
            h == float(procs_per_follower)
            for h in mesh_hosts.values()
        ), f"follower pods not fully formed: {mesh_hosts}"
        assert sum(mesh_launches.values()) >= 1, (
            f"no follower mesh launches: {mesh_launches}"
        )

        # -- oracle parity (reduced scale) ------------------------------
        parity = {"oracle": bool(oracle)}
        if oracle:
            oracle_chain, oracle_storm = _oracle_placements(
                spec, jobs, count, storm_jobs,
                timeout=drive_deadline,
            )
            parity["chain_match"] = chain_keys == oracle_chain
            parity["storm_match"] = storm_keys == oracle_storm
            assert parity["chain_match"], (
                "chain placements diverge from oracle: "
                f"only_fanout={sorted(chain_keys - oracle_chain)[:5]} "
                f"only_oracle={sorted(oracle_chain - chain_keys)[:5]}"
            )
            assert parity["storm_match"], (
                "storm placements diverge from oracle: "
                f"only_fanout={sorted(storm_keys - oracle_storm)[:5]} "
                f"only_oracle={sorted(oracle_storm - storm_keys)[:5]}"
            )

        # -- snapshot catch-up: kill + restart one follower -------------
        victim = follower_is[0]
        fleet.kill_pair(victim)
        t_restart = time.monotonic()
        spawn_pair(
            victim, addrs[leader_i], seed_world=False, gen=1
        )
        _wait(
            lambda: _log_has(
                fleet.log_path("server", victim, gen=1), "READY "
            ),
            "restarted follower READY",
            timeout,
        )
        restart_ready_s = time.monotonic() - t_restart
        _wait(
            lambda: node_visible(http_ports[victim]),
            "restarted follower world catch-up",
            seed_budget + timeout,
            poll=0.5,
        )
        catchup_s = time.monotonic() - t_restart
        # the re-established fleet must plan correctly (never against
        # a stale mirror): one more job, placed through the cluster
        post_job = _chain_job(spec, jobs, count)
        post_job.id = "bw-postrestart-0000"
        from ..api.codec import job_to_dict

        out = _http(
            http_ports[leader_i], "/v1/jobs",
            {"Job": job_to_dict(post_job)},
        )
        assert out.get("EvalID"), out
        _wait(
            lambda: len(
                _job_allocs(http_ports[leader_i], post_job.id)
            )
            >= count,
            "post-restart placement",
            drive_deadline,
        )
        # pod re-forms on the restarted follower (it is still a
        # follower: leadership never moved)
        def pod_reformed() -> bool:
            try:
                dump = _http(http_ports[victim], "/v1/metrics")
            except Exception:  # noqa: BLE001
                return False
            return dump.get("gauges", {}).get(
                "mesh.hosts", 0.0
            ) == float(procs_per_follower)

        _wait(
            pod_reformed, "restarted follower pod", drive_deadline,
            poll=0.5,
        )

        return {
            "world": {
                "nodes": spec["nodes"],
                "allocs": spec["allocs"],
                "dcs": spec["dcs"],
                "sentinel": sentinel,
            },
            "topology": {
                "servers": servers,
                "followers": len(follower_is),
                "procs_per_follower": procs_per_follower,
                "devices_per_proc": devices_per_proc,
                "global_devices_per_follower": (
                    procs_per_follower * devices_per_proc
                ),
            },
            "seed_s": round(seed_s, 2),
            "drive_s": round(drive_s, 2),
            "placements_total": placements_total,
            "placements_per_s": round(
                placements_total / max(drive_s, 1e-9), 2
            ),
            "bytes_per_flush_per_host": bytes_per_flush,
            "mesh_hosts": mesh_hosts,
            "mesh_launches": mesh_launches,
            "catchup": {
                "server": addrs[victim],
                "restart_ready_s": round(restart_ready_s, 2),
                "catchup_s": round(catchup_s, 2),
            },
            "lost": lost,
            "pod_check": True,
            "parity": parity,
            "log_dir": log_dir,
        }
    except BaseException as exc:
        raise RuntimeError(
            f"bigworld smoke failed ({exc!r}); logs in {log_dir}:\n"
            f"{fleet.tails()}"
        ) from exc
    finally:
        fleet.shutdown()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=(
            "composed fan-out × pod bigworld smoke "
            "(spawned netagent + pod-peer processes)"
        )
    )
    parser.add_argument("--nodes", type=int, default=256)
    parser.add_argument("--allocs", type=int, default=2048)
    parser.add_argument("--jobs", type=int, default=4)
    parser.add_argument("--count", type=int, default=2)
    parser.add_argument("--storm-jobs", type=int, default=8)
    parser.add_argument("--servers", type=int, default=3)
    parser.add_argument(
        "--procs-per-follower", type=int, default=2
    )
    parser.add_argument(
        "--devices-per-proc", type=int, default=2
    )
    parser.add_argument("--dcs", type=int, default=2)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--no-oracle", action="store_true",
        help="skip the in-process single-server parity oracle",
    )
    parser.add_argument("--timeout", type=float, default=600.0)
    args = parser.parse_args(argv)
    result = run_bigworld(
        nodes=args.nodes,
        allocs=args.allocs,
        jobs=args.jobs,
        count=args.count,
        storm_jobs=args.storm_jobs,
        servers=args.servers,
        procs_per_follower=args.procs_per_follower,
        devices_per_proc=args.devices_per_proc,
        dcs=args.dcs,
        seed=args.seed,
        oracle=not args.no_oracle,
        timeout=args.timeout,
    )
    print("BIGWORLD_JSON " + json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
