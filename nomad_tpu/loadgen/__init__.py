"""Swarm-scale load generation against the real HTTP API.

``swarm.py`` holds the reusable traffic generators (heartbeat storm,
submitter swarm, blocking-query fan-out, rolling drains);
``python -m nomad_tpu.loadgen.swarm_smoke`` composes them into the
SLO-gated overload/mass-death smoke exported as the bench ``swarm``
block.
"""
from .swarm import (  # noqa: F401
    BlockingFanout,
    HeartbeatStorm,
    HttpSession,
    SubmitterSwarm,
)
