"""Synthetic million-node world seeding (the ``bigworld`` arena).

The paper's scale claim (ROADMAP item 2) is a 1M-node / 10M-alloc
world; no real fleet that size fits a CPU-harness process if every
node costs a scheduling fingerprint (~1KB) and every allocation a
full ``Allocation`` dataclass (~3KB).  This module builds the world
the memory-lean way:

* **Lean nodes.** Real ``Node`` objects — every scheduler path that
  reads ``store.nodes`` keeps working — but all container fields
  (attributes, meta, drivers, host volumes, CSI plugins, reserved
  resources) and the per-shape ``NodeResources`` are SHARED template
  objects, and ``computed_class`` is computed once per (dc, shape)
  prototype instead of hashed per node.  A node costs its instance
  dict plus one id string.  Registration goes through
  ``StateStore.bulk_register_nodes`` (one index bump, sliced column
  writes, no per-row fingerprints).

* **Array-backed allocations.** The 10M allocations exist only as a
  usage ledger: per-alloc (row, cpu, mem, disk) arrays aggregated
  into the node table's usage columns via ``np.add.at`` and retained
  as per-row ballast (``StateStore.bulk_seed_usage``) so later real
  alloc writes recompute usage ON TOP of the seeded base.  They carry
  no ports, devices or job linkage — pure capacity pressure, which is
  exactly what the placement kernels read.

Expansion is a deterministic function of the spec (seeded numpy PCG,
no wall clock), so the ``seed_world`` FSM command replays identically
on every raft replica and the hermetic harness can seed follower
processes independently and still agree bit-for-bit.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..structs import (
    NODE_STATUS_READY,
    Node,
    NodeReservedResources,
    NodeResources,
    compute_node_class,
)

# (cpu MHz, memory MB, disk MB) machine shapes, cycled across rows
SHAPES: Tuple[Tuple[int, int, int], ...] = (
    (8_000, 16_384, 200_000),
    (16_000, 32_768, 400_000),
    (32_000, 65_536, 800_000),
)

# per-allocation asks, cycled by the seeded RNG; mean ~283 MHz so the
# default 10 allocs/node land well under the smallest shape
ALLOC_CPU = (100, 250, 500)
ALLOC_MEM = (128, 256, 512)
ALLOC_DISK = (0, 100, 300)


def normalize_spec(spec: Optional[dict]) -> dict:
    """Fill defaults and coerce types so every replica expands the
    SAME world from the command payload."""
    spec = dict(spec or {})
    return {
        "nodes": int(spec.get("nodes", 1_000_000)),
        "allocs": int(spec.get("allocs", 10_000_000)),
        "dcs": max(1, int(spec.get("dcs", 4))),
        "seed": int(spec.get("seed", 0)),
        "prefix": str(spec.get("prefix", "bw")),
    }


def world_datacenters(spec: Optional[dict]) -> List[str]:
    spec = normalize_spec(spec)
    return [f"{spec['prefix']}-dc{i}" for i in range(spec["dcs"])]


def build_nodes(spec: dict) -> List[Node]:
    """The lean-node expansion: one prototype per (dc, shape) carries
    the shared containers and the precomputed class hash."""
    n = spec["nodes"]
    dcs = world_datacenters(spec)
    prefix = spec["prefix"]
    attrs = {"kernel.name": "linux", "cpu.arch": "amd64"}
    meta: Dict[str, str] = {}
    drivers = {"exec": True}
    empty: Dict[str, object] = {}
    reserved = NodeReservedResources()
    protos = []
    for di, dc in enumerate(dcs):
        for si, (cpu, mem, disk) in enumerate(SHAPES):
            res = NodeResources(cpu=cpu, memory_mb=mem, disk_mb=disk)
            proto = Node(
                id=f"{prefix}-proto-{di}-{si}",
                datacenter=dc,
                node_class="bigworld",
                attributes=attrs,
                meta=meta,
                node_resources=res,
                reserved_resources=reserved,
                drivers=drivers,
                host_volumes=empty,  # type: ignore[arg-type]
                csi_node_plugins=empty,  # type: ignore[arg-type]
                status=NODE_STATUS_READY,
            )
            proto.computed_class = compute_node_class(proto)
            protos.append(proto)
    n_proto = len(protos)
    out: List[Node] = []
    for i in range(n):
        p = protos[i % n_proto]
        out.append(
            Node(
                id=f"{prefix}-{i:08d}",
                datacenter=p.datacenter,
                node_class=p.node_class,
                attributes=p.attributes,
                meta=p.meta,
                node_resources=p.node_resources,
                reserved_resources=p.reserved_resources,
                drivers=p.drivers,
                host_volumes=p.host_volumes,
                csi_node_plugins=p.csi_node_plugins,
                status=NODE_STATUS_READY,
                computed_class=p.computed_class,
            )
        )
    return out


def build_alloc_ledger(
    spec: dict,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """(node_idx, cpu, mem, disk) arrays — one entry per synthetic
    allocation, node indices relative to the spec's node block."""
    m = spec["allocs"]
    rng = np.random.default_rng(spec["seed"])
    node_idx = rng.integers(0, spec["nodes"], size=m, dtype=np.int64)
    pick = rng.integers(0, len(ALLOC_CPU), size=m)
    cpu = np.asarray(ALLOC_CPU, dtype=np.float64)[pick]
    mem = np.asarray(ALLOC_MEM, dtype=np.float64)[pick]
    disk = np.asarray(ALLOC_DISK, dtype=np.float64)[pick]
    return node_idx, cpu, mem, disk


def seed_world(store, spec: Optional[dict]) -> dict:
    """Expand ``spec`` into the store: bulk node registration plus the
    array-backed allocation ballast.  Deterministic — this is the body
    of the ``seed_world`` FSM command, applied on every replica."""
    spec = normalize_spec(spec)
    table = store.node_table
    start = table.n_rows
    nodes = build_nodes(spec)
    store.bulk_register_nodes(nodes)
    node_idx, cpu, mem, disk = build_alloc_ledger(spec)
    index = store.bulk_seed_usage(
        start + node_idx, cpu, mem, disk,
        alloc_count=spec["allocs"],
    )
    return {
        "index": index,
        "nodes": spec["nodes"],
        "allocs": spec["allocs"],
        "row_start": start,
        "datacenters": world_datacenters(spec),
    }
