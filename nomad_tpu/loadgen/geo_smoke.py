"""Geo-plane SLO smoke: two federated regions under swarm load.

The federation claim is not "regions can talk" — it is "a global job
lands in every region it names, region-local traffic never crosses
the WAN, and a whole region dying redirects its submitters without
losing a single accepted eval elsewhere".  This harness plays that
day against TWO real 3-server clusters (east/west) on one in-memory
transport, each fronted by real HTTP servers:

* **federation both ways**: a ``Multiregion`` job submitted via east
  and another via west, each fanned out with per-region count
  overrides; per-region placement must match a single-region oracle
  cluster fed the identical nodes and jobspec (placement parity — the
  geo plane may route, never re-schedule);
* **region-local reads stay local**: per-region heartbeat storms,
  submitter swarms and blocking fan-outs run over HTTP, after which
  ``federation.wan_reads`` must be ZERO on every server — only the
  explicit ``?region=`` escape hatch may cross the WAN (exercised and
  asserted to increment);
* **shed-redirect**: a flood against the east leader trips the
  overload ladder; sheds must carry the ``X-Nomad-Retry-Region`` hint
  and redirected submitters must land on west within the SLO;
* **region-kill drill**: all three east servers go dark at once
  (transport down + HTTP stopped, the SIGKILL shape); a fresh
  submitter wave pointed at the dead region must fail over via its
  cached retry-region hint within the SLO, and the surviving region
  ends with zero lost evals (no pending/blocked, empty failed queue,
  every accepted job fully placed);
* **rejoin**: the transport heals, east re-elects and re-advertises
  fresh HTTP addresses over gossip; a final multiregion job submitted
  via west must place in BOTH regions again.

SLO gates (exit 0 = all held, 2 = the JSON names the violation).

Usage::

    python -m nomad_tpu.loadgen.geo_smoke [--nodes-per-region N]
        [--flood-submitters S] [--redirect-slo SEC] [--json PATH]

The result is the bench ``federation`` block (bench.py embeds it
under ``BENCH_FEDERATION=1``).
"""
from __future__ import annotations

import argparse
import copy
import http.client
import json
import os
import random
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

# knob defaults for the smoke, applied BEFORE nomad_tpu imports so
# construction-time reads see them; explicit operator env wins
_SMOKE_ENV = {
    # the flood phase must ENGAGE overload (and thereby the
    # retry-region hint on sheds)
    "NOMAD_TPU_OVERLOAD": "1",
    "NOMAD_TPU_OVERLOAD_AGE_S": "10",
    # fast region-table refresh so rejoin detection is not the
    # long pole of the drill
    "NOMAD_TPU_REGION_PROBE_S": "0.2",
}


def _apply_env(flood_submitters: int) -> None:
    for key, value in _SMOKE_ENV.items():
        os.environ.setdefault(key, value)
    # depth threshold far below the flood so the east leader sheds
    # (and hints west) instead of queueing the burst
    os.environ.setdefault(
        "NOMAD_TPU_OVERLOAD_DEPTH",
        str(max(8, flood_submitters // 12)),
    )


def _percentile(vals: List[float], q: float) -> float:
    if not vals:
        return 0.0
    ordered = sorted(vals)
    idx = min(len(ordered) - 1, int(round(q * (len(ordered) - 1))))
    return ordered[idx]


def _sub_job_dict(job_id: str, datacenters: List[str]) -> dict:
    """Wire-form single-alloc service job (what a real client POSTs).

    Submitter jobs list BOTH datacenters so a shed-redirected or
    failed-over submission is placeable in whichever region accepts
    it — the redirect contract is "your work lands somewhere", not
    "your work lands where you first knocked".
    """
    return {
        "ID": job_id,
        "Name": job_id,
        "Type": "service",
        "Priority": 40,
        "Datacenters": list(datacenters),
        "TaskGroups": [
            {
                "Name": "g",
                "Count": 1,
                "Tasks": [
                    {
                        "Name": "t",
                        "Driver": "mock_driver",
                        "Config": {"run_for": -1},
                        "Resources": {"CPU": 50, "MemoryMB": 32},
                    }
                ],
            }
        ],
    }


def _mr_job_dict(
    job_id: str, east_count: int, west_count: int
) -> dict:
    """Wire-form Multiregion job: one jobspec, per-region count and
    datacenter overrides (the fan-out input)."""
    return {
        "ID": job_id,
        "Name": job_id,
        "Type": "service",
        "Priority": 50,
        "Datacenters": ["dc-east", "dc-west"],
        "Multiregion": {
            "Strategy": {"MaxParallel": 1},
            "Regions": [
                {
                    "Name": "east",
                    "Count": east_count,
                    "Datacenters": ["dc-east"],
                },
                {
                    "Name": "west",
                    "Count": west_count,
                    "Datacenters": ["dc-west"],
                },
            ],
        },
        "TaskGroups": [
            {
                "Name": "web",
                "Count": 1,
                "Tasks": [
                    {
                        "Name": "t",
                        "Driver": "mock_driver",
                        "Config": {"run_for": -1},
                        "Resources": {"CPU": 50, "MemoryMB": 32},
                    }
                ],
            }
        ],
    }


def _fully_placed(store, namespace, job_id, count) -> bool:
    live = [
        a
        for a in store.allocs_by_job(namespace, job_id)
        if not a.terminal_status()
    ]
    return len(live) == count


def _placements(store, namespace, job_id) -> List[Tuple[str, str]]:
    return sorted(
        (a.task_group, a.node_id)
        for a in store.allocs_by_job(namespace, job_id)
        if not a.terminal_status()
    )


def _drain_region(leader, timeout_s: float) -> bool:
    """Leader-side settle: broker idle AND no pending/blocked evals."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        pending = [
            ev
            for ev in list(leader.store.evals.values())
            if ev.status in ("pending", "blocked")
        ]
        if not pending and leader.drain_to_idle(timeout=2.0):
            return True
        time.sleep(0.2)
    return False


class RedirectSubmitter:
    """``n`` logical clients each registering one job against a
    primary region over HTTP, honoring 429 Retry-After AND following
    the shed's ``X-Nomad-Retry-Region-Addr`` hint to the suggested
    region.  Hint addresses learned from any shed are shared across
    the client population (the cached region table a real
    multi-region client keeps), so a client whose primary stops
    answering entirely — the region-kill drill — fails over to the
    last healthy region it heard about.

    ``redirect_latencies_s`` records, per redirected submission, the
    time from the first shed/failure to acceptance elsewhere — the
    redirect SLO input.
    """

    def __init__(
        self,
        primary_addr: str,
        n: int,
        make_job,
        threads: int = 12,
        max_attempts: int = 200,
        seed_hints: Optional[List[str]] = None,
        timeout_s: float = 10.0,
    ) -> None:
        self.primary = primary_addr
        self.n = n
        self._make_job = make_job
        self._timeout_s = timeout_s
        self._max_attempts = max_attempts
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.accepted = 0
        self.sheds = 0
        self.errors = 0
        self.redirects = 0
        self.failed: List[str] = []
        self.redirect_latencies_s: List[float] = []
        self.hint_regions: set = set()
        # learned region table: insertion-ordered so the freshest
        # hint wins on failover
        self._known: List[str] = [primary_addr]
        for hint in seed_hints or []:
            self._learn(hint)
        self._bad: set = set()
        threads = max(1, min(threads, n or 1))
        self._slices = [list(range(n))[i::threads] for i in range(threads)]
        self._threads = [
            threading.Thread(
                target=self._run, args=(i,),
                name=f"geo-submitter-{i}", daemon=True,
            )
            for i in range(threads)
        ]
        for t in self._threads:
            t.start()

    # -- shared region table ----------------------------------------

    def _learn(self, addr: str) -> None:
        with self._lock:
            if addr in self._known:
                self._known.remove(addr)
            self._known.append(addr)

    def _mark_bad(self, addr: str) -> None:
        with self._lock:
            self._bad.add(addr)

    def _failover(self, current: str) -> Optional[str]:
        with self._lock:
            live = [
                a
                for a in self._known
                if a not in self._bad and a != current
            ]
        return live[-1] if live else None

    # -- lifecycle ---------------------------------------------------

    def done(self) -> bool:
        return all(not t.is_alive() for t in self._threads)

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=timeout)

    # -- workers -----------------------------------------------------

    def _session(self, sessions: dict, addr: str):
        from .swarm import HttpSession

        if addr not in sessions:
            host, port = addr.rsplit(":", 1)
            sessions[addr] = HttpSession(
                host, int(port), timeout=self._timeout_s
            )
        return sessions[addr]

    def _run(self, idx: int) -> None:
        rng = random.Random(idx)
        sessions: dict = {}
        for sub_i in self._slices[idx]:
            if self._stop.is_set():
                break
            self._one(sub_i, rng, sessions)
        for sess in sessions.values():
            sess.close()

    def _one(self, sub_i: int, rng, sessions: dict) -> None:
        job = self._make_job(sub_i)
        addr = self.primary
        first_block: Optional[float] = None
        redirected = False
        for _ in range(self._max_attempts):
            if self._stop.is_set():
                break
            sess = self._session(sessions, addr)
            try:
                status, headers, _body = sess.request(
                    "POST", "/v1/jobs", {"Job": job}
                )
            except (http.client.HTTPException, OSError):
                # region gone dark: remember it, fail over to the
                # freshest hinted region
                self._mark_bad(addr)
                if first_block is None:
                    first_block = time.monotonic()
                nxt = self._failover(addr)
                if nxt is not None:
                    if nxt != self.primary:
                        redirected = True
                    addr = nxt
                time.sleep(0.05 + rng.random() * 0.1)
                continue
            if status == 200:
                with self._lock:
                    self.accepted += 1
                    if redirected and first_block is not None:
                        self.redirects += 1
                        self.redirect_latencies_s.append(
                            time.monotonic() - first_block
                        )
                return
            if status == 429:
                if first_block is None:
                    first_block = time.monotonic()
                hint_addr = headers.get(
                    "x-nomad-retry-region-addr", ""
                )
                hint_region = headers.get("x-nomad-retry-region", "")
                with self._lock:
                    self.sheds += 1
                    if hint_region:
                        self.hint_regions.add(hint_region)
                if hint_addr:
                    self._learn(hint_addr)
                with self._lock:
                    hint_ok = (
                        hint_addr
                        and hint_addr != addr
                        and hint_addr not in self._bad
                    )
                if hint_ok:
                    # take the hint: retry in the suggested region
                    addr = hint_addr
                    redirected = True
                    time.sleep(0.02 + rng.random() * 0.05)
                else:
                    try:
                        retry_after = float(
                            headers.get("retry-after", "0.25")
                        )
                    except ValueError:
                        retry_after = 0.25
                    time.sleep(
                        min(retry_after, 1.5)
                        * (0.5 + rng.random())
                    )
                continue
            # 5xx (leaderless window, proxy failure): brief backoff
            with self._lock:
                self.errors += 1
            if first_block is None:
                first_block = time.monotonic()
            time.sleep(0.2 + rng.random() * 0.2)
        else:
            with self._lock:
                self.failed.append(job["ID"])


def run_geo(
    nodes_per_region: int = 10,
    local_submitters: int = 24,
    flood_submitters: int = 96,
    kill_submitters: int = 24,
    redirect_slo_s: float = 20.0,
    seed: int = 0,
    settle_timeout_s: float = 240.0,
) -> Dict:
    """Run the geo scenario; returns the bench ``federation`` block
    (``ok`` = every SLO held, ``violations`` names what didn't)."""
    _apply_env(flood_submitters)

    from .. import mock
    from ..api import start_http_server
    from ..raft.transport import InmemTransport
    from ..server import Server
    from ..server.cluster import TestCluster
    from .swarm import BlockingFanout, HeartbeatStorm, SubmitterSwarm

    t_start = time.monotonic()
    violations: List[str] = []
    phase_s: Dict[str, float] = {}
    timings: Dict[str, float] = {}

    transport = InmemTransport()
    # one scheduler per server: the flood must outpace the consumer
    # side so the overload ladder engages organically (same seed as
    # the parity oracles — placement must be reproducible)
    clusters = {
        "east": TestCluster(
            3, transport=transport, region="east",
            name_prefix="east", heartbeat_ttl=600.0, seed=seed,
            num_schedulers=1,
        ),
        "west": TestCluster(
            3, transport=transport, region="west",
            name_prefix="west", heartbeat_ttl=600.0, seed=seed,
            num_schedulers=1,
        ),
    }
    https: Dict[str, list] = {"east": [], "west": []}
    oracles: List[Server] = []
    generators: list = []

    def _leader(name: str):
        return clusters[name].wait_for_leader(timeout=10.0)

    def _leader_http_addr(name: str) -> str:
        leader = _leader(name)
        for srv, http_srv in zip(
            clusters[name].servers, https[name]
        ):
            if srv is leader:
                return f"127.0.0.1:{http_srv.port}"
        raise AssertionError(f"no http server for {name} leader")

    try:
        # -- phase: boot — two regions, one WAN ----------------------
        t0 = time.monotonic()
        for cl in clusters.values():
            cl.start()
        # WAN join: east and west gossip into one member list
        clusters["west"].servers[0].join(
            clusters["east"].servers[0].addr
        )
        for name, cl in clusters.items():
            for srv in cl.servers:
                https[name].append(start_http_server(srv, port=0))
        leaders = {name: _leader(name) for name in clusters}

        # every server's region table must show both regions with
        # advertised HTTP addresses before traffic starts
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            views = [
                srv.federation.regions()
                for cl in clusters.values()
                for srv in cl.servers
            ]
            if all(
                view.get(r, {}).get("members", 0) == 3
                and view.get(r, {}).get("http")
                for view in views
                for r in ("east", "west")
            ):
                break
            time.sleep(0.1)
        else:
            violations.append(
                "region tables never converged with HTTP addresses"
            )

        # nodes: identical pristine copies are kept per region so the
        # single-region parity oracles schedule over the same world
        node_ids: Dict[str, List[str]] = {}
        pristine: Dict[str, list] = {}
        for name in ("east", "west"):
            node_ids[name], pristine[name] = [], []
            for _ in range(nodes_per_region):
                node = mock.node(datacenter=f"dc-{name}")
                pristine[name].append(copy.deepcopy(node))
                leaders[name].register_node(node)
                node_ids[name].append(node.id)
        phase_s["boot"] = time.monotonic() - t0

        # -- phase: federation both ways + placement parity ----------
        t0 = time.monotonic()
        from .swarm import HttpSession

        fanout_register_ms: List[float] = []
        mr_specs = {
            # submitted via EAST, overrides for both regions
            "geo-mr-east": ("east", _mr_job_dict("geo-mr-east", 2, 3)),
            # submitted via WEST (the other way around)
            "geo-mr-west": ("west", _mr_job_dict("geo-mr-west", 1, 2)),
        }
        for job_id, (via, job_dict) in mr_specs.items():
            host, port = _leader_http_addr(via).rsplit(":", 1)
            sess = HttpSession(host, int(port), timeout=30.0)
            t_reg = time.monotonic()
            status, _h, body = sess.request(
                "POST", "/v1/jobs", {"Job": job_dict}
            )
            fanout_register_ms.append(
                (time.monotonic() - t_reg) * 1000.0
            )
            sess.close()
            if status != 200:
                violations.append(
                    f"{job_id} register via {via} -> HTTP {status}: "
                    f"{body[:200]!r}"
                )
            for name in ("east", "west"):
                if not _drain_region(leaders[name], settle_timeout_s):
                    violations.append(
                        f"{name} did not settle after {job_id}"
                    )

        expected = {
            "geo-mr-east": {"east": 2, "west": 3},
            "geo-mr-west": {"east": 1, "west": 2},
        }
        for job_id, counts in expected.items():
            for name, count in counts.items():
                if not _fully_placed(
                    leaders[name].store, "default", job_id, count
                ):
                    violations.append(
                        f"{job_id} not fully placed in {name} "
                        f"(want {count})"
                    )

        # federation status endpoint aggregates every region's view
        host, port = _leader_http_addr("east").rsplit(":", 1)
        sess = HttpSession(host, int(port), timeout=30.0)
        status, _h, body = sess.request(
            "GET", "/v1/job/geo-mr-east/federation"
        )
        fed_status = json.loads(body) if status == 200 else {}
        sess.close()
        if status != 200:
            violations.append(
                f"/v1/job/geo-mr-east/federation -> HTTP {status}"
            )
        else:
            for name, count in expected["geo-mr-east"].items():
                region_view = fed_status.get("regions", {}).get(
                    name, {}
                )
                if not region_view.get("registered") or (
                    region_view.get("groups", {}).get("web") != count
                ):
                    violations.append(
                        f"federation status wrong for {name}: "
                        f"{region_view!r}"
                    )

        # parity: a single-region oracle fed the identical nodes and
        # jobspecs must produce the identical placement set — the geo
        # plane routes, it never re-schedules
        from ..api.codec import job_from_dict

        for name in ("east", "west"):
            oracle = Server(
                num_schedulers=1, heartbeat_ttl=600.0, seed=seed
            )
            # interpolate the multiregion overrides as this region
            oracle.region = name
            oracles.append(oracle)
            oracle.start()
            for node in pristine[name]:
                oracle.register_node(copy.deepcopy(node))
            # same jobs, same order as the cluster applied them
            for job_id, (_via, job_dict) in mr_specs.items():
                oracle.register_job(job_from_dict(dict(job_dict)))
                if not oracle.drain_to_idle(timeout=60.0):
                    violations.append(
                        f"{name} oracle did not settle on {job_id}"
                    )
            for job_id in mr_specs:
                got = _placements(
                    leaders[name].store, "default", job_id
                )
                want = _placements(oracle.store, "default", job_id)
                if got != want:
                    violations.append(
                        f"placement parity broken for {job_id} in "
                        f"{name}: cluster={got} oracle={want}"
                    )
        phase_s["federate"] = time.monotonic() - t0

        # -- phase: region-local swarm load, wan_reads must stay 0 ---
        t0 = time.monotonic()
        storms, swarms, fanouts = {}, {}, {}
        for name in ("east", "west"):
            host, port = _leader_http_addr(name).rsplit(":", 1)
            storms[name] = HeartbeatStorm(
                host, int(port), node_ids[name],
                period_s=2.0, threads=8,
            )
            dcs = [f"dc-{name}"]
            swarms[name] = SubmitterSwarm(
                host, int(port), local_submitters,
                make_job=lambda i, _n=name, _d=dcs: _sub_job_dict(
                    f"geo-local-{_n}-{i:04d}", _d
                ),
                threads=8,
            )
            fanouts[name] = BlockingFanout(host, int(port), threads=4)
            generators.extend(
                (storms[name], swarms[name], fanouts[name])
            )
        deadline = time.monotonic() + settle_timeout_s
        while time.monotonic() < deadline:
            if all(sw.done() for sw in swarms.values()):
                break
            time.sleep(0.25)
        for name, sw in swarms.items():
            if not sw.done():
                violations.append(f"{name} local swarm wedged")
            if sw.failed:
                violations.append(
                    f"{len(sw.failed)} {name} local submitters "
                    "never succeeded"
                )
        for gen in generators:
            gen.stop()
        for name in ("east", "west"):
            if not _drain_region(leaders[name], settle_timeout_s):
                violations.append(
                    f"{name} did not settle after local load"
                )

        # THE geo-plane read contract: all of the above was
        # region-local traffic — not one read crossed the WAN
        wan_reads_local = {
            srv.addr: srv.metrics.get_counter("federation.wan_reads")
            for cl in clusters.values()
            for srv in cl.servers
        }
        leaked = {a: c for a, c in wan_reads_local.items() if c > 0}
        if leaked:
            violations.append(
                f"region-local traffic crossed the WAN: {leaked}"
            )
        phase_s["local_load"] = time.monotonic() - t0

        # -- phase: the explicit ?region= escape hatch ---------------
        t0 = time.monotonic()
        host, port = _leader_http_addr("east").rsplit(":", 1)
        east_leader = leaders["east"]
        sess = HttpSession(host, int(port), timeout=30.0)
        forward_ms: List[float] = []
        before = east_leader.metrics.get_counter(
            "federation.wan_reads"
        )
        # proxied API read: east answers with west's node list
        status, headers, body = sess.request(
            "GET", "/v1/nodes?region=west"
        )
        if status != 200 or len(json.loads(body)) != len(
            node_ids["west"]
        ):
            violations.append(
                f"?region=west node proxy failed: HTTP {status}"
            )
        elif headers.get("x-nomad-proxied-region") != "west":
            violations.append(
                "proxied response missing X-Nomad-Proxied-Region"
            )
        # forwarded cluster read, timed (the bench forward latency)
        for _ in range(20):
            t_req = time.monotonic()
            status, _h, _b = sess.request(
                "GET", "/v1/cluster/metrics?region=west"
            )
            forward_ms.append((time.monotonic() - t_req) * 1000.0)
            if status != 200:
                violations.append(
                    f"/v1/cluster/metrics?region=west -> {status}"
                )
                break
        sess.close()
        after = east_leader.metrics.get_counter(
            "federation.wan_reads"
        )
        if after <= before:
            violations.append(
                "?region= escape hatch did not count wan_reads"
            )
        phase_s["escape_hatch"] = time.monotonic() - t0

        # -- phase: shed-redirect flood ------------------------------
        t0 = time.monotonic()
        flood = RedirectSubmitter(
            _leader_http_addr("east"),
            flood_submitters,
            make_job=lambda i: _sub_job_dict(
                f"geo-flood-{i:04d}", ["dc-east", "dc-west"]
            ),
            threads=16,
        )
        generators.append(flood)
        deadline = time.monotonic() + settle_timeout_s
        while time.monotonic() < deadline:
            if flood.done():
                break
            time.sleep(0.25)
        if not flood.done():
            flood.stop()
            violations.append("flood swarm wedged")
        if flood.failed:
            violations.append(
                f"{len(flood.failed)} flood submitters never "
                "succeeded"
            )
        if flood.sheds <= 0:
            violations.append(
                "flood never shed — overload (and the retry-region "
                "hint) was not exercised"
            )
        if "west" not in flood.hint_regions:
            violations.append(
                f"sheds never hinted west: {flood.hint_regions!r}"
            )
        if flood.redirects <= 0:
            violations.append("no submitter followed the hint")
        redirect_p99 = _percentile(flood.redirect_latencies_s, 0.99)
        if redirect_p99 > redirect_slo_s:
            violations.append(
                f"shed-redirect p99 {redirect_p99:.1f}s > SLO "
                f"{redirect_slo_s:.0f}s"
            )
        phase_s["flood"] = time.monotonic() - t0

        # -- phase: region-kill drill --------------------------------
        t0 = time.monotonic()
        for name in ("east", "west"):
            _drain_region(leaders[name], settle_timeout_s)
        east_primary = _leader_http_addr("east")
        t_kill = time.monotonic()
        # all three east servers at once: transport dark (raft,
        # gossip and federation RPC all dead) and HTTP refused — the
        # SIGKILL shape, no graceful leave
        for srv in clusters["east"].servers:
            transport.set_down(srv.addr)
        for http_srv in https["east"]:
            http_srv.stop()

        # a fresh submitter wave aimed at the DEAD region, carrying
        # only the region table the flood learned from shed hints
        kill_wave = RedirectSubmitter(
            east_primary,
            kill_submitters,
            make_job=lambda i: _sub_job_dict(
                f"geo-kill-{i:04d}", ["dc-east", "dc-west"]
            ),
            threads=8,
            seed_hints=list(flood._known[1:]),  # hints only
        )
        generators.append(kill_wave)

        # west notices the region death through gossip
        west_leader = leaders["west"]
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            view = west_leader.federation.regions()
            # a fully-dead region drops out of the table entirely
            if view.get("east", {}).get("members", 0) == 0:
                break
            time.sleep(0.1)
        else:
            violations.append("west never noticed the east kill")
        timings["kill_detect_s"] = time.monotonic() - t_kill

        deadline = time.monotonic() + settle_timeout_s
        while time.monotonic() < deadline:
            if kill_wave.done():
                break
            time.sleep(0.25)
        if not kill_wave.done():
            kill_wave.stop()
            violations.append("kill-wave swarm wedged")
        if kill_wave.failed:
            violations.append(
                f"{len(kill_wave.failed)} kill-wave submitters lost "
                "their work"
            )
        failover_p99 = _percentile(
            kill_wave.redirect_latencies_s, 0.99
        )
        timings["failover_p99_s"] = failover_p99
        if kill_wave.accepted and failover_p99 > redirect_slo_s:
            violations.append(
                f"kill failover p99 {failover_p99:.1f}s > SLO "
                f"{redirect_slo_s:.0f}s"
            )

        # zero lost evals in the surviving region
        if not _drain_region(west_leader, settle_timeout_s):
            violations.append("west did not settle after the kill")
        nonterminal = [
            ev.id
            for ev in list(west_leader.store.evals.values())
            if ev.status in ("pending", "blocked")
        ]
        if nonterminal:
            violations.append(
                f"{len(nonterminal)} non-terminal evals in west "
                "after the kill"
            )
        if west_leader.broker.failed():
            violations.append(
                f"{len(west_leader.broker.failed())} evals in west's "
                "failed queue after the kill"
            )
        west_missing = [
            job.id
            for job in west_leader.store.iter_jobs()
            if job.id.startswith(("geo-kill-", "geo-flood-"))
            and not _fully_placed(
                west_leader.store, "default", job.id, 1
            )
        ]
        if west_missing:
            violations.append(
                f"{len(west_missing)} accepted jobs not placed in "
                "west after the kill"
            )
        phase_s["region_kill"] = time.monotonic() - t0

        # -- phase: rejoin — east heals and re-federates -------------
        t0 = time.monotonic()
        for srv in clusters["east"].servers:
            transport.set_down(srv.addr, down=False)
        # fresh HTTP listeners, re-advertised over gossip
        https["east"] = [
            start_http_server(srv, port=0)
            for srv in clusters["east"].servers
        ]
        deadline = time.monotonic() + 60.0
        east_leader = None
        while time.monotonic() < deadline:
            try:
                east_leader = clusters["east"].wait_for_leader(
                    timeout=5.0
                )
                break
            except AssertionError:
                continue
        if east_leader is None:
            violations.append("east never re-elected after the heal")
        new_addrs = {
            f"127.0.0.1:{http_srv.port}" for http_srv in https["east"]
        }
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            view = west_leader.federation.regions()
            east_view = view.get("east", {})
            if east_view.get("members", 0) == 3 and new_addrs & set(
                east_view.get("http", [])
            ):
                break
            time.sleep(0.1)
        else:
            violations.append(
                "west never saw east rejoin with fresh HTTP "
                "addresses"
            )
        timings["rejoin_detect_s"] = time.monotonic() - t0

        # a multiregion job submitted via WEST must place in BOTH
        # regions again
        if east_leader is not None:
            leaders["east"] = east_leader
            host, port = _leader_http_addr("west").rsplit(":", 1)
            sess = HttpSession(host, int(port), timeout=30.0)
            t_reg = time.monotonic()
            status, _h, body = sess.request(
                "POST",
                "/v1/jobs",
                {"Job": _mr_job_dict("geo-rejoin", 1, 1)},
            )
            fanout_register_ms.append(
                (time.monotonic() - t_reg) * 1000.0
            )
            sess.close()
            if status != 200:
                violations.append(
                    f"rejoin register -> HTTP {status}: "
                    f"{body[:200]!r}"
                )
            for name in ("east", "west"):
                if not _drain_region(leaders[name], settle_timeout_s):
                    violations.append(
                        f"{name} did not settle after rejoin"
                    )
                if not _fully_placed(
                    leaders[name].store, "default", "geo-rejoin", 1
                ):
                    violations.append(
                        f"geo-rejoin not placed in {name}"
                    )
            # east drained: pre-kill accepted work survived the drill
            if not _drain_region(east_leader, settle_timeout_s):
                violations.append("east did not settle after rejoin")
        phase_s["rejoin"] = time.monotonic() - t0
    finally:
        for gen in generators:
            try:
                gen.stop()
            except Exception:
                pass
        for servers in https.values():
            for http_srv in servers:
                try:
                    http_srv.stop()
                except Exception:
                    pass
        transport.heal()
        for cl in clusters.values():
            try:
                cl.stop()
            except Exception:
                pass
        for oracle in oracles:
            try:
                oracle.stop()
            except Exception:
                pass

    def _sum_counter(name: str) -> float:
        return sum(
            srv.metrics.get_counter(name)
            for cl in clusters.values()
            for srv in cl.servers
        )

    block = {
        "ok": not violations,
        "violations": violations,
        "regions": 2,
        "servers_per_region": 3,
        "nodes_per_region": nodes_per_region,
        "local_submitters": local_submitters,
        "flood_submitters": flood_submitters,
        "kill_submitters": kill_submitters,
        "forwarded": _sum_counter("federation.forwarded"),
        "fanout_jobs": _sum_counter("federation.fanout_jobs"),
        "fanout_regions": _sum_counter("federation.fanout_regions"),
        "wan_reads": _sum_counter("federation.wan_reads"),
        "rpc_errors": _sum_counter("federation.rpc_errors"),
        "retries": _sum_counter("federation.retries"),
        "shed_redirects": _sum_counter("federation.shed_redirects"),
        "forward_p50_ms": round(_percentile(forward_ms, 0.50), 2),
        "forward_p99_ms": round(_percentile(forward_ms, 0.99), 2),
        "fanout_register_p50_ms": round(
            _percentile(fanout_register_ms, 0.50), 1
        ),
        "fanout_register_max_ms": round(
            max(fanout_register_ms or [0.0]), 1
        ),
        "flood_sheds": flood.sheds,
        "flood_redirects": flood.redirects,
        "redirect_p99_s": round(redirect_p99, 2),
        "kill_detect_s": round(timings.get("kill_detect_s", 0.0), 2),
        "failover_p99_s": round(
            timings.get("failover_p99_s", 0.0), 2
        ),
        "rejoin_detect_s": round(
            timings.get("rejoin_detect_s", 0.0), 2
        ),
        "redirect_slo_s": redirect_slo_s,
        "phase_s": {k: round(v, 2) for k, v in phase_s.items()},
        "elapsed_s": round(time.monotonic() - t_start, 2),
    }
    return block


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="two-region federation + region-kill SLO smoke"
    )
    parser.add_argument("--nodes-per-region", type=int, default=10)
    parser.add_argument("--local-submitters", type=int, default=24)
    parser.add_argument("--flood-submitters", type=int, default=96)
    parser.add_argument("--kill-submitters", type=int, default=24)
    parser.add_argument("--redirect-slo", type=float, default=20.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--json", default="", help="also write the block to this path"
    )
    args = parser.parse_args(argv)
    block = run_geo(
        nodes_per_region=args.nodes_per_region,
        local_submitters=args.local_submitters,
        flood_submitters=args.flood_submitters,
        kill_submitters=args.kill_submitters,
        redirect_slo_s=args.redirect_slo,
        seed=args.seed,
    )
    out = {"federation": block}
    print(json.dumps(out, indent=2, default=str))
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(out, fh, indent=2, default=str)
    if not block["ok"]:
        print("GEO_SMOKE: FAIL", file=sys.stderr)
        return 2
    print(
        "GEO_SMOKE: ok — 2 regions federated both ways, "
        "%d wan reads (escape hatch only), %d sheds redirected, "
        "kill detected in %.1fs, failover p99 %.1fs, rejoined"
        % (
            int(block["wan_reads"]),
            int(block["flood_sheds"]),
            block["kill_detect_s"],
            block["failover_p99_s"],
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
