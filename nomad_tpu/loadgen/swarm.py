"""Swarm traffic generators: millions-of-users-shaped load against
the REAL HTTP API (ROADMAP item 2's load-harness half).

Thousands of logical clients are multiplexed over a small worker-
thread pool, each worker holding ONE persistent HTTP/1.1 connection
(``HttpSession``) — the server's ThreadingHTTPServer then carries one
thread per generator worker, not one per logical client, so a 2k-node
heartbeat storm plus 1k submitters is a few dozen OS threads on each
side instead of thousands.

Every generator honors the server's backpressure contract: a 429
response is counted as a shed and retried after its ``Retry-After``
advice — the client half of the overload ladder.  Heartbeats are
never expected to shed (the server exempts the liveness plane), so
the storm counts any heartbeat failure against the SLO.
"""
from __future__ import annotations

import http.client
import json
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple


class HttpSession:
    """One persistent HTTP/1.1 connection with reconnect-on-error —
    the per-worker client half of the swarm."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.host = host
        self.port = port
        self.timeout = timeout
        self._conn: Optional[http.client.HTTPConnection] = None

    def _connection(self) -> http.client.HTTPConnection:
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout
            )
        return self._conn

    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None

    def request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        retry_conn: bool = True,
    ) -> Tuple[int, Dict[str, str], bytes]:
        """(status, lowercased headers, raw body); reconnects once on
        a torn connection (keep-alive churn, server restart)."""
        payload = (
            json.dumps(body).encode() if body is not None else None
        )
        headers = (
            {"Content-Type": "application/json"} if payload else {}
        )
        try:
            conn = self._connection()
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            return (
                resp.status,
                {k.lower(): v for k, v in resp.getheaders()},
                data,
            )
        except (http.client.HTTPException, OSError):
            self.close()
            if not retry_conn:
                raise
            return self.request(method, path, body, retry_conn=False)


class _Workers:
    """Shared start/stop shape for the generators below."""

    def __init__(self, name: str) -> None:
        self._name = name
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()

    def _spawn(self, n: int, target: Callable[[int], None]) -> None:
        for i in range(n):
            t = threading.Thread(
                target=target,
                args=(i,),
                name=f"{self._name}-{i}",
                daemon=True,
            )
            self._threads.append(t)
            t.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=timeout)


class HeartbeatStorm(_Workers):
    """Every node heartbeats each ``period_s`` over the real HTTP
    API; ``kill()`` silences a node set (the mass-death injection —
    from the server's view the rack just went dark).  Any non-200 on
    a live node counts against the heartbeat SLO."""

    def __init__(
        self,
        host: str,
        port: int,
        node_ids: Sequence[str],
        period_s: float,
        threads: int = 16,
    ) -> None:
        super().__init__("hb-storm")
        self.period_s = period_s
        self._ok_n = 0
        self._fail_n = 0
        self._dead: set = set()
        self._lock = threading.Lock()
        threads = max(1, min(threads, len(node_ids) or 1))
        slices = [list(node_ids[i::threads]) for i in range(threads)]
        self._host, self._port = host, port
        self._slices = slices
        self._spawn(threads, self._run)

    def kill(self, node_ids: Sequence[str]) -> None:
        with self._lock:
            self._dead.update(node_ids)

    def counts(self) -> Tuple[int, int]:
        with self._lock:
            return self._ok_n, self._fail_n

    def _run(self, idx: int) -> None:
        session = HttpSession(self._host, self._port)
        mine = self._slices[idx]
        if not mine:
            return
        # spread each worker's nodes over the period so heartbeats
        # arrive as a steady storm, not a thundering phase-locked herd
        gap = self.period_s / len(mine)
        while not self._stop.is_set():
            for node_id in mine:
                if self._stop.is_set():
                    return
                with self._lock:
                    dead = node_id in self._dead
                if not dead:
                    try:
                        status, _h, _b = session.request(
                            "POST", f"/v1/node/{node_id}/heartbeat",
                            body={},
                        )
                        ok = status == 200
                    except (http.client.HTTPException, OSError):
                        ok = False
                    # counted under the lock: += on an attribute is
                    # not atomic across 32 workers, and a lost bump
                    # would skew the hb-success SLO either way
                    with self._lock:
                        if ok:
                            self._ok_n += 1
                        else:
                            self._fail_n += 1
                self._stop.wait(gap)
        session.close()


class SubmitterSwarm(_Workers):
    """``n_submitters`` logical clients, each registering one job and
    retrying on 429 per the server's Retry-After advice (scaled by
    ``retry_scale`` so a smoke run doesn't spend minutes sleeping on
    honest backoff).  A submitter is DONE only when its job was
    accepted — sheds absorb the overload, they never lose work."""

    def __init__(
        self,
        host: str,
        port: int,
        n_submitters: int,
        make_job: Callable[[int], dict],
        threads: int = 24,
        # honor Retry-After at face value: a shed client that comes
        # back early just re-arrives inside the same overload (and
        # burns generator CPU the heartbeat plane needs)
        retry_scale: float = 1.0,
        max_attempts: int = 400,
    ) -> None:
        super().__init__("submitter")
        self.accepted = 0
        self.sheds = 0
        self.errors = 0
        self.failed: List[int] = []
        self.latencies_ms: List[float] = []
        self.retry_after_honored = 0
        self._lock = threading.Lock()
        self._host, self._port = host, port
        self._make_job = make_job
        self._retry_scale = retry_scale
        self._max_attempts = max_attempts
        threads = max(1, min(threads, n_submitters or 1))
        self._slices = [
            list(range(n_submitters))[i::threads]
            for i in range(threads)
        ]
        self._spawn(threads, self._run)

    def done(self) -> bool:
        return all(not t.is_alive() for t in self._threads)

    def _run(self, idx: int) -> None:
        session = HttpSession(self._host, self._port)
        rng = random.Random(idx)
        for sub_i in self._slices[idx]:
            if self._stop.is_set():
                break
            job = self._make_job(sub_i)
            t0 = time.monotonic()
            for _attempt in range(self._max_attempts):
                if self._stop.is_set():
                    break
                try:
                    status, headers, _body = session.request(
                        "POST", "/v1/jobs", body={"Job": job}
                    )
                except (http.client.HTTPException, OSError):
                    with self._lock:
                        self.errors += 1
                    time.sleep(0.05)
                    continue
                if status == 200:
                    with self._lock:
                        self.accepted += 1
                        self.latencies_ms.append(
                            (time.monotonic() - t0) * 1000.0
                        )
                    break
                if status == 429:
                    # the backpressure contract: honor Retry-After
                    # (scaled), with a little jitter so the shed herd
                    # doesn't re-arrive in one wave
                    advice = float(headers.get("retry-after", 1))
                    with self._lock:
                        self.sheds += 1
                        self.retry_after_honored += 1
                    time.sleep(
                        advice * self._retry_scale
                        * (0.5 + rng.random())
                    )
                    continue
                with self._lock:
                    self.errors += 1
                time.sleep(0.05)
            else:
                with self._lock:
                    self.failed.append(sub_i)
        session.close()


class BlockingFanout(_Workers):
    """Long-poll fan-out: each worker loops blocking queries with the
    last X-Nomad-Index, the read-heavy half of a million-user UI.
    Under SHEDDING the server answers immediately (degraded, counted
    server-side as overload.deferred) — the fan-out only counts hard
    failures."""

    def __init__(
        self,
        host: str,
        port: int,
        threads: int = 8,
        path: str = "/v1/nodes",
        wait_s: float = 2.0,
    ) -> None:
        super().__init__("blocking")
        self.responses = 0
        self.failures = 0
        self._lock = threading.Lock()
        self._host, self._port = host, port
        self._path = path
        self._wait_s = wait_s
        self._spawn(threads, self._run)

    def _run(self, idx: int) -> None:
        session = HttpSession(self._host, self._port)
        index = 1
        while not self._stop.is_set():
            try:
                status, headers, _body = session.request(
                    "GET",
                    f"{self._path}?index={index}"
                    f"&wait={self._wait_s}",
                )
                if status == 200:
                    with self._lock:
                        self.responses += 1
                    index = int(
                        headers.get("x-nomad-index", index) or index
                    )
                else:
                    with self._lock:
                        self.failures += 1
                    self._stop.wait(0.1)
            except (http.client.HTTPException, OSError):
                with self._lock:
                    self.failures += 1
                self._stop.wait(0.1)
        session.close()


def rolling_drain(
    host: str,
    port: int,
    node_ids: Sequence[str],
    pause_s: float = 0.2,
) -> int:
    """Drain the given nodes one at a time over the HTTP API (the
    operator's rolling-maintenance shape under load); returns the
    count drained successfully."""
    session = HttpSession(host, port)
    drained = 0
    for node_id in node_ids:
        try:
            status, _h, _b = session.request(
                "POST",
                f"/v1/node/{node_id}/drain",
                body={"DrainSpec": {"Deadline": int(600e9)}},
            )
            if status == 200:
                drained += 1
        except (http.client.HTTPException, OSError):
            pass
        time.sleep(pause_s)
    session.close()
    return drained
