"""Batched placement kernels: the (candidate-nodes x placements) score
matrix of BASELINE.json's north star.

`plan_picks` runs P sequential placements of one task group entirely on
device: a `lax.scan` where each step scores all nodes, emulates the
reference's rotating limited-walk selection (ops/score.py semantics),
picks the winner, and scatters the plan delta (proposed usage +
anti-affinity collision + optional distinct-hosts exclusion) before the
next step — the "stateful within an eval" scoring the reference gets from
`ProposedAllocs` (scheduler/context.go:120), expressed as in-kernel
updates instead of re-walking allocation lists.

`batch_plan_picks` vmaps that over E independent evaluations sharing the
node table — the optimistic-concurrency analog of the reference's
parallel scheduling workers (scheduler/scheduler.go:46): evals in a batch
do not see each other's placements; the serialized plan applier resolves
conflicts exactly as it does for the reference's workers.

Scope: the scan path covers binpack/spread fitness, job anti-affinity,
rescheduling penalties, node affinities and distinct_hosts.  Spread
stanzas change per-value use counts between picks and currently route
through the per-pick kernel in tpu_stack (exact, host-looped); an
in-kernel vocab-count carry is the planned extension.
"""
from __future__ import annotations

import functools
from typing import List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..device_lock import align_jax_platforms
from .score import MAX_SKIP, NO_NODE, SKIP_THRESHOLD, _pow10 as _pow10_f32

# every kernel user funnels through this module: make jax's config
# agree with an explicit JAX_PLATFORMS=cpu here so no code path can
# dial a tunnel sitecustomize's pinned backend from a "CPU-only"
# process (the config set at interpreter start beats the env var)
align_jax_platforms()


def pow2_bucket(n: int, floor: int = 1) -> int:
    """Next power of two >= n: launch-shape bucketing so jit traces
    stay cached across varying pick/row counts."""
    v = max(floor, 1)
    while v < n:
        v *= 2
    return v


class SpreadInputs(NamedTuple):
    """Percent-target spread state for the in-kernel carry (reference
    spread.go:163 boost; the use counts that shift between picks are a
    small per-value vector updated by one-hot scatter each step).

    Shapes: S spread stanzas x (V+1) value slots; slot V is the penalty
    slot (missing attribute, or value with no target and no implicit
    "*") scoring a flat -1.0.  Even-spread mode (spread.go:178) stays on
    the exact host path.

    The per-pick used count reproduces propertySet.GetCombinedUseMap
    (reference propertyset.go): used = max(0, existing + proposed -
    cleared'), where `existing0` counts the job's live allocs at the
    snapshot, `proposed` starts at `proposed0` — in-place/attribute
    updates enter plan.NodeAllocation before any select, so the
    reference counts those allocs BOTH as existing and as proposed —
    and accumulates in-kernel placements, `cleared` starts at
    `cleared0` (plan stops staged before the first pick) and grows as
    per-pick destructive evictions land, and cleared' applies the
    PopulateProposed quirk — a value with both proposed and cleared>1
    counts one fewer cleared."""

    codes: jnp.ndarray  # i32[S, C] value slot per node (V = penalty)
    desired: jnp.ndarray  # f[S, V+1] desired count per slot
    used0: jnp.ndarray  # f[S, V+1] existing (live) use at snapshot
    proposed0: jnp.ndarray  # f[S, V+1] plan placements staged pre-pick
    cleared0: jnp.ndarray  # f[S, V+1] pre-staged plan stops per slot
    weight: jnp.ndarray  # f[S] weight / sum(|weights|)
    active: jnp.ndarray  # bool[S] (padding rows are inert)
    # even-spread mode (no targets, reference spread.go:178): min/max
    # balance boost over the observed use map, UNWEIGHTED (the oracle
    # adds evenSpreadScoreBoost without the weight fraction)
    even: jnp.ndarray = None  # bool[S]
    # owning group slot per stanza (propertysets are GROUP-scoped —
    # propertyset.py:151 filters to one task group): pick k of group t
    # scores with and updates ONLY slots where group == t.  None (the
    # single-group trace) means every slot applies to every pick.
    group: jnp.ndarray = None  # i32[S]


class TGInputs(NamedTuple):
    """Per-pick task-group routing for multi-task-group evals.

    The sequential scheduler iterates every task group's placements
    within ONE eval (reference generic_sched.go:468 computePlacements:
    destructive updates then places, each carrying its own task
    group), with the stack's rotating walk offset persisting across
    groups and failure coalescing applying PER GROUP.  The kernel
    models that with per-pick routing: pick k selects group slot
    ``tg_idx[k]``'s feasibility/affinity/collision columns and its own
    ask/limit scalars, while the walk offset and usage columns stay a
    single carry.  Single-task-group callers normalize to T=1 with
    tg_idx==0 — the arithmetic is identical to the historical
    single-group kernel."""

    tg_idx: jnp.ndarray  # i32[P] group slot per pick
    feasible: jnp.ndarray  # bool[T, C] static feasibility per group
    affinity: jnp.ndarray  # f[T, C]
    coll0: jnp.ndarray  # i32[T, C] anti-affinity base per group
    ask_cpu: jnp.ndarray  # f[P] per-pick resource ask
    ask_mem: jnp.ndarray  # f[P]
    ask_disk: jnp.ndarray  # f[P]
    desired_count: jnp.ndarray  # i32[P] group count for anti score
    limit: jnp.ndarray  # i32[P] walk visit limit per pick


class PortInputs(NamedTuple):
    """Static (reserved) host-port occupancy for the chain.

    The reference's binpack skips a port-collided node WITHOUT
    consuming a walk-limit slot (rank.go network path `continue`) —
    identical to an infeasible node in the walk arithmetic, so the
    kernel folds collision into the per-pick feasibility mask.  The Q
    axis enumerates the distinct static ports asked across the batch;
    occupancy chains across evals like the usage columns (a placement
    with static ports blocks those ports for every later pick/eval).
    Port RELEASES (stops/evictions freeing an asked port) are gated to
    the sequential path host-side — modeling only occupation keeps the
    carry monotone and exact for everything admitted."""

    ask: jnp.ndarray  # bool[T, Q] port slots this group's ask needs
    used0: jnp.ndarray  # bool[Q, C] occupied at snapshot (node space)


class DeviceInputs(NamedTuple):
    """Device-capacity accounting for the chain (SURVEY §7.3:
    capacity-count masks on device, exact host-side assignment).

    The D axis enumerates the batch's distinct device-ask signatures
    (each = a set of matching device-group codes).  Free instance
    counts chain across evals like usage columns; a pick is feasible
    only where every asked signature has enough free instances, and
    the winner consumes its group's asked counts.  Pooled counting is
    exact because the host admits only batches whose signatures are
    identical-or-disjoint (overlapping-but-different matched sets gate
    to the sequential path), and instance releases (evictions freeing
    asked devices) cut the chain host-side — the carry is monotone."""

    ask: jnp.ndarray  # i32[T, D] instances needed per signature
    free0: jnp.ndarray  # i32[D, C] free instances at snapshot


def spread_contribution(
    onehot, desired_node, penalty_node, safe_desired,
    existing, prop, clr, weight, active, even, dtype,
):
    """Per-node spread score contribution for one pick — THE single
    implementation shared by the unsharded step and the sharded
    (shard_map) planner so the two can never drift (the parity
    contract between them is bit-identity).  All inputs are in the
    caller's node layout (permuted or shard-local); `existing/prop/
    clr` are the replicated (S, V+1) carries; `even` is None when no
    stanza uses even mode (skips tracing the min/max block).

    Reproduces GetCombinedUseMap incl. the PopulateProposed
    cleared-decrement quirk and spread.py's boost order (empty use
    map short-circuits BEFORE the missing-attribute penalty)."""
    clr_adj = clr - jnp.where((prop > 0) & (clr > 1), 1.0, 0.0)
    combined = jnp.maximum(0.0, existing + prop - clr_adj)
    used_node = jnp.einsum("scv,sv->sc", onehot, combined)
    frac = (desired_node - (used_node + 1.0)) / safe_desired
    pct_contrib = frac * weight[:, None]
    pct_full = jnp.where(
        penalty_node, jnp.asarray(-1.0, dtype), pct_contrib
    )
    if even is not None:
        V1 = combined.shape[-1]
        value_slot = jnp.arange(V1) < (V1 - 1)
        present = ((existing + prop) > 0) & value_slot
        has_map = present.any(axis=-1)
        big = jnp.asarray(jnp.inf, dtype)
        min_c = jnp.min(jnp.where(present, combined, big), axis=-1)
        max_c = jnp.max(jnp.where(present, combined, -big), axis=-1)
        min_b = min_c[:, None]
        max_b = max_c[:, None]
        safe_min = jnp.where(min_b > 0, min_b, 1.0)
        delta_boost = jnp.where(
            min_b == 0.0, -1.0, (min_b - used_node) / safe_min
        )
        even_val = jnp.where(
            used_node != min_b,
            delta_boost,
            jnp.where(
                min_b == max_b,
                -1.0,
                jnp.where(
                    min_b == 0.0, 1.0, (max_b - min_b) / safe_min
                ),
            ),
        )
        even_full = jnp.where(
            has_map[:, None],
            jnp.where(
                penalty_node, jnp.asarray(-1.0, dtype), even_val
            ),
            0.0,
        )
        contrib = jnp.where(even[:, None], even_full, pct_full)
    else:
        contrib = pct_full
    contrib = jnp.where(active[:, None], contrib, 0.0)
    return jnp.sum(contrib, axis=0)


class StepDeltas(NamedTuple):
    """Per-pick plan mutations for steady-state evals (leading axis E
    when chained).  The sequential path interleaves plan edits with
    selects inside computePlacements (generic_sched.go:468): a
    destructive update stops its previous alloc *just before* its
    replacement is scored, and each reschedule penalizes the nodes in
    its own previous alloc's history (generic_sched.go:642
    getSelectOptions).  These are those edits, expressed as in-kernel
    deltas applied at the top of pick k's scan step."""

    evict_rows: jnp.ndarray  # i32[P] node row stopped before pick k (-1 none)
    evict_cpu: jnp.ndarray  # f[P] signed usage delta (negative)
    evict_mem: jnp.ndarray  # f[P]
    evict_disk: jnp.ndarray  # f[P]
    evict_coll: jnp.ndarray  # i32[P] anti-affinity collision delta
    penalty_rows: jnp.ndarray  # i32[P, K] penalized node rows (-1 pad)


class PreDeltas(NamedTuple):
    """Per-eval pre-placement plan state (leading axis E when chained):
    usage freed by lost/stopped allocs and shifted by in-place updates,
    applied to the chained usage columns before the eval's first pick —
    the plan-eviction half of ProposedAllocs (context.go:120).  Rows are
    padded with row 0 / delta 0."""

    rows: jnp.ndarray  # i32[R]
    cpu: jnp.ndarray  # f[R] signed deltas
    mem: jnp.ndarray  # f[R]
    disk: jnp.ndarray  # f[R]


class BatchInputs(NamedTuple):
    """Per-eval inputs (leading axis E when vmapped); node columns are
    shared."""

    feasible: jnp.ndarray  # bool[C] static feasibility for this (job, tg)
    base_cpu_used: jnp.ndarray  # f[C] usage at snapshot
    base_mem_used: jnp.ndarray  # f[C]
    base_disk_used: jnp.ndarray  # f[C]
    base_collisions: jnp.ndarray  # i32[C] existing same-job+tg allocs
    penalty: jnp.ndarray  # bool[C]
    affinity_score: jnp.ndarray  # f[C]
    perm: jnp.ndarray  # i32[C] shuffled walk order
    ask_cpu: jnp.ndarray  # f scalar
    ask_mem: jnp.ndarray  # f scalar
    ask_disk: jnp.ndarray  # f scalar
    desired_count: jnp.ndarray  # i32
    limit: jnp.ndarray  # i32
    distinct_hosts: jnp.ndarray  # bool scalar


def _rotated_prefix(cs, c_off, total, in_wrap, is_tail):
    """Inclusive count of set entries at-or-before each position in
    *walk order*, from the inclusive permuted-order cumsum `cs`.

    Walk order is the permuted order rotated left by `offset` within
    the candidate region; `in_wrap` marks positions < offset (they walk
    after the pre-wrap segment), `is_tail` the padding region past
    n_candidates (never rotated, walks last, and carries no set
    entries)."""
    pre = jnp.where(in_wrap, cs + (total - c_off), cs - c_off)
    return jnp.where(is_tail, total, pre)


def _walk(s_p, f_p, offset, limit, n_candidates):
    """The reference's rotating limited-walk selection, evaluated
    entirely in permuted space (no per-step gathers — the rotation is
    closed-form prefix arithmetic; see ops/score.py for the walk
    semantics being emulated).  `s_p`/`f_p` are score/feasibility in
    permuted order.  Returns (win_pos, any_emitted, pulls) where
    win_pos indexes the permuted arrays."""
    n = s_p.shape[0]
    # int32 throughout: under x64 a default arange is int64, which
    # would promote `pulls` and break the int32 offset scan carry
    pos = jnp.arange(n, dtype=jnp.int32)
    is_tail = pos >= n_candidates
    in_wrap = pos < offset
    # walk position of each permuted index (tail walks last, in place)
    wp = jnp.where(
        is_tail, pos, jnp.mod(pos - offset + n_candidates, n_candidates)
    )

    def rot(b):
        # b has no support in the tail (every mask is ANDed with f_p),
        # so the full-array total equals the candidate-region total
        cs = jnp.cumsum(b.astype(jnp.int32))
        total = cs[-1]
        c_off = jnp.where(offset > 0, cs[offset - 1], 0)
        return (
            _rotated_prefix(cs, c_off, total, in_wrap, is_tail), total
        )

    bad = f_p & (s_p <= SKIP_THRESHOLD)
    bad_rank, _ = rot(bad)
    diverted = bad & (bad_rank <= MAX_SKIP)
    nd = f_p & ~diverted
    nd_incl, nd_count = rot(nd)
    div_incl, n_div = rot(diverted)
    div_rank = div_incl - 1
    # two-diverted replay reversal happens only when a non-diverted
    # emission preceded the replay: the replayed head then re-enters
    # the skip loop and is re-appended behind its sibling
    # (select.py next()).  With NO good nodes the source exhausts
    # inside the first skip loop and the tail _next_option returns
    # the diverted nodes in ORIGINAL order.
    div_order = jnp.where(
        (n_div == 2) & (nd_count > 0), 1 - div_rank, div_rank
    )
    emit_order = jnp.where(nd, nd_incl - 1, nd_count + div_order)
    emitted = f_p & (emit_order < limit)

    neg_inf = jnp.asarray(-jnp.inf, dtype=s_p.dtype)
    masked = jnp.where(emitted, s_p, neg_inf)
    best = jnp.max(masked)
    candidates = emitted & (masked == best)
    order_key = jnp.where(
        candidates, emit_order, jnp.asarray(2**31 - 1, jnp.int32)
    )
    win = jnp.argmin(order_key)
    any_emitted = jnp.any(emitted)

    limit_reached = nd_count >= limit
    big = jnp.asarray(2**31 - 1, jnp.int32)
    lth_wp = jnp.min(
        jnp.where(nd & (nd_incl == limit), wp, big)
    )
    pulls = jnp.where(limit_reached, lth_wp + 1, n_candidates)
    return win, any_emitted, pulls


def _run_picks(
    cpu_total,
    mem_total,
    disk_total,
    used0,  # (cpu_used, mem_used, disk_used) starting columns
    inp: BatchInputs,
    n_candidates,
    n_picks: int,
    spread_fit: bool,
    wanted=None,  # i32 scalar: picks actually desired (<= n_picks);
                  # surplus scan steps are inert so a batch can share one
                  # static scan length without phantom placements
    spread: "SpreadInputs" = None,
    deltas: "StepDeltas" = None,
    tg: "TGInputs" = None,
    port_ask=None,  # bool[T, Q] (PortInputs.ask)
    port_used=None,  # bool[Q, C] node-space occupancy at eval start
    dev_ask=None,  # i32[T, D] (DeviceInputs.ask)
    dev_free=None,  # i32[D, C] node-space free counts at eval start
    dev_aff=None,  # f[T, C] device-affinity score per node (static)
    dev_aff_on=None,  # bool[T] ask has device affinities (weight != 0)
    occ_extra=None,  # i32[C] distinct_hosts occupancy from job groups
                     # placing NOTHING this eval (their allocs are
                     # outside the T axis but still block the node)
    dh_tg=None,  # bool[T] GROUP-level distinct_hosts: block only on
                 # the picking group's own allocs (feasible.py
                 # _satisfies: job_collision AND task_collision)
):
    """Inner pick scan; returns (rows i32[P], final used columns).

    All per-pick state lives in PERMUTED space: every input column is
    gathered through `inp.perm` exactly once up front, and each scan
    step is purely elementwise + cumsum + reductions (the rotated walk
    is closed-form prefix arithmetic in `_walk`).  TPU gathers are the
    expensive op here — hoisting them out of the step turned a
    ~0.54 ms/eval·pick kernel into a bandwidth-bound one.

    Internally the scan runs in per-pick/per-group space (see
    TGInputs): single-task-group callers (``tg is None``) normalize to
    T=1 with every pick routed to slot 0 — numerically identical to
    the historical single-group kernel."""
    if wanted is None:
        wanted = jnp.asarray(n_picks, jnp.int32)
    dtype = cpu_total.dtype
    perm = inp.perm

    def take(col):
        return jnp.take(col, perm)

    if tg is None:
        tg = TGInputs(
            tg_idx=jnp.zeros(n_picks, jnp.int32),
            feasible=inp.feasible[None],
            affinity=inp.affinity_score[None],
            coll0=inp.base_collisions[None],
            ask_cpu=jnp.broadcast_to(inp.ask_cpu, (n_picks,)),
            ask_mem=jnp.broadcast_to(inp.ask_mem, (n_picks,)),
            ask_disk=jnp.broadcast_to(inp.ask_disk, (n_picks,)),
            desired_count=jnp.broadcast_to(
                inp.desired_count, (n_picks,)
            ),
            limit=jnp.broadcast_to(inp.limit, (n_picks,)),
        )
    T = tg.feasible.shape[0]

    cpu_total_p = take(cpu_total)
    mem_total_p = take(mem_total)
    disk_total_p = take(disk_total)
    feas_tp = jnp.take(tg.feasible, perm, axis=1)  # (T, C)
    penalty_p = take(inp.penalty)
    aff_tp = jnp.take(tg.affinity, perm, axis=1)  # (T, C)
    ports_on = port_ask is not None
    if ports_on:
        ports_p0 = jnp.take(port_used, perm, axis=1)  # (Q, C)
    devs_on = dev_ask is not None
    if devs_on:
        devs_p0 = jnp.take(dev_free, perm, axis=1)  # (D, C)
    if dev_aff is not None:
        dev_aff_p = jnp.take(dev_aff, perm, axis=1)  # (T, C)
    occ_extra_p = (
        jnp.take(occ_extra, perm) if occ_extra is not None else None
    )
    safe_cpu = jnp.where(cpu_total_p > 0, cpu_total_p, 1.0)
    safe_mem = jnp.where(mem_total_p > 0, mem_total_p, 1.0)

    if spread is not None:
        # small-vocab lookups as one-hot matmuls (MXU-friendly; avoids
        # per-step gathers): desired/penalty per node are static,
        # used-per-node recomputes from the (S, V+1) carries each step
        _S, V1 = spread.desired.shape
        codes_sp = jnp.take(spread.codes, perm, axis=1)  # (S, C)
        onehot_p = jax.nn.one_hot(codes_sp, V1, dtype=dtype)
        desired_node = jnp.einsum(
            "scv,sv->sc", onehot_p, spread.desired
        )
        penalty_node = codes_sp == (V1 - 1)
        safe_desired = jnp.where(desired_node != 0, desired_node, 1.0)
        spread_existing = spread.used0.astype(dtype)  # (S, V+1)

    def step(carry, pick_idx):
        cpu_used = carry["cpu"]
        mem_used = carry["mem"]
        disk_used = carry["disk"]
        collisions = carry["coll"]  # (T, C) per-group carry
        offset = carry["off"]
        dead = carry["dead"]  # (T,) per-group coalescing
        if spread is not None:
            spread_prop = carry["spread_prop"]
            spread_clr = carry["spread_clr"]
        t = tg.tg_idx[pick_idx]
        # once a pick fails, later picks for ITS task group are inert:
        # the sequential path coalesces subsequent placements per task
        # group after its first failure (generic_sched.go:482); other
        # groups' picks continue
        active = (pick_idx < wanted) & ~dead[t]
        penalty_vec = penalty_p
        app = jnp.asarray(False)
        if deltas is not None:
            erow = deltas.evict_rows[pick_idx]
            epos = jnp.argmax(perm == erow)
            app = active & (erow >= 0)
            zf = jnp.asarray(0.0, dtype)
            cpu_used = cpu_used.at[epos].add(
                jnp.where(app, deltas.evict_cpu[pick_idx], zf)
            )
            mem_used = mem_used.at[epos].add(
                jnp.where(app, deltas.evict_mem[pick_idx], zf)
            )
            disk_used = disk_used.at[epos].add(
                jnp.where(app, deltas.evict_disk[pick_idx], zf)
            )
            collisions = collisions.at[t, epos].add(
                jnp.where(app, deltas.evict_coll[pick_idx], 0)
            )
            prow = deltas.penalty_rows[pick_idx]  # (K,)
            penalty_vec = penalty_vec | jnp.any(
                perm[:, None] == prow[None, :], axis=1
            )
            if spread is not None:
                # the evicted alloc's value slot gains one cleared use
                # (its stop is staged into plan.node_update just before
                # this pick — propertyset counts it as cleared).  A
                # destructive eviction replaces an alloc of the PICKING
                # group, so group-scoped slots of other groups are
                # untouched
                evict_slot = spread.codes[:, jnp.maximum(erow, 0)]
                app_slot = jnp.asarray(app)
                if spread.group is not None:
                    app_slot = (app & (spread.group == t))[:, None]
                spread_clr = spread_clr + jnp.where(
                    app_slot,
                    jax.nn.one_hot(evict_slot, V1, dtype=dtype),
                    0.0,
                )
        ask_cpu_k = tg.ask_cpu[pick_idx]
        ask_mem_k = tg.ask_mem[pick_idx]
        ask_disk_k = tg.ask_disk[pick_idx]
        coll_t = collisions[t]  # this pick's group's collision row
        cpu_after = cpu_used + ask_cpu_k
        mem_after = mem_used + ask_mem_k
        disk_after = disk_used + ask_disk_k
        fit = (
            (cpu_after <= cpu_total_p)
            & (mem_after <= mem_total_p)
            & (disk_after <= disk_total_p)
        )
        # distinct_hosts (feasible.go:470 DistinctHostsIterator,
        # both scopes): the collision carries ARE the proposed-
        # allocs-per-node counts — live allocs at the snapshot, +1
        # per pick, -1 per staged destructive eviction.  JOB-level
        # scope blocks on any proposed job alloc: the summed carries
        # plus occ_extra (groups placing nothing this eval).
        # GROUP-level scope blocks only on the picking group's own
        # carry; multi-group jobs with ONLY group-level constraints
        # ship dh_tg and leave inp.distinct_hosts False.
        occupancy = collisions.sum(axis=0)
        if occ_extra_p is not None:
            occupancy = occupancy + occ_extra_p
        feasible = feas_tp[t] & fit & ~(
            inp.distinct_hosts & (occupancy > 0)
        )
        if dh_tg is not None:
            feasible = feasible & ~(dh_tg[t] & (coll_t > 0))
        if ports_on:
            # static-port collision: skipped WITHOUT consuming a
            # walk-limit slot (rank.go network path `continue`) —
            # exactly how the walk treats infeasible nodes
            ask_t_ports = port_ask[t]  # (Q,)
            ports_c = carry["ports"]
            collide = jnp.any(
                ports_c & ask_t_ports[:, None], axis=0
            )
            feasible = feasible & ~collide
        if devs_on:
            # device capacity: feasible only where every ASKED
            # signature still has enough free instances (the
            # DeviceChecker runs pre-binpack, so shortage is plain
            # infeasibility in the walk arithmetic).  Unasked slots
            # (ask 0) must not couple the pick to unrelated pools
            ask_t_dev = dev_ask[t]  # (D,)
            devs_c = carry["dev"]
            feasible = feasible & jnp.all(
                (ask_t_dev[:, None] == 0)
                | (devs_c >= ask_t_dev[:, None]),
                axis=0,
            )

        free_cpu = 1.0 - cpu_after / safe_cpu
        free_mem = 1.0 - mem_after / safe_mem
        # canonical f32-rounded exponential (structs/funcs.py _pow10)
        base = _pow10_f32(free_cpu, dtype) + _pow10_f32(free_mem, dtype)
        if spread_fit:
            fitness = jnp.clip(base - 2.0, 0.0, 18.0)
        else:
            fitness = jnp.clip(20.0 - base, 0.0, 18.0)
        score_sum = fitness / 18.0
        count = jnp.ones_like(score_sum)

        has_coll = coll_t > 0
        anti = jnp.where(
            has_coll,
            -(coll_t.astype(dtype) + 1.0)
            / tg.desired_count[pick_idx].astype(dtype),
            0.0,
        )
        score_sum = score_sum + anti
        count = count + has_coll.astype(dtype)
        score_sum = score_sum - penalty_vec.astype(dtype)
        count = count + penalty_vec.astype(dtype)
        aff_k = aff_tp[t]
        has_aff = aff_k != 0.0
        score_sum = score_sum + jnp.where(has_aff, aff_k, 0.0)
        count = count + has_aff.astype(dtype)
        if dev_aff is not None:
            # device-affinity match fraction (rank.go:460): appended
            # for EVERY scored node when the ask carries affinities
            # with non-zero total weight — even a 0.0 value enters
            # the mean, unlike the node-affinity component
            d_on = dev_aff_on[t]
            score_sum = score_sum + jnp.where(
                d_on, dev_aff_p[t], 0.0
            )
            count = count + d_on.astype(dtype)
        if spread is not None:
            # boost per stanza: ((desired - (used+1)) / desired) * w,
            # -1.0 on the penalty slot (spread.py next()); appended
            # to the score list only when the total is non-zero —
            # shared implementation with the sharded planner.  For
            # multi-group evals only the picking group's slots score
            # (group-scoped propertysets)
            slot_active = spread.active
            if spread.group is not None:
                slot_active = slot_active & (spread.group == t)
            spread_total = spread_contribution(
                onehot_p, desired_node, penalty_node, safe_desired,
                spread_existing, spread_prop, spread_clr,
                spread.weight, slot_active, spread.even, dtype,
            )
            has_spread = spread_total != 0.0
            score_sum = score_sum + spread_total
            count = count + has_spread.astype(dtype)
        final = score_sum / count

        win, any_emitted, step_pulls = _walk(
            final, feasible, offset, tg.limit[pick_idx], n_candidates
        )
        ok = active & any_emitted
        dead = dead.at[t].set(dead[t] | (active & ~any_emitted))
        row = jnp.where(ok, perm[win], NO_NODE)
        pulls = jnp.where(active, step_pulls, 0)
        safe_win = jnp.where(ok, win, 0)
        upd = lambda arr, delta: arr.at[safe_win].add(
            jnp.where(ok, delta, jnp.zeros_like(delta))
        )
        cpu_used = upd(cpu_used, ask_cpu_k)
        mem_used = upd(mem_used, ask_mem_k)
        disk_used = upd(disk_used, ask_disk_k)
        collisions = collisions.at[t, safe_win].add(
            jnp.where(ok, 1, 0)
        )
        offset = jnp.mod(offset + pulls, n_candidates)
        out = {
            "cpu": cpu_used,
            "mem": mem_used,
            "disk": disk_used,
            "coll": collisions,
            "off": offset,
            "dead": dead,
        }
        if ports_on:
            # the winner occupies its group's static ports for every
            # later pick (and, chained, every later eval)
            win_mask = ok & (
                jnp.arange(ports_c.shape[1]) == safe_win
            )
            out["ports"] = ports_c | (
                ask_t_ports[:, None] & win_mask[None, :]
            )
        if devs_on:
            out["dev"] = devs_c.at[:, safe_win].add(
                jnp.where(ok, -ask_t_dev, 0)
            )
        if spread is not None:
            # the placed node's value slot gains one proposed use per
            # stanza — of the PICKING group only, when group-scoped
            slot_ok = jnp.asarray(ok)
            if spread.group is not None:
                slot_ok = ok & (spread.group == t)
                slot_ok = slot_ok[:, None]
            out["spread_prop"] = spread_prop + jnp.where(
                slot_ok, onehot_p[:, safe_win, :], 0.0
            )
            out["spread_clr"] = spread_clr
        return out, (row, app, pulls)

    carry0 = {
        "cpu": take(used0[0]),
        "mem": take(used0[1]),
        "disk": take(used0[2]),
        "coll": jnp.take(tg.coll0, perm, axis=1),  # (T, C)
        "off": jnp.asarray(0, jnp.int32),
        "dead": jnp.zeros((T,), dtype=bool),
    }
    if ports_on:
        carry0["ports"] = ports_p0
    if devs_on:
        carry0["dev"] = devs_p0
    if spread is not None:
        carry0["spread_prop"] = spread.proposed0.astype(dtype)
        carry0["spread_clr"] = spread.cleared0.astype(dtype)
    _final, (rows, eapps, pulls) = jax.lax.scan(
        step, carry0, jnp.arange(n_picks, dtype=jnp.int32)
    )
    # node-space final usage for the chained (serially-equivalent)
    # variant: apply the P placement deltas onto the node-space bases
    ok_rows = rows != NO_NODE
    safe_rows = jnp.where(ok_rows, rows, 0)

    def back(base_col, ask):
        delta = jnp.where(
            ok_rows, jnp.broadcast_to(ask, rows.shape), 0.0
        ).astype(base_col.dtype)
        return base_col.at[safe_rows].add(delta)

    used_cpu = back(used0[0], tg.ask_cpu)
    used_mem = back(used0[1], tg.ask_mem)
    used_disk = back(used0[2], tg.ask_disk)
    if deltas is not None:
        # applied per-pick evictions also shift the chained columns
        safe_er = jnp.where(eapps, deltas.evict_rows, 0)

        def back_evict(col, dvals):
            d = jnp.where(eapps, dvals, 0.0).astype(col.dtype)
            return col.at[safe_er].add(d)

        used_cpu = back_evict(used_cpu, deltas.evict_cpu)
        used_mem = back_evict(used_mem, deltas.evict_mem)
        used_disk = back_evict(used_disk, deltas.evict_disk)
    if ports_on or devs_on:
        # node-space carries for the chain: every successful pick's
        # row gains its group's static ports / loses its group's
        # asked device instances
        onehot_rows = (
            safe_rows[:, None]
            == jnp.arange(used_cpu.shape[0])[None, :]
        ).astype(jnp.int32)  # (P, C)
        extras = {}
        if ports_on:
            ask_rows = port_ask[tg.tg_idx]  # (P, Q)
            hit = (ok_rows[:, None] & ask_rows).astype(jnp.int32)
            extras["ports"] = port_used | (
                jnp.einsum("pq,pc->qc", hit, onehot_rows) > 0
            )
        if devs_on:
            dev_rows = dev_ask[tg.tg_idx]  # (P, D)
            consumed = jnp.einsum(
                "pd,pc->dc",
                jnp.where(ok_rows[:, None], dev_rows, 0),
                onehot_rows,
            )
            extras["dev"] = dev_free - consumed
        return rows, (used_cpu, used_mem, used_disk), pulls, extras
    return rows, (used_cpu, used_mem, used_disk), pulls


@functools.partial(
    jax.jit, static_argnames=("n_picks", "spread_fit")
)
def plan_picks(
    cpu_total,
    mem_total,
    disk_total,
    inp: BatchInputs,
    n_candidates,
    n_picks: int,
    spread_fit: bool = False,
    spread: SpreadInputs = None,
    deltas: StepDeltas = None,
):
    """P sequential placements for one eval; returns rows i32[P]
    (NO_NODE when placement failed)."""
    rows, _used, _pulls = _run_picks(
        cpu_total,
        mem_total,
        disk_total,
        (inp.base_cpu_used, inp.base_mem_used, inp.base_disk_used),
        inp,
        n_candidates,
        n_picks,
        spread_fit,
        spread=spread,
        deltas=deltas,
    )
    return rows


@functools.partial(
    jax.jit, static_argnames=("n_picks", "spread_fit")
)
def plan_picks_full(
    cpu_total,
    mem_total,
    disk_total,
    inp: BatchInputs,
    n_candidates,
    n_picks: int,
    spread_fit: bool = False,
):
    """Like plan_picks but also returns per-pick pull counts so the
    caller can mirror the rotating offset (select.go source position).
    Starting rotation is folded into `inp.perm` by the caller.  Used by
    the TPUGenericStack look-ahead: one launch pre-computes the whole
    placement loop of a task group instead of one device round trip per
    placement (generic_sched.go:468 computePlacements).

    Returns ONE stacked i32[2, P] array ([rows; pulls]) so the host
    pays a single device->host sync — each fetch is a full round trip
    on tunneled accelerators."""
    rows, _used, pulls = _run_picks(
        cpu_total,
        mem_total,
        disk_total,
        (inp.base_cpu_used, inp.base_mem_used, inp.base_disk_used),
        inp,
        n_candidates,
        n_picks,
        spread_fit,
    )
    return jnp.stack([rows.astype(jnp.int32), pulls.astype(jnp.int32)])


@functools.partial(
    jax.jit, static_argnames=("n_picks", "spread_fit")
)
def chained_plan_picks(
    cpu_total,
    mem_total,
    disk_total,
    batch: BatchInputs,  # leading axis E
    n_candidates,  # i32[E]
    n_picks: int,
    spread_fit: bool = False,
    wanted=None,  # i32[E]: per-eval pick counts (<= n_picks)
    spread: SpreadInputs = None,  # leading axis E on every field
    deltas: StepDeltas = None,  # leading axis E on every field
    pre: PreDeltas = None,  # leading axis E on every field
):
    """E evals x P picks in ONE launch, *serially equivalent*: a
    lax.scan over the evals carries the proposed-usage columns forward,
    so eval k scores against the state left by evals 0..k-1 — exactly
    what the sequential worker loop produces when each plan commits
    before the next eval runs.  One device round trip amortizes over the
    whole batch (the point, on tunneled accelerators) while decisions
    stay bit-identical to serial execution.

    Steady-state evals additionally carry `pre` (usage freed by
    lost/stopped allocs + in-place update shifts, applied before the
    eval's first pick) and `deltas` (per-pick destructive-update
    evictions + reschedule penalty rows), so the chain reflects every
    plan mutation the sequential scheduler would commit — not just
    placements.

    Anti-affinity collision and distinct-hosts state reset per eval
    (they are per-job; the broker's JobID dedup guarantees no two evals
    in flight share a job).  Returns rows i32[E, P]."""
    E = batch.perm.shape[0]
    nc = jnp.broadcast_to(jnp.asarray(n_candidates, jnp.int32), (E,))
    if wanted is None:
        wanted = jnp.full((E,), n_picks, jnp.int32)

    used0 = (
        batch.base_cpu_used[0],
        batch.base_mem_used[0],
        batch.base_disk_used[0],
    )

    def eval_step(used, xs):
        b, n, w, s, d, p = xs
        if p is not None:
            used = (
                used[0].at[p.rows].add(p.cpu.astype(used[0].dtype)),
                used[1].at[p.rows].add(p.mem.astype(used[1].dtype)),
                used[2].at[p.rows].add(p.disk.astype(used[2].dtype)),
            )
        rows, used_next, _pulls = _run_picks(
            cpu_total, mem_total, disk_total, used, b, n,
            n_picks, spread_fit, wanted=w, spread=s, deltas=d,
        )
        return used_next, rows

    # xs entries that are None are threaded as static Nones via a
    # wrapper (lax.scan xs must be arrays): build per-variant closures
    def make_xs():
        parts = [batch, nc, wanted]
        pattern = []
        for x in (spread, deltas, pre):
            pattern.append(x is not None)
            if x is not None:
                parts.append(x)
        return tuple(parts), pattern

    xs_arrays, pattern = make_xs()

    def eval_step_packed(used, xs):
        it = iter(xs[3:])
        s = next(it) if pattern[0] else None
        d = next(it) if pattern[1] else None
        p = next(it) if pattern[2] else None
        return eval_step(used, (xs[0], xs[1], xs[2], s, d, p))

    _final, rows = jax.lax.scan(eval_step_packed, used0, xs_arrays)
    return rows


class ChainInputs(NamedTuple):
    """Per-eval inputs for the production chained launch (leading axis
    E).  Unlike BatchInputs this carries NO copies of the shared node
    columns: the snapshot usage chains through the scan carry and the
    totals are closure inputs, so host assembly ships only what actually
    differs per eval (~5x less host->device traffic at E=64).

    The group axis T (usually 1) and the per-pick routing fields carry
    multi-task-group evals: pick k uses group slot ``tg_idx[:, k]``'s
    feasibility row and its own ask/count/limit scalars, mirroring
    computePlacements' per-group iteration within one eval (reference
    generic_sched.go:468)."""

    feasible: jnp.ndarray  # bool[E, T, C]
    perm: jnp.ndarray  # i32[E, C]
    ask_cpu: jnp.ndarray  # f[E, P]
    ask_mem: jnp.ndarray  # f[E, P]
    ask_disk: jnp.ndarray  # f[E, P]
    desired_count: jnp.ndarray  # i32[E, P]
    limit: jnp.ndarray  # i32[E, P]
    distinct_hosts: jnp.ndarray  # bool[E]
    tg_idx: jnp.ndarray  # i32[E, P]


def chained_plan_picks_cols(
    cpu_total,
    mem_total,
    disk_total,
    used0_cpu,  # f[C] snapshot usage (shared; the chain carries deltas)
    used0_mem,
    used0_disk,
    batch: ChainInputs,
    n_candidates,  # i32[E]
    n_picks: int,
    spread_fit: bool = False,
    wanted=None,  # i32[E]
    coll0=None,  # i32[E, T, C] anti-affinity base (None = zeros)
    affinity=None,  # f[E, T, C] (None = zeros)
    spread: SpreadInputs = None,  # leading axis E
    deltas: StepDeltas = None,  # leading axis E
    pre: PreDeltas = None,  # leading axis E
    port_ask=None,  # bool[E, T, Q] static-port slots per group
    port_used0=None,  # bool[Q, C] occupancy at the chain snapshot
    dev_ask=None,  # i32[E, T, D] device instances asked per group
    dev_free0=None,  # i32[D, C] free instances at the chain snapshot
    dev_aff=None,  # f[E, T, C] device-affinity score per node
    dev_aff_on=None,  # bool[E, T]
    occ0=None,  # i32[E, C] pickless-group distinct_hosts occupancy
    dh_tg=None,  # bool[E, T] group-level distinct_hosts flags
    return_carry: bool = False,
):
    """Serially-equivalent chained planner over shared node columns —
    the BatchWorker's production launch.  Semantics identical to
    `chained_plan_picks`; only the input layout differs.

    With ``return_carry=True`` the final scan carry — the chained
    (cpu, mem, disk) usage columns plus the port-occupancy and
    device-free carries (None when absent) — is returned as a third
    output.  Splitting one E-eval chain into consecutive launches
    whose carry-out feeds the next launch's ``used0_*``/``port_used0``/
    ``dev_free0`` is bit-identical to the single launch (a lax.scan cut
    at an eval boundary), which is what the BatchWorker's pipelined
    prescore relies on: chunk N+1 dispatches against chunk N's
    device-resident carry while the host replays chunk N-1."""
    E = batch.perm.shape[0]
    C = cpu_total.shape[0]
    T = batch.feasible.shape[1]
    nc = jnp.broadcast_to(jnp.asarray(n_candidates, jnp.int32), (E,))
    if wanted is None:
        wanted = jnp.full((E,), n_picks, jnp.int32)
    zeros_ti = jnp.zeros((T, C), jnp.int32)
    zeros_b = jnp.zeros(C, dtype=bool)
    zeros_tf = jnp.zeros((T, C), cpu_total.dtype)
    ports_on = port_ask is not None
    devs_on = dev_ask is not None

    parts = [batch, nc, wanted]
    pattern = []
    dev_aff_pair = (
        (dev_aff, dev_aff_on) if dev_aff is not None else None
    )
    for x in (coll0, affinity, spread, deltas, pre, port_ask,
              dev_ask, dev_aff_pair, occ0, dh_tg):
        pattern.append(x is not None)
        if x is not None:
            parts.append(x)

    def eval_step(carry, xs):
        used, ports, devs = carry
        it = iter(xs[3:])
        b = xs[0]
        coll = next(it) if pattern[0] else zeros_ti
        aff = next(it) if pattern[1] else zeros_tf
        s = next(it) if pattern[2] else None
        d = next(it) if pattern[3] else None
        p = next(it) if pattern[4] else None
        pa = next(it) if pattern[5] else None
        da = next(it) if pattern[6] else None
        daff, daff_on = (
            next(it) if pattern[7] else (None, None)
        )
        oc = next(it) if pattern[8] else None
        dhg = next(it) if pattern[9] else None
        if p is not None:
            used = (
                used[0].at[p.rows].add(p.cpu.astype(used[0].dtype)),
                used[1].at[p.rows].add(p.mem.astype(used[1].dtype)),
                used[2].at[p.rows].add(p.disk.astype(used[2].dtype)),
            )
        tg_in = TGInputs(
            tg_idx=b.tg_idx,
            feasible=b.feasible,
            affinity=aff,
            coll0=coll,
            ask_cpu=b.ask_cpu,
            ask_mem=b.ask_mem,
            ask_disk=b.ask_disk,
            desired_count=b.desired_count,
            limit=b.limit,
        )
        # the BatchInputs carrier only supplies perm/penalty/
        # distinct_hosts here — group-routed fields ride in tg_in
        inp = BatchInputs(
            feasible=b.feasible[0],
            base_cpu_used=used[0],
            base_mem_used=used[1],
            base_disk_used=used[2],
            base_collisions=coll[0],
            penalty=zeros_b,
            affinity_score=aff[0],
            perm=b.perm,
            ask_cpu=b.ask_cpu[0],
            ask_mem=b.ask_mem[0],
            ask_disk=b.ask_disk[0],
            desired_count=b.desired_count[0],
            limit=b.limit[0],
            distinct_hosts=b.distinct_hosts,
        )
        if ports_on or devs_on:
            rows, used_next, pulls, extras = _run_picks(
                cpu_total, mem_total, disk_total, used, inp, xs[1],
                n_picks, spread_fit, wanted=xs[2], spread=s,
                deltas=d, tg=tg_in, port_ask=pa, port_used=ports,
                dev_ask=da, dev_free=devs, dev_aff=daff,
                dev_aff_on=daff_on, occ_extra=oc, dh_tg=dhg,
            )
            return (
                used_next,
                extras.get("ports"),
                extras.get("dev"),
            ), (rows, pulls)
        rows, used_next, pulls = _run_picks(
            cpu_total, mem_total, disk_total, used, inp, xs[1],
            n_picks, spread_fit, wanted=xs[2], spread=s, deltas=d,
            tg=tg_in, dev_aff=daff, dev_aff_on=daff_on,
            occ_extra=oc, dh_tg=dhg,
        )
        return (used_next, None, None), (rows, pulls)

    used0 = (used0_cpu, used0_mem, used0_disk)
    carry0 = (used0, port_used0, dev_free0)
    final, (rows, pulls) = jax.lax.scan(
        eval_step, carry0, tuple(parts)
    )
    # pulls[E, P]: source-iterator consumption per pick — the host
    # reconstructs the sequential walk offset at any pick from the
    # running sum (preemption-retry passthrough seeds the oracle's
    # StaticIterator offset with it)
    if return_carry:
        return rows, pulls, final
    return rows, pulls


chained_plan_picks_cols = jax.jit(
    chained_plan_picks_cols,
    static_argnames=("n_picks", "spread_fit", "return_carry"),
)

_chained_cols_donated = None


def chained_plan_picks_cols_donated():
    """jit variant of `chained_plan_picks_cols` that donates the
    chain-carry buffers (usage columns + port/device occupancy) so
    back-to-back pipelined launches reuse device memory instead of
    holding every in-flight chunk's carry live.  Created lazily: the
    caller (BatchWorker) only selects it on non-CPU backends, where
    donation is honored, and only when the inputs are the previous
    launch's carry-out (never the persistent usage-column cache, which
    must survive the launch)."""
    global _chained_cols_donated
    if _chained_cols_donated is None:
        fn = jax.jit(
            chained_plan_picks_cols.__wrapped__,
            static_argnames=("n_picks", "spread_fit", "return_carry"),
            donate_argnames=(
                "used0_cpu",
                "used0_mem",
                "used0_disk",
                "port_used0",
                "dev_free0",
            ),
        )
        # distinct name: the cold-compile shield keys signatures by
        # fn name, and the donated executable compiles separately
        fn.__name__ = "chained_plan_picks_cols_donated"
        _chained_cols_donated = fn
    return _chained_cols_donated


@jax.jit
def patch_rows(col, idx, vals):
    """Scatter-patch dirty rows into a persistent device column:
    ``col[idx] = vals`` with out-of-bounds indices DROPPED (padding
    slots use idx == C; negative indices would wrap).  The delta-sync
    primitive for the BatchWorker's device-resident usage mirror."""
    return col.at[idx].set(vals, mode="drop")


_patch_rows_donated = None


def patch_rows_donated():
    """jit variant of `patch_rows` that donates the stale mirror
    column: it is replaced in the caller's cache by the patched
    output, so the old buffer is device memory the scatter can write
    in place — with the chained-launch carry donation this makes the
    steady-state sync path allocate nothing net on device.  (The
    idx/vals staging uploads are NOT donated: their [width] shapes
    can never alias the [C] output, so XLA could not honor it and
    jax would warn on every width bucket.)  The caller
    (BatchWorker._device_columns_locked) only selects this variant on
    non-CPU backends, and only while no abandoned in-flight launch or
    background shield compile could still be reading the column being
    donated (it falls back to the copying `patch_rows` — and a full
    re-upload — whenever that cannot be proven)."""
    global _patch_rows_donated
    if _patch_rows_donated is None:
        fn = jax.jit(
            patch_rows.__wrapped__, donate_argnums=(0,)
        )
        fn.__name__ = "patch_rows_donated"
        _patch_rows_donated = fn
    return _patch_rows_donated


_patch_rows_sharded_cache: dict = {}


def patch_rows_sharded(mesh, donate: bool = False):
    """Per-shard scatter-patch for a ``NamedSharding(P("nodes"))``
    mirror column — the delta-sync primitive for the BatchWorker's
    SHARDED device-resident usage mirror.  Each device receives the
    replicated (idx, vals) staging buffers (O(dirty rows) bytes
    host->device, total) and scatters only the rows that land in its
    own node shard: one local scatter per shard, zero cross-shard
    traffic.  Padding slots use ``idx == C`` (out of this shard's
    range on every shard) and are dropped, exactly like `patch_rows`.

    ``donate=True`` donates the stale column like `patch_rows_donated`
    — the caller replaces it in its cache with the patched output, so
    the scatter writes device memory in place.  The same exclusivity
    gating applies: the caller must prove no abandoned in-flight
    launch or background shield compile could still be reading the
    buffer (BatchWorker falls back to the copying variant — and a full
    re-upload — whenever that cannot be proven).  Compiled runners are
    cached per (mesh, donate)."""
    key = (mesh, bool(donate))
    fn = _patch_rows_sharded_cache.get(key)
    if fn is None:
        from jax.sharding import PartitionSpec as _P

        from ..parallel.mesh import shard_map as _shard_map

        def _patch(col, idx, vals):
            shard = jax.lax.axis_index("nodes")
            size = col.shape[0]
            local = idx - shard * size
            ok = (local >= 0) & (local < size)
            # misses (another shard's rows, padding) map to `size`,
            # which mode="drop" discards
            safe = jnp.where(ok, local, size)
            return col.at[safe].set(vals, mode="drop")

        wrapped = functools.partial(
            _shard_map,
            mesh=mesh,
            in_specs=(_P("nodes"), _P(), _P()),
            out_specs=_P("nodes"),
        )(_patch)
        fn = jax.jit(
            wrapped, donate_argnums=(0,) if donate else ()
        )
        fn.__name__ = (
            "patch_rows_sharded_donated"
            if donate
            else "patch_rows_sharded"
        )
        _patch_rows_sharded_cache[key] = fn
    return fn


def patch_rows_hostlocal(mesh, donate: bool = False):
    """Per-DEVICE staging variant of `patch_rows_sharded` for MULTI-
    host meshes: the delta-sync primitive of the cross-host flush
    protocol.  ``idx`` and ``vals`` arrive as ``[D, w]`` arrays
    sharded ``P("nodes")`` along the leading device axis — device d's
    row holds ONLY the dirty rows landing in its own node shard, with
    indices already shard-LOCAL and padding slots set to the shard
    size (out of bounds -> dropped, exactly like `patch_rows`).  Each
    host therefore builds and ships staging for its own devices'
    dirty rows and nothing else: a warm cross-host flush costs every
    host O(its dirty rows) bytes, never a replicated buffer and never
    a full column over the network.  ``w`` is the pow2 bucket of the
    LARGEST per-device dirty count (a shared static shape — every
    process must compile the identical program).

    Bit-identical to `patch_rows_sharded` on the same dirty set: both
    reduce to one local in-shard scatter per device.  The single-
    process mirror keeps the replicated PR 8 staging (same bytes,
    same trace); this variant exists for the world where "replicated"
    means a network broadcast.  ``donate=True`` follows
    `patch_rows_donated`'s exclusivity contract."""
    key = (mesh, "hostlocal", bool(donate))
    fn = _patch_rows_sharded_cache.get(key)
    if fn is None:
        from jax.sharding import PartitionSpec as _P

        from ..parallel.mesh import shard_map as _shard_map

        def _patch(col, idx, vals):
            # leading axis: this device's single [1, w] staging row;
            # indices are pre-localized, padding == shard size drops
            return col.at[idx[0]].set(vals[0], mode="drop")

        wrapped = functools.partial(
            _shard_map,
            mesh=mesh,
            in_specs=(_P("nodes"), _P("nodes"), _P("nodes")),
            out_specs=_P("nodes"),
        )(_patch)
        fn = jax.jit(
            wrapped, donate_argnums=(0,) if donate else ()
        )
        fn.__name__ = (
            "patch_rows_hostlocal_donated"
            if donate
            else "patch_rows_hostlocal"
        )
        _patch_rows_sharded_cache[key] = fn
    return fn


def hostlocal_staging(
    mesh, idx: np.ndarray, capacity: int
) -> Tuple[np.ndarray, List[np.ndarray], int]:
    """Build the `patch_rows_hostlocal` index staging for a dirty-row
    set: returns ``(idx_stack[D, w] shard-local i32, order, w)`` where
    ``order[d]`` is the slice of ``idx`` (global rows, sorted) that
    landed in device d's shard — the caller gathers each column's
    values with it.  Deterministic across processes: every process
    computes the identical stack from the shared dirty log, then
    ships only its own devices' rows (`mesh_put`)."""
    n_dev = int(mesh.devices.size)
    size = capacity // n_dev
    per_dev = [
        idx[(idx >= d * size) & (idx < (d + 1) * size)]
        for d in range(n_dev)
    ]
    w = pow2_bucket(
        max(1, max(len(s) for s in per_dev)), floor=8
    )
    idx_stack = np.full((n_dev, w), size, np.int32)
    for d, sel in enumerate(per_dev):
        idx_stack[d, : len(sel)] = sel - d * size
    return idx_stack, per_dev, w


@functools.partial(
    jax.jit, static_argnames=("n_picks", "spread_fit")
)
def chained_plan_picks_shared(
    cpu_total,
    mem_total,
    disk_total,
    feasible,  # bool[C] shared static mask
    base_cpu_used,  # f[C] shared snapshot usage
    base_mem_used,
    base_disk_used,
    perms,  # i32[E, C]
    ask_cpu,  # f[E]
    ask_mem,
    ask_disk,
    desired_count,  # i32[E]
    limit,  # i32[E]
    n_candidates,
    n_picks: int,
    spread_fit: bool = False,
):
    """Serially-equivalent chained planner with shared node columns:
    the production dispatch shape — only E x C walk orders and per-eval
    scalars ship per launch, usage chains across evals in-kernel."""
    C = cpu_total.shape[0]
    zeros_i = jnp.zeros(C, jnp.int32)
    zeros_b = jnp.zeros(C, dtype=bool)
    zeros_f = jnp.zeros(C, cpu_total.dtype)

    def eval_step(used, xs):
        perm, a_cpu, a_mem, a_disk, desired, lim = xs
        inp = BatchInputs(
            feasible=feasible,
            base_cpu_used=used[0],
            base_mem_used=used[1],
            base_disk_used=used[2],
            base_collisions=zeros_i,
            penalty=zeros_b,
            affinity_score=zeros_f,
            perm=perm,
            ask_cpu=a_cpu,
            ask_mem=a_mem,
            ask_disk=a_disk,
            desired_count=desired,
            limit=lim,
            distinct_hosts=jnp.asarray(False),
        )
        rows, used_next, _pulls = _run_picks(
            cpu_total,
            mem_total,
            disk_total,
            used,
            inp,
            jnp.asarray(n_candidates, jnp.int32),
            n_picks,
            spread_fit,
            wanted=desired,
        )
        return used_next, rows

    used0 = (base_cpu_used, base_mem_used, base_disk_used)
    _final, rows = jax.lax.scan(
        eval_step,
        used0,
        (perms, ask_cpu, ask_mem, ask_disk, desired_count, limit),
    )
    return rows


@functools.partial(
    jax.jit, static_argnames=("n_picks", "spread_fit")
)
def batch_plan_picks_shared(
    cpu_total,
    mem_total,
    disk_total,
    feasible,  # bool[C] shared static mask
    base_cpu_used,  # f[C] shared snapshot usage
    base_mem_used,
    base_disk_used,
    perms,  # i32[E, C] per-eval walk orders
    ask_cpu,  # f[E]
    ask_mem,
    ask_disk,
    desired_count,  # i32[E]
    limit,  # i32[E]
    n_candidates,
    n_picks: int,
    spread_fit: bool = False,
):
    """Batched planner for the common case where every eval in the batch
    scores against the same snapshot (fresh jobs, no penalties or
    affinities): node columns ship once, only the E x C walk orders and
    per-eval scalars vary.  Cuts host->device traffic ~12x versus
    stacking full BatchInputs per eval — decisive when the accelerator
    sits behind a high-latency tunnel (SURVEY.md section 7.3 Go<->TPU
    latency note)."""
    C = cpu_total.shape[0]
    zeros_i = jnp.zeros(C, jnp.int32)
    zeros_b = jnp.zeros(C, dtype=bool)
    zeros_f = jnp.zeros(C, cpu_total.dtype)

    def one(perm, a_cpu, a_mem, a_disk, desired, lim):
        inp = BatchInputs(
            feasible=feasible,
            base_cpu_used=base_cpu_used,
            base_mem_used=base_mem_used,
            base_disk_used=base_disk_used,
            base_collisions=zeros_i,
            penalty=zeros_b,
            affinity_score=zeros_f,
            perm=perm,
            ask_cpu=a_cpu,
            ask_mem=a_mem,
            ask_disk=a_disk,
            desired_count=desired,
            limit=lim,
            distinct_hosts=jnp.asarray(False),
        )
        return plan_picks(
            cpu_total, mem_total, disk_total, inp,
            n_candidates, n_picks, spread_fit,
        )

    return jax.vmap(one)(
        perms, ask_cpu, ask_mem, ask_disk, desired_count, limit
    )


@functools.partial(
    jax.jit, static_argnames=("n_picks", "spread_fit")
)
def batch_plan_picks(
    cpu_total,
    mem_total,
    disk_total,
    batch: BatchInputs,  # leading axis E on every field
    n_candidates,  # scalar or per-eval i32[E] (walk rotation modulus)
    n_picks: int,
    spread_fit: bool = False,
    spread: SpreadInputs = None,  # leading axis E on every field
):
    """E independent evals x P picks in one launch; returns rows
    i32[E, P]."""
    E = batch.perm.shape[0]
    nc = jnp.broadcast_to(jnp.asarray(n_candidates, jnp.int32), (E,))
    if spread is not None:
        return jax.vmap(
            lambda b, n, s: plan_picks(
                cpu_total, mem_total, disk_total, b, n,
                n_picks, spread_fit, spread=s,
            )
        )(batch, nc, spread)
    return jax.vmap(
        lambda b, n: plan_picks(
            cpu_total,
            mem_total,
            disk_total,
            b,
            n,
            n_picks,
            spread_fit,
        )
    )(batch, nc)
