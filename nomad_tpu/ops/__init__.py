"""JAX kernels: the vectorized scoring backend.

`constraints.py` compiles constraints/affinities/spreads into boolean or
float lookup tables over interned column vocabularies (exact reference
operator semantics evaluated host-side over the tiny vocab; the device
does only `lut[codes]` gathers).  `score.py` is the jitted score kernel +
deterministic limited-walk selection that reproduces the reference's
GenericStack.Select bit-for-bit.  `batch.py` scans/vmaps the kernel over
picks and evals for throughput.
"""
from .score import score_and_select, ScoreInputs  # noqa: F401
from .constraints import MaskCompiler  # noqa: F401
