"""Constraint/affinity/spread compilation to columnar lookup tables.

The reference evaluates every constraint per (node, constraint) pair with
string operations (scheduler/feasible.go:750 checkConstraint — regex,
version parsing, set ops).  On TPU, strings can't ride along; instead each
node attribute column is interned (state/node_table.py) and a constraint
becomes a boolean LUT over the column's vocabulary: we run the *exact*
reference operator semantics (sched/operators.py) once per distinct value
host-side, then the per-node check is `lut[codes]` — a gather that
vectorizes over all nodes and fuses into the score kernel.  This covers
every operator including the reference's "escaped" cases (regex, version,
semver; feasible.go:776) with zero per-node host work.

LUTs are cached per (column, operand, rtarget) and extended incrementally
as vocabularies grow with node churn.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..state.node_table import MISSING, NodeTable
from ..structs import (
    Affinity,
    Constraint,
    CONSTRAINT_DISTINCT_HOSTS,
    CONSTRAINT_DISTINCT_PROPERTY,
)
from ..sched.feasible import target_column_key
from ..sched.operators import check_constraint


class MaskCompiler:
    def __init__(self, table: NodeTable) -> None:
        self.table = table
        self.regex_cache: Dict = {}
        self.version_cache: Dict = {}
        # (lkey, operand, rtarget) -> bool lut over vocab (+1 missing slot)
        self._lut_cache: Dict[Tuple[str, str, str], np.ndarray] = {}

    # ------------------------------------------------------------------

    def constraint_mask(self, constraint: Constraint) -> Optional[np.ndarray]:
        """Boolean mask[capacity]; None means "always true" (handled
        elsewhere, e.g. distinct_hosts)."""
        if constraint.operand in (
            CONSTRAINT_DISTINCT_HOSTS,
            CONSTRAINT_DISTINCT_PROPERTY,
        ):
            return None
        lkey = target_column_key(constraint.ltarget)
        rkey = target_column_key(constraint.rtarget)

        if lkey is None and rkey is None:
            ok = check_constraint(
                constraint.operand,
                constraint.ltarget,
                constraint.rtarget,
                True,
                True,
                self.regex_cache,
                self.version_cache,
            )
            return np.full(self.table.capacity, ok, dtype=bool)

        if rkey is None:
            return self._column_vs_literal(
                lkey, constraint.operand, constraint.rtarget, lhs=True
            )
        if lkey is None:
            return self._column_vs_literal(
                rkey, constraint.operand, constraint.ltarget, lhs=False
            )
        return self._column_vs_column(lkey, rkey, constraint.operand)

    def affinity_match_mask(self, affinity: Affinity) -> np.ndarray:
        c = Constraint(
            ltarget=affinity.ltarget,
            rtarget=affinity.rtarget,
            operand=affinity.operand,
        )
        mask = self.constraint_mask(c)
        if mask is None:
            mask = np.ones(self.table.capacity, dtype=bool)
        return mask

    def affinity_score_vector(
        self, affinities: List[Affinity]
    ) -> Tuple[np.ndarray, float]:
        """Per-node sum of matched affinity weights and the |weight| sum
        (reference rank.go:637-658)."""
        total = np.zeros(self.table.capacity, dtype=np.float64)
        sum_weight = 0.0
        for aff in affinities:
            sum_weight += abs(float(aff.weight))
            mask = self.affinity_match_mask(aff)
            total += mask.astype(np.float64) * float(aff.weight)
        return total, sum_weight

    # ------------------------------------------------------------------

    def _column_vs_literal(
        self, key: str, operand: str, literal: str, lhs: bool
    ) -> np.ndarray:
        if key == "":
            # unresolvable interpolation: found=False on the column side
            if lhs:
                ok = check_constraint(
                    operand, None, literal, False, True,
                    self.regex_cache, self.version_cache,
                )
            else:
                ok = check_constraint(
                    operand, literal, None, True, False,
                    self.regex_cache, self.version_cache,
                )
            return np.full(self.table.capacity, ok, dtype=bool)

        col = self.table.column(key)
        vocab = col.interner.values
        cache_key = (key, operand, literal if lhs else "\x00L:" + literal)
        lut = self._lut_cache.get(cache_key)
        if lut is None or len(lut) < len(vocab) + 1:
            lut = np.empty(len(vocab) + 1, dtype=bool)
            for i, value in enumerate(vocab):
                if lhs:
                    lut[i] = check_constraint(
                        operand, value, literal, True, True,
                        self.regex_cache, self.version_cache,
                    )
                else:
                    lut[i] = check_constraint(
                        operand, literal, value, True, True,
                        self.regex_cache, self.version_cache,
                    )
            # last slot: value missing on the node
            if lhs:
                lut[-1] = check_constraint(
                    operand, None, literal, False, True,
                    self.regex_cache, self.version_cache,
                )
            else:
                lut[-1] = check_constraint(
                    operand, literal, None, True, False,
                    self.regex_cache, self.version_cache,
                )
            self._lut_cache[cache_key] = lut
        # codes: MISSING (-1) indexes the last slot
        return lut[col.codes]

    def _column_vs_column(
        self, lkey: str, rkey: str, operand: str
    ) -> np.ndarray:
        """Both targets interpolate (rare).  Evaluate per distinct
        (lcode, rcode) pair."""
        lcol = self.table.column(lkey) if lkey else None
        rcol = self.table.column(rkey) if rkey else None
        lcodes = (
            lcol.codes
            if lcol is not None
            else np.full(self.table.capacity, MISSING, dtype=np.int32)
        )
        rcodes = (
            rcol.codes
            if rcol is not None
            else np.full(self.table.capacity, MISSING, dtype=np.int32)
        )
        pairs = np.stack([lcodes, rcodes], axis=1)
        uniq, inverse = np.unique(pairs, axis=0, return_inverse=True)
        out = np.empty(len(uniq), dtype=bool)
        for i, (lc, rc) in enumerate(uniq):
            lval = (
                lcol.interner.values[lc]
                if lcol is not None and lc != MISSING
                else None
            )
            rval = (
                rcol.interner.values[rc]
                if rcol is not None and rc != MISSING
                else None
            )
            out[i] = check_constraint(
                operand,
                lval,
                rval,
                lval is not None,
                rval is not None,
                self.regex_cache,
                self.version_cache,
            )
        return out[inverse]

    # ------------------------------------------------------------------

    def spread_kernel_inputs(
        self,
        attribute: str,
        desired_counts: Dict[str, float],
        existing_use: Dict[str, int],
        cleared_use: Optional[Dict[str, int]] = None,
        proposed_use: Optional[Dict[str, int]] = None,
    ):
        """Columns for the in-kernel spread carry (ops/batch.py
        SpreadInputs): per-node value slot codes, desired count,
        existing / pre-staged proposed / pre-staged cleared use per
        slot.  The last slot is the penalty slot (missing attribute /
        value with no target and no implicit "*"), matching
        spread_boost_vector's -1.0 semantics."""
        C = self.table.capacity
        cleared_use = cleared_use or {}
        proposed_use = proposed_use or {}
        key = target_column_key(attribute) or ""
        if key == "":
            # non-interpolatable attribute: every node is a penalty
            codes = np.zeros(C, dtype=np.int32)
            z = np.zeros(1)
            return codes, z, z, z, z
        col = self.table.column(key)
        vocab = col.interner.values
        V = len(vocab)
        slot_of = np.full(V + 1, V, dtype=np.int32)
        desired = np.zeros(V + 1, dtype=np.float64)
        used0 = np.zeros(V + 1, dtype=np.float64)
        proposed0 = np.zeros(V + 1, dtype=np.float64)
        cleared0 = np.zeros(V + 1, dtype=np.float64)
        for i, value in enumerate(vocab):
            if desired_counts is None:
                # even-spread mode (no targets): every observed value
                # gets a slot; desired is unused
                d = 0.0
            else:
                d = desired_counts.get(value)
                if d is None:
                    d = desired_counts.get("*")
                if d is None:
                    continue  # stays on the penalty slot
            slot_of[i] = i
            desired[i] = d
            used0[i] = float(existing_use.get(value, 0))
            proposed0[i] = float(proposed_use.get(value, 0))
            cleared0[i] = float(cleared_use.get(value, 0))
        node_codes = np.where(col.codes >= 0, col.codes, V)
        codes = slot_of[node_codes]
        return codes, desired, used0, proposed0, cleared0

    def spread_boost_vector(
        self,
        attribute: str,
        weight_frac: Optional[float],
        desired_counts: Optional[Dict[str, float]],
        combined_use: Dict[str, int],
    ) -> np.ndarray:
        """Per-node spread score contribution for one spread attribute.

        Target mode (reference spread.go:163): boost =
        ((desired - (used+1)) / desired) * weight_frac, -1 for values with
        no desired count and no implicit target, -1 when the attribute is
        missing.  Even mode (spread.go:178): the min/max-delta formula.
        The per-*value* boost is computed host-side over the vocabulary and
        gathered per node.
        """
        key = target_column_key(attribute)
        if key is None:
            # constant attribute (not an interpolation): every node shares
            # one value
            key = ""
        if key == "":
            return np.full(self.table.capacity, -1.0, dtype=np.float64)
        col = self.table.column(key)
        vocab = col.interner.values
        boosts = np.empty(len(vocab) + 1, dtype=np.float64)

        if desired_counts is not None:
            for i, value in enumerate(vocab):
                used = combined_use.get(value, 0) + 1
                desired = desired_counts.get(value)
                if desired is None:
                    desired = desired_counts.get("*")
                if desired is None:
                    boosts[i] = -1.0
                    continue
                boosts[i] = ((desired - float(used)) / desired) * weight_frac
            boosts[-1] = -1.0  # missing property
        else:
            # even-spread mode
            if not combined_use:
                boosts[:] = 0.0
                boosts[-1] = 0.0
                return boosts[col.codes]
            counts = list(combined_use.values())
            min_count = 0
            max_count = 0
            for v in counts:
                if min_count == 0 or v < min_count:
                    min_count = v
                if max_count == 0 or v > max_count:
                    max_count = v
            for i, value in enumerate(vocab):
                current = combined_use.get(value, 0)
                if min_count == 0:
                    delta_boost = -1.0
                else:
                    delta_boost = float(min_count - current) / float(
                        min_count
                    )
                if current != min_count:
                    boosts[i] = delta_boost
                elif min_count == max_count:
                    boosts[i] = -1.0
                elif min_count == 0:
                    boosts[i] = 1.0
                else:
                    boosts[i] = float(max_count - min_count) / float(
                        min_count
                    )
            boosts[-1] = -1.0
        return boosts[col.codes]

    # ------------------------------------------------------------------

    def device_feasibility(
        self, requests: List
    ) -> Optional[np.ndarray]:
        """Mask of nodes with enough free matching device instances for
        every request (reference feasible.go:1138 DeviceChecker +
        capacity accounting)."""
        if not requests:
            return None
        table = self.table
        mask = np.ones(table.capacity, dtype=bool)
        for req in requests:
            matching_codes = set()
            for code in range(len(table.device_sigs)):
                if not table.device_sig_matches(code, req.name):
                    continue
                if not self._device_sig_meets_constraints(code, req):
                    continue
                matching_codes.add(code)
            total = np.zeros(table.capacity, dtype=np.int32)
            for row, groups in table.device_groups.items():
                for code, count in groups:
                    if code in matching_codes:
                        total[row] += count
            used = np.zeros(table.capacity, dtype=np.int32)
            for (row, key), count in table.device_used.items():
                for code in matching_codes:
                    sig = table._device_sig_meta[code]
                    if (sig[0], sig[1], sig[2]) == key:
                        used[row] += count
                        break
            mask &= (total - used) >= req.count
        return mask

    def _device_sig_meets_constraints(self, code: int, req) -> bool:
        from ..sched.feasible import _resolve_device_target
        from ..structs import NodeDeviceResource

        sig = self.table._device_sig_meta[code]
        group = NodeDeviceResource(
            vendor=sig[0], type=sig[1], name=sig[2],
            attributes=dict(sig[3]),
        )
        for constraint in req.constraints:
            lval, lok = _resolve_device_target(constraint.ltarget, group)
            rval, rok = _resolve_device_target(constraint.rtarget, group)
            if not check_constraint(
                constraint.operand, lval, rval, lok, rok,
                self.regex_cache, self.version_cache,
            ):
                return False
        return True
