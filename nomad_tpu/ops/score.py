"""The vectorized score kernel + deterministic selection.

This is the TPU-native replacement for the reference's innermost loop
(SURVEY.md section 3.2): one jitted function scores *all* candidate nodes
at once — fit masks, BestFit-v3 bin-packing (funcs.go:175), job
anti-affinity (rank.go:527), rescheduling penalty (rank.go:573), node
affinity (rank.go:658), spread boosts (spread.go:163), mean normalization
(rank.go:706) — and then *exactly emulates* the reference's shuffled
limited walk (select.go: LimitIterator with skip-threshold 0 / max-skip 3,
MaxScoreIterator's first-wins strict max) over the score vector, so the
selected node is bit-identical to what the pull-based iterator chain
would have chosen while doing O(N) vector math instead of O(limit) pointer
chasing.

Score-append semantics are reproduced as a (sum, count) pair: each term
contributes to the sum and increments the count only under the reference's
append conditions; the final score is sum/count.

Shapes are fixed to the node arena capacity so jit traces cache across
cluster churn; vacant rows are masked.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

MAX_SKIP = 3  # (reference stack.go:17)
SKIP_THRESHOLD = 0.0  # (reference stack.go:13)
NO_NODE = -1


class PolicyTerms(NamedTuple):
    """Optional policy terms fused into the score pass (Gavel-style
    heterogeneity throughput + migration stickiness), PRE-SCALED by
    their coefficients host-side (one numpy mul at assembly — f64
    multiplication is deterministic, so host and device scaling are
    bit-identical and the kernel saves the per-candidate ops).  Shapes
    follow the ScoreInputs they ride in: per-node vectors broadcast
    exactly like `feasible` ([C] for a single select, [A, C] after the
    storm solver's per-row gather), flags like `desired_count` ([] or
    [A, 1]).

    Each term group is independently optional: a None group is absent
    from the pytree, so a throughput-only job (the common identity-
    weights shape) pays ONE vector add plus a scalar count bump and a
    migration-only job pays only the penalty ops.  Single selects drop
    whichever group is inert; storms keep both groups dense (all-zero
    rows for policy-less evals are float-exact no-ops) so one compiled
    signature covers every mixed storm.

    `tput_term` is `tput_coef * tput_norm[node]`, appended for EVERY
    candidate when present (zeros included — an unknown node class
    pulls the mean down exactly like the serial oracle); `has_tput` is
    its 0/1 append-count flag (per-eval in storms).  `mig_term` is
    `mig_coef * mig[node]` where mig is -1 on every node EXCEPT those
    currently hosting this TG's live allocs; it appends only where
    non-zero (node-reschedule-penalty convention — the incumbent's
    score mean stays untouched, movers are dragged down)."""

    tput_term: Optional[jnp.ndarray]  # f[C] coef * normalized tput
    has_tput: Optional[jnp.ndarray]  # f 0/1 flag, paired with tput_term
    mig_term: Optional[jnp.ndarray]  # f[C] coef * (-1 off-host, 0 on)


def _pow10(x, dtype):
    """Canonical 10^x for fitness scoring: f64 pow rounded through
    float32 so host and accelerator implementations agree bit-for-bit
    (see structs/funcs.py _pow10)."""
    raw = jnp.power(jnp.asarray(10.0, dtype), x)
    return raw.astype(jnp.float32).astype(dtype)


class ScoreInputs(NamedTuple):
    """Arena-shaped kernel inputs.  All float arrays share one dtype
    (f64 for bit-parity tests on CPU, f32 on TPU).  `perm` is the rotated
    visit order for this select; `n_candidates` the number of real
    candidates at its front."""

    cpu_total: jnp.ndarray  # [C] node capacity minus node-reserved
    mem_total: jnp.ndarray  # [C]
    disk_total: jnp.ndarray  # [C]
    cpu_used: jnp.ndarray  # [C] proposed usage (state + plan deltas)
    mem_used: jnp.ndarray  # [C]
    disk_used: jnp.ndarray  # [C]
    feasible: jnp.ndarray  # bool[C] all static+dynamic feasibility masks
    collisions: jnp.ndarray  # i32[C] proposed allocs of same job+tg
    penalty: jnp.ndarray  # bool[C] rescheduling penalty nodes
    affinity_score: jnp.ndarray  # f[C] normalized affinity score
    spread_boost: jnp.ndarray  # f[C] total spread boost
    perm: jnp.ndarray  # i32[C] walk order: perm[i] = row at position i
    ask_cpu: jnp.ndarray  # f scalar
    ask_mem: jnp.ndarray  # f scalar
    ask_disk: jnp.ndarray  # f scalar
    desired_count: jnp.ndarray  # i32 scalar (tg.count)
    limit: jnp.ndarray  # i32 scalar (visit limit; INT32_MAX = unlimited)
    n_candidates: jnp.ndarray  # i32 scalar
    # policy-weighted scoring: absent (None) for policy-less jobs.  A
    # None NamedTuple field contributes no pytree leaves, so the
    # policy-off kernel keeps today's compiled signatures AND traces
    # the bit-identical computation (the fused terms below are guarded
    # by a trace-time `is not None`); a present PolicyTerms forks one
    # new pinned signature per ladder rung (ops/contracts.py).
    policy: Optional[PolicyTerms] = None


def _score_vectors(inp: ScoreInputs, spread_fit: bool):
    """Returns (feasible_after_fit bool[C], final_scores f[C])."""
    dtype = inp.cpu_total.dtype
    cpu_after = inp.cpu_used + inp.ask_cpu
    mem_after = inp.mem_used + inp.ask_mem
    disk_after = inp.disk_used + inp.ask_disk

    fit = (
        (cpu_after <= inp.cpu_total)
        & (mem_after <= inp.mem_total)
        & (disk_after <= inp.disk_total)
    )
    feasible = inp.feasible & fit

    safe_cpu_total = jnp.where(inp.cpu_total > 0, inp.cpu_total, 1.0)
    safe_mem_total = jnp.where(inp.mem_total > 0, inp.mem_total, 1.0)
    free_cpu = 1.0 - cpu_after / safe_cpu_total
    free_mem = 1.0 - mem_after / safe_mem_total
    # the fitness exponential is DEFINED at float32 precision (see
    # structs/funcs.py _pow10): host libm and XLA pow disagree by 1 f64
    # ulp on ~5% of inputs, so both sides round the pow through f32 and
    # continue in the working dtype
    base = _pow10(free_cpu, dtype) + _pow10(free_mem, dtype)
    if spread_fit:
        fitness = jnp.clip(base - 2.0, 0.0, 18.0)
    else:
        fitness = jnp.clip(20.0 - base, 0.0, 18.0)
    binpack = fitness / 18.0

    score_sum = binpack
    count = jnp.ones_like(binpack)

    has_collision = inp.collisions > 0
    anti = jnp.where(
        has_collision,
        -(inp.collisions.astype(dtype) + 1.0)
        / inp.desired_count.astype(dtype),
        0.0,
    )
    score_sum = score_sum + anti
    count = count + has_collision.astype(dtype)

    score_sum = score_sum - inp.penalty.astype(dtype)
    count = count + inp.penalty.astype(dtype)

    has_aff = inp.affinity_score != 0.0
    score_sum = score_sum + jnp.where(has_aff, inp.affinity_score, 0.0)
    count = count + has_aff.astype(dtype)

    has_spread = inp.spread_boost != 0.0
    score_sum = score_sum + jnp.where(has_spread, inp.spread_boost, 0.0)
    count = count + has_spread.astype(dtype)

    # policy-weighted terms append LAST so the serial oracle's
    # left-to-right float-sum order is preserved (PolicyIterator sits
    # after SpreadIterator in the chain).  Trace-time guard: with
    # policy=None this block vanishes and the kernel is bit-identical
    # to the policy-less build.
    if inp.policy is not None:
        pol = inp.policy
        # terms arrive pre-scaled (PolicyTerms docstring), so each
        # present group is one add into the running sum: the term is
        # already 0 wherever it must not append (a zero add is exact —
        # score_sum is never -0.0, and np.zeros stages +0.0), so only
        # the count needs a flag/predicate
        if pol.tput_term is not None:
            score_sum = score_sum + pol.tput_term
            count = count + pol.has_tput
        if pol.mig_term is not None:
            score_sum = score_sum + pol.mig_term
            count = count + (pol.mig_term != 0.0).astype(dtype)

    final = score_sum / count
    return feasible, final


def _limited_walk_argmax(
    feasible: jnp.ndarray,
    scores: jnp.ndarray,
    perm: jnp.ndarray,
    limit: jnp.ndarray,
    n_candidates: jnp.ndarray,
):
    """Emulate LimitIterator + MaxScoreIterator over all nodes at once.

    `perm` is the *rotated* visit order for this select: the reference's
    StaticIterator keeps its offset across Reset (feasible.go:75-113), so
    consecutive selects continue round-robin through the shuffled list;
    the caller rotates the permutation by the accumulated pull count and
    advances it by the returned `pulls`.

    The walk visits feasible nodes in order.  The first up-to-3 nodes
    scoring <= threshold are diverted to a side list that is replayed
    only if the source runs dry before `limit` nodes were emitted
    (select.go:35-75).  Replay normally preserves diversion order; with
    exactly two diverted nodes the reference's re-skip quirk replays them
    in reverse (the first diverted node is re-appended before being
    returned), which we reproduce.  The winner is the strict maximum over
    emitted nodes, earliest emitted wins ties (select.go:94-113).

    Pull accounting: if at least `limit` nodes are emitted from the
    source, the walk stops at the limit-th one and the pull count is its
    1-based position; otherwise the whole candidate list is consumed.
    Infeasible nodes consume pulls (they are filtered mid-chain), which
    is exactly how the reference's rotation advances.
    """
    s = scores[perm]
    f = feasible[perm]

    bad = f & (s <= SKIP_THRESHOLD)
    bad_rank = jnp.cumsum(bad.astype(jnp.int32))
    diverted = bad & (bad_rank <= MAX_SKIP)
    nd = f & ~diverted
    nd_cum = jnp.cumsum(nd.astype(jnp.int32))
    nd_count = nd_cum[-1]
    nd_rank = nd_cum - 1
    n_div = jnp.sum(diverted.astype(jnp.int32))
    div_rank = jnp.cumsum(diverted.astype(jnp.int32)) - 1
    # two-diverted replay reversal (see docstring) — only when a
    # non-diverted emission preceded the replay; with no good nodes
    # the source exhausts inside the first skip loop and the tail
    # _next_option replays in ORIGINAL order (select.py next())
    div_order = jnp.where(
        (n_div == 2) & (nd_count > 0), 1 - div_rank, div_rank
    )
    emit_order = jnp.where(nd, nd_rank, nd_count + div_order)
    emitted = f & (emit_order < limit)

    neg_inf = jnp.asarray(-jnp.inf, dtype=s.dtype)
    masked = jnp.where(emitted, s, neg_inf)
    best = jnp.max(masked)
    candidates = emitted & (masked == best)
    order_key = jnp.where(
        candidates, emit_order, jnp.asarray(2**31 - 1, jnp.int32)
    )
    win_pos = jnp.argmin(order_key)
    chosen_row = perm[win_pos]
    any_emitted = jnp.any(emitted)
    chosen_row = jnp.where(any_emitted, chosen_row, NO_NODE)

    limit_reached = nd_count >= limit
    lth_pos = jnp.argmax(nd_cum >= limit)
    pulls = jnp.where(limit_reached, lth_pos + 1, n_candidates)
    return chosen_row, best, jnp.sum(f.astype(jnp.int32)), pulls


@functools.partial(jax.jit, static_argnames=("spread_fit",))
def score_and_select(inp: ScoreInputs, spread_fit: bool = False):
    """Returns (chosen_row, chosen_score, feasible_count, pulls).
    chosen_row == -1 when no feasible node was emitted."""
    feasible, final = _score_vectors(inp, spread_fit)
    chosen_row, best, feasible_count, pulls = _limited_walk_argmax(
        feasible, final, inp.perm, inp.limit, inp.n_candidates
    )
    return chosen_row, best, feasible_count, pulls


@functools.partial(jax.jit, static_argnames=("spread_fit",))
def score_and_select_packed(inp: ScoreInputs, spread_fit: bool = False):
    """score_and_select with all outputs packed into ONE i32[2] array
    ([chosen_row, pulls]) so the host pays a single device->host sync
    per select — each fetch is a full round trip on tunneled
    accelerators."""
    chosen_row, _best, _n, pulls = score_and_select(
        inp, spread_fit=spread_fit
    )
    return jnp.stack(
        [chosen_row.astype(jnp.int32), pulls.astype(jnp.int32)]
    )


@functools.partial(jax.jit, static_argnames=("spread_fit",))
def score_all(inp: ScoreInputs, spread_fit: bool = False):
    """Scores + feasibility only (system stack / diagnostics)."""
    feasible, final = _score_vectors(inp, spread_fit)
    return feasible, final


def make_perm(rng, rows, capacity: int) -> np.ndarray:
    """Walk order matching the oracle's seeded Fisher-Yates shuffle
    (sched/feasible.py shuffle_nodes) applied to the same candidate list:
    perm[i] = arena row visited at walk position i.  Arena rows not in the
    candidate list are appended at the end; they are masked infeasible and
    can never win, but keep the perm a full permutation of the arena."""
    rows = list(rows)
    for i in range(len(rows) - 1, 0, -1):
        j = rng.randint(0, i)
        rows[i], rows[j] = rows[j], rows[i]
    present = set(rows)
    rows.extend(r for r in range(capacity) if r not in present)
    return np.asarray(rows, dtype=np.int32)
