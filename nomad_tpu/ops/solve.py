"""Global storm assignment solver: one device-resident solve for a
whole backlog of pending evals.

A placement storm (node drain, mass failure, dispatch scale-up) turns
into hundreds of pending evals of one job family.  The per-eval chunk
chain (batch_worker.py) walks them one placement at a time — fast per
walk, but the work is still factored per eval.  CvxCluster (PAPERS.md)
shows large granular allocation problems solved as ONE optimization
run orders of magnitude faster than per-item heuristics, and the
(pending-allocs x candidate-nodes) score matrix this repo already
computes (ops/score.py) is exactly that problem's cost matrix.

``storm_assignment`` coalesces the storm into a single jitted solve:

1. **Score matrix.** The shared ``_score_vectors`` kernel scores every
   (alloc row, node) pair in one broadcasted pass — same fit masks,
   bin-packing curve, anti-affinity/penalty/affinity terms as the
   serial chain, against the device-resident usage mirror columns
   (plus the storm's staged pre-placement deltas).
2. **Greedy warm start.** Each row's serial pick — the shuffled
   limited-walk winner (``_limited_walk_argmax`` vmapped over rows,
   with each eval's recorded rng order and visit limit).  A one-row
   storm therefore converges to EXACTLY the chunk chain's selection
   (the degenerate-parity contract), pulls included.
3. **Auction rounds.** A ``lax.while_loop`` of bidding rounds resolves
   contention: every unassigned row bids its best value
   (score - node price) among nodes whose REMAINING capacity fits its
   ask; each node then accepts the best-value PREFIX of its bidders
   whose cumulative asks still fit (ties break to the lowest row
   index — broker FIFO), debits its capacity and raises its price.
   Acceptance never over-commits a node, and every bidding node
   accepts at least its top bidder per round (an individual bid
   already proved fit), so a storm of identical asks fills a node in
   ONE round instead of one-acceptance-at-a-time and the loop
   converges in a handful of rounds.  Rows left unassigned (nothing
   feasible fits, or the round budget ran out) return ``NO_NODE`` and
   their evals fall back to the serial chain — correctness never
   depends on the solver.

Serial equivalence is deliberately relaxed under contention: the
auction maximizes cluster-wide score, not arrival-order greed.  Every
divergence from the warm-start walk is reported per row (``greedy``
output) so the scheduler can tag explain records with the solver
round and assignment score.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .score import NO_NODE, ScoreInputs, _limited_walk_argmax, _score_vectors

# per-acceptance price increment: enough to tie-break repeated
# contention (scores live in roughly [-1, 1]) without distorting the
# score landscape for uncontended rows
PRICE_EPS = 0.01
# tie-spreading jitter, orders of magnitude below PRICE_EPS (the
# auction's own optimality tolerance): without it, every row whose
# value ties at the max bids argmax's FIRST index, so a storm of
# identical asks over hundreds of equally-scored nodes fills ONE
# node per round instead of spreading — O(rows/node-capacity)
# rounds.  The jitter only picks WHICH of the tied-max nodes a row
# bids; the bid value itself stays un-jittered, so assignment
# scores and the round-0 warm-start parity are untouched.
TIE_JITTER = 1e-6


class StormInputs(NamedTuple):
    """Host-staged inputs of one storm solve.  ``E`` evals contribute
    ``A`` pending-alloc rows over the ``C``-row node arena; per-eval
    vectors are gathered per row through ``eval_of`` so E-axis data is
    staged once per eval, not once per placement."""

    feasible: jnp.ndarray  # bool[E, C] static feasibility per eval
    affinity: jnp.ndarray  # f[E, C] normalized affinity score
    collisions: jnp.ndarray  # i32[E, C] anti-affinity base counts
    perm: jnp.ndarray  # i32[E, C] recorded serial walk order
    limit: jnp.ndarray  # i32[E] visit limit (INT32_MAX = unlimited)
    n_cand: jnp.ndarray  # i32[E] real candidates at perm's front
    eval_of: jnp.ndarray  # i32[A] row -> eval index
    penalty: jnp.ndarray  # bool[A, C] reschedule-penalty nodes
    ask: jnp.ndarray  # f[A, 3] cpu/mem/disk ask per row
    desired: jnp.ndarray  # i32[A] tg.count per row
    real: jnp.ndarray  # bool[A] padding rows are never assigned
    pre_cpu: jnp.ndarray  # f[C] staged pre-placement usage deltas
    pre_mem: jnp.ndarray  # f[C]
    pre_disk: jnp.ndarray  # f[C]


@functools.partial(
    jax.jit, static_argnames=("spread_fit", "max_rounds")
)
def storm_assignment(
    inp: StormInputs, cols, spread_fit: bool, max_rounds: int
):
    """Returns ``(assigned, pulls, accept_round, score, greedy,
    rounds)``:

    - assigned i32[A]: arena node row per alloc row, NO_NODE unsolved
    - pulls i32[A]: serial walk pulls when the row kept its greedy
      pick (exact chunk-chain pulls), the candidate count otherwise
    - accept_round i32[A]: auction round the row was accepted in
      (0 = warm start / uncontended; -1 = unsolved)
    - score f[A]: the assignment's score matrix entry
    - greedy i32[A]: the warm-start serial-walk winner, for
      divergence accounting
    - rounds i32: auction rounds run before convergence
    """
    cpu_t, mem_t, disk_t, cpu_u, mem_u, disk_u = cols
    dtype = cpu_t.dtype
    cpu_u = cpu_u + inp.pre_cpu
    mem_u = mem_u + inp.pre_mem
    disk_u = disk_u + inp.pre_disk
    A = inp.ask.shape[0]
    C = cpu_t.shape[0]
    eo = inp.eval_of

    # broadcasted score matrix: [C] shared columns + [A, 1] per-row
    # asks flow through the SAME kernel the serial walk uses, so a
    # storm row's score of a node is bit-identical to the chunk
    # chain's first-pick score of it
    si = ScoreInputs(
        cpu_total=cpu_t,
        mem_total=mem_t,
        disk_total=disk_t,
        cpu_used=cpu_u,
        mem_used=mem_u,
        disk_used=disk_u,
        feasible=inp.feasible[eo],
        collisions=inp.collisions[eo],
        penalty=inp.penalty,
        affinity_score=inp.affinity[eo],
        spread_boost=jnp.zeros((), dtype),
        perm=inp.perm[eo],
        ask_cpu=inp.ask[:, 0:1],
        ask_mem=inp.ask[:, 1:2],
        ask_disk=inp.ask[:, 2:3],
        desired_count=inp.desired[:, None],
        limit=inp.limit[eo],
        n_candidates=inp.n_cand[eo],
    )
    feas, scores = _score_vectors(si, spread_fit)
    feas = feas & inp.real[:, None]

    # greedy warm start: the serial chain's shuffled limited walk,
    # one row at a time (vmapped) — the uncontended answer, and the
    # degenerate one-row storm's EXACT answer
    rows0, _best0, _nf, pulls0 = jax.vmap(_limited_walk_argmax)(
        feas, scores, si.perm, si.limit, si.n_candidates
    )

    neg_inf = jnp.asarray(-jnp.inf, dtype=scores.dtype)
    row_ids = jnp.arange(A, dtype=jnp.int32)
    node_ids = jnp.arange(C, dtype=jnp.int32)
    # deterministic per-(row, node) tie-spreading perturbation (see
    # TIE_JITTER): a fixed Knuth-hash lattice, no RNG state
    jitter = (
        (
            (
                row_ids[:, None] * jnp.int32(-1640531527)
                + node_ids[None, :] * jnp.int32(40503)
            )
            & jnp.int32(0xFFFF)
        ).astype(scores.dtype)
        / 65536.0
        * jnp.asarray(TIE_JITTER, scores.dtype)
    )
    free0 = jnp.stack(
        [cpu_t - cpu_u, mem_t - mem_u, disk_t - disk_u], axis=1
    )
    rows0_c = jnp.clip(rows0, 0, C - 1)

    def cond(st):
        _assigned, _free, _price, _acc, rnd, progress = st
        return (rnd < max_rounds) & progress

    def body(st):
        assigned, free, price, acc_round, rnd, _progress = st
        unass = (assigned == NO_NODE) & inp.real
        fits = jnp.all(
            free[None, :, :] >= inp.ask[:, None, :], axis=2
        )
        ok = feas & fits & unass[:, None]
        value = jnp.where(ok, scores - price[None, :], neg_inf)
        # argmax over the jittered value picks WHICH tied-max node a
        # row bids (spreading ties across equal nodes); the bid's
        # VALUE is read back un-jittered
        best_c = jnp.argmax(value + jitter, axis=1).astype(jnp.int32)
        best_v = jnp.take_along_axis(
            value, best_c[:, None], axis=1
        )[:, 0]
        # round 0 bids the serial walk winner when it still fits, so
        # an uncontended storm IS the greedy walk; later rounds bid
        # the price-adjusted argmax (global quality)
        walk_v = jnp.take_along_axis(
            value, rows0_c[:, None], axis=1
        )[:, 0]
        use_walk = (rnd == 0) & (rows0 >= 0) & (walk_v > neg_inf)
        bid_c = jnp.where(use_walk, rows0_c, best_c)
        bid_v = jnp.where(use_walk, walk_v, best_v)
        has_bid = bid_v > neg_inf
        # per-node PREFIX acceptance: each row's rank among its bid
        # node's bidders comes from an [A, A] comparison (value
        # descending, ties to the lowest row index — broker FIFO;
        # far cheaper than an [A, C] sort), and node c accepts its
        # top m_c bidders where m_c = floor(min_d free_dc /
        # max-bidder-ask_dc) — accepting m rows each no larger than
        # the max ask can never overcommit the node.  The top bidder
        # is always accepted (its individual bid proved fit against
        # this round's free), so every bid-receiving node makes
        # progress each round — and a storm of identical asks fills
        # a node in ONE round instead of one-acceptance-at-a-time
        same = (
            (bid_c[:, None] == bid_c[None, :])
            & has_bid[:, None]
            & has_bid[None, :]
        )
        better = (bid_v[None, :] > bid_v[:, None]) | (
            (bid_v[None, :] == bid_v[:, None])
            & (row_ids[None, :] < row_ids[:, None])
        )
        rank = jnp.sum(same & better, axis=1).astype(jnp.int32)
        onehot = (bid_c[:, None] == node_ids[None, :]) & has_bid[
            :, None
        ]
        maxask = jnp.max(
            jnp.where(
                onehot[:, :, None], inp.ask[:, None, :], 0.0
            ),
            axis=0,
        )  # [C, 3]
        m = jnp.min(
            jnp.where(
                maxask > 0,
                jnp.floor(free / jnp.maximum(maxask, 1e-9)),
                jnp.inf,
            ),
            axis=1,
        )
        accepted = has_bid & ((rank == 0) | (rank < m[bid_c]))
        assigned = jnp.where(accepted, bid_c, assigned)
        acc_round = jnp.where(accepted, rnd, acc_round)
        acc_oh = (onehot & accepted[:, None]).astype(dtype)
        free = free - acc_oh.T @ inp.ask
        price = price + jnp.where(
            jnp.any(onehot, axis=0),
            jnp.asarray(PRICE_EPS, dtype),
            0.0,
        ).astype(dtype)
        return (
            assigned, free, price, acc_round,
            rnd + 1, jnp.any(accepted),
        )

    assigned, _free, _price, acc_round, rounds, _prog = (
        jax.lax.while_loop(
            cond,
            body,
            (
                jnp.full(A, NO_NODE, jnp.int32),
                free0,
                jnp.zeros(C, dtype),
                jnp.full(A, -1, jnp.int32),
                jnp.asarray(0, jnp.int32),
                jnp.asarray(True),
            ),
        )
    )
    solved = assigned >= 0
    kept_walk = solved & (assigned == rows0)
    # pulls: exact serial walk pulls for rows that kept the greedy
    # pick; a diverged pick examined every candidate
    pulls = jnp.where(
        kept_walk, pulls0, si.n_candidates
    ).astype(jnp.int32)
    score = jnp.where(
        solved,
        jnp.take_along_axis(
            scores, jnp.clip(assigned, 0, C - 1)[:, None], axis=1
        )[:, 0],
        jnp.asarray(0.0, dtype=scores.dtype),
    )
    return assigned, pulls, acc_round, score, rows0, rounds


def pad_axis(arr: np.ndarray, n: int, fill) -> np.ndarray:
    """Pad ``arr``'s leading axis out to ``n`` rows of ``fill``."""
    if arr.shape[0] == n:
        return arr
    out = np.full((n,) + arr.shape[1:], fill, dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out
