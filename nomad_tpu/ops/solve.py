"""Global storm assignment solver: one device-resident solve for a
whole backlog of pending evals.

A placement storm (node drain, mass failure, dispatch scale-up) turns
into hundreds of pending evals of one job family.  The per-eval chunk
chain (batch_worker.py) walks them one placement at a time — fast per
walk, but the work is still factored per eval.  CvxCluster (PAPERS.md)
shows large granular allocation problems solved as ONE optimization
run orders of magnitude faster than per-item heuristics, and the
(pending-allocs x candidate-nodes) score matrix this repo already
computes (ops/score.py) is exactly that problem's cost matrix.

``storm_assignment`` coalesces the storm into a single jitted solve:

1. **Score matrix.** The shared ``_score_vectors`` kernel scores every
   (alloc row, node) pair in one broadcasted pass — same fit masks,
   bin-packing curve, anti-affinity/penalty/affinity terms as the
   serial chain, against the device-resident usage mirror columns
   (plus the storm's staged pre-placement deltas).
2. **Greedy warm start.** Each row's serial pick — the shuffled
   limited-walk winner (``_limited_walk_argmax`` vmapped over rows,
   with each eval's recorded rng order and visit limit).  A one-row
   storm therefore converges to EXACTLY the chunk chain's selection
   (the degenerate-parity contract), pulls included.
3. **Auction rounds.** A ``lax.while_loop`` of bidding rounds resolves
   contention: every unassigned row bids its best value
   (score - node price) among nodes whose REMAINING capacity fits its
   ask; each node then accepts the best-value PREFIX of its bidders
   whose cumulative asks still fit (ties break to the lowest row
   index — broker FIFO), debits its capacity and raises its price.
   Acceptance never over-commits a node, and every bidding node
   accepts at least its top bidder per round (an individual bid
   already proved fit), so a storm of identical asks fills a node in
   ONE round instead of one-acceptance-at-a-time and the loop
   converges in a handful of rounds.  Rows left unassigned (nothing
   feasible fits, or the round budget ran out) return ``NO_NODE`` and
   their evals fall back to the serial chain — correctness never
   depends on the solver.

Serial equivalence is deliberately relaxed under contention: the
auction maximizes cluster-wide score, not arrival-order greed.  Every
divergence from the warm-start walk is reported per row (``greedy``
output) so the scheduler can tag explain records with the solver
round and assignment score.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .score import (
    NO_NODE,
    PolicyTerms,
    ScoreInputs,
    _limited_walk_argmax,
    _score_vectors,
)

# per-acceptance price increment: enough to tie-break repeated
# contention (scores live in roughly [-1, 1]) without distorting the
# score landscape for uncontended rows
PRICE_EPS = 0.01
# tie-spreading jitter, orders of magnitude below PRICE_EPS (the
# auction's own optimality tolerance): without it, every row whose
# value ties at the max bids argmax's FIRST index, so a storm of
# identical asks over hundreds of equally-scored nodes fills ONE
# node per round instead of spreading — O(rows/node-capacity)
# rounds.  The jitter only picks WHICH of the tied-max nodes a row
# bids; the bid value itself stays un-jittered, so assignment
# scores and the round-0 warm-start parity are untouched.
TIE_JITTER = 1e-6


class StormInputs(NamedTuple):
    """Host-staged inputs of one storm solve.  ``E`` evals contribute
    ``A`` pending-alloc rows over the ``C``-row node arena; per-eval
    vectors are gathered per row through ``eval_of`` so E-axis data is
    staged once per eval, not once per placement."""

    feasible: jnp.ndarray  # bool[E, C] static feasibility per eval
    affinity: jnp.ndarray  # f[E, C] normalized affinity score
    collisions: jnp.ndarray  # i32[E, C] anti-affinity base counts
    perm: jnp.ndarray  # i32[E, C] recorded serial walk order
    limit: jnp.ndarray  # i32[E] visit limit (INT32_MAX = unlimited)
    n_cand: jnp.ndarray  # i32[E] real candidates at perm's front
    eval_of: jnp.ndarray  # i32[A] row -> eval index
    penalty: jnp.ndarray  # bool[A, C] reschedule-penalty nodes
    ask: jnp.ndarray  # f[A, 3] cpu/mem/disk ask per row
    desired: jnp.ndarray  # i32[A] tg.count per row
    real: jnp.ndarray  # bool[A] padding rows are never assigned
    pre_cpu: jnp.ndarray  # f[C] staged pre-placement usage deltas
    pre_mem: jnp.ndarray  # f[C]
    pre_disk: jnp.ndarray  # f[C]
    # policy-weighted scoring (sched/policy.py): absent (None) for
    # unweighted storms — None fields contribute no pytree leaves, so
    # the unweighted solve keeps today's compiled signatures and
    # traces bit-identically.  A weighted storm stages PRE-SCALED
    # per-eval term rows (ops/score.py PolicyTerms); policy-less evals
    # in a mixed storm carry all-zero rows, which add float-exactly
    # nothing, so ONE compiled signature covers every mix.
    policy_tput_term: Optional[jnp.ndarray] = None  # f[E, C] coef*tput
    policy_has_tput: Optional[jnp.ndarray] = None  # f[E] 0/1 flag
    policy_mig_term: Optional[jnp.ndarray] = None  # f[E, C] coef*mig


@functools.partial(
    jax.jit, static_argnames=("spread_fit", "max_rounds")
)
def storm_assignment(
    inp: StormInputs, cols, spread_fit: bool, max_rounds: int
):
    """Returns ``(assigned, pulls, accept_round, score, greedy,
    rounds)``:

    - assigned i32[A]: arena node row per alloc row, NO_NODE unsolved
    - pulls i32[A]: serial walk pulls when the row kept its greedy
      pick (exact chunk-chain pulls), the candidate count otherwise
    - accept_round i32[A]: auction round the row was accepted in
      (0 = warm start / uncontended; -1 = unsolved)
    - score f[A]: the assignment's score matrix entry
    - greedy i32[A]: the warm-start serial-walk winner, for
      divergence accounting
    - rounds i32: auction rounds run before convergence
    """
    cpu_t, mem_t, disk_t, cpu_u, mem_u, disk_u = cols
    dtype = cpu_t.dtype
    cpu_u = cpu_u + inp.pre_cpu
    mem_u = mem_u + inp.pre_mem
    disk_u = disk_u + inp.pre_disk
    A = inp.ask.shape[0]
    C = cpu_t.shape[0]
    eo = inp.eval_of

    # broadcasted score matrix: [C] shared columns + [A, 1] per-row
    # asks flow through the SAME kernel the serial walk uses, so a
    # storm row's score of a node is bit-identical to the chunk
    # chain's first-pick score of it
    si = ScoreInputs(
        cpu_total=cpu_t,
        mem_total=mem_t,
        disk_total=disk_t,
        cpu_used=cpu_u,
        mem_used=mem_u,
        disk_used=disk_u,
        feasible=inp.feasible[eo],
        collisions=inp.collisions[eo],
        penalty=inp.penalty,
        affinity_score=inp.affinity[eo],
        spread_boost=jnp.zeros((), dtype),
        perm=inp.perm[eo],
        ask_cpu=inp.ask[:, 0:1],
        ask_mem=inp.ask[:, 1:2],
        ask_disk=inp.ask[:, 2:3],
        desired_count=inp.desired[:, None],
        limit=inp.limit[eo],
        n_candidates=inp.n_cand[eo],
        policy=(
            None
            if inp.policy_tput_term is None
            else PolicyTerms(
                tput_term=inp.policy_tput_term[eo],
                has_tput=inp.policy_has_tput[eo][:, None],
                mig_term=inp.policy_mig_term[eo],
            )
        ),
    )
    feas, scores = _score_vectors(si, spread_fit)
    feas = feas & inp.real[:, None]

    # greedy warm start: the serial chain's shuffled limited walk,
    # one row at a time (vmapped) — the uncontended answer, and the
    # degenerate one-row storm's EXACT answer
    rows0, _best0, _nf, pulls0 = jax.vmap(_limited_walk_argmax)(
        feas, scores, si.perm, si.limit, si.n_candidates
    )

    neg_inf = jnp.asarray(-jnp.inf, dtype=scores.dtype)
    row_ids = jnp.arange(A, dtype=jnp.int32)
    node_ids = jnp.arange(C, dtype=jnp.int32)
    # deterministic per-(row, node) tie-spreading perturbation (see
    # TIE_JITTER): a fixed Knuth-hash lattice, no RNG state
    jitter = (
        (
            (
                row_ids[:, None] * jnp.int32(-1640531527)
                + node_ids[None, :] * jnp.int32(40503)
            )
            & jnp.int32(0xFFFF)
        ).astype(scores.dtype)
        / 65536.0
        * jnp.asarray(TIE_JITTER, scores.dtype)
    )
    free0 = jnp.stack(
        [cpu_t - cpu_u, mem_t - mem_u, disk_t - disk_u], axis=1
    )
    rows0_c = jnp.clip(rows0, 0, C - 1)

    def cond(st):
        _assigned, _free, _price, _acc, rnd, progress = st
        return (rnd < max_rounds) & progress

    def body(st):
        assigned, free, price, acc_round, rnd, _progress = st
        unass = (assigned == NO_NODE) & inp.real
        fits = jnp.all(
            free[None, :, :] >= inp.ask[:, None, :], axis=2
        )
        ok = feas & fits & unass[:, None]
        value = jnp.where(ok, scores - price[None, :], neg_inf)
        # argmax over the jittered value picks WHICH tied-max node a
        # row bids (spreading ties across equal nodes); the bid's
        # VALUE is read back un-jittered
        best_c = jnp.argmax(value + jitter, axis=1).astype(jnp.int32)
        best_v = jnp.take_along_axis(
            value, best_c[:, None], axis=1
        )[:, 0]
        # round 0 bids the serial walk winner when it still fits, so
        # an uncontended storm IS the greedy walk; later rounds bid
        # the price-adjusted argmax (global quality)
        walk_v = jnp.take_along_axis(
            value, rows0_c[:, None], axis=1
        )[:, 0]
        use_walk = (rnd == 0) & (rows0 >= 0) & (walk_v > neg_inf)
        bid_c = jnp.where(use_walk, rows0_c, best_c)
        bid_v = jnp.where(use_walk, walk_v, best_v)
        has_bid = bid_v > neg_inf
        # per-node PREFIX acceptance: each row's rank among its bid
        # node's bidders comes from an [A, A] comparison (value
        # descending, ties to the lowest row index — broker FIFO;
        # far cheaper than an [A, C] sort), and node c accepts its
        # top m_c bidders where m_c = floor(min_d free_dc /
        # max-bidder-ask_dc) — accepting m rows each no larger than
        # the max ask can never overcommit the node.  The top bidder
        # is always accepted (its individual bid proved fit against
        # this round's free), so every bid-receiving node makes
        # progress each round — and a storm of identical asks fills
        # a node in ONE round instead of one-acceptance-at-a-time
        same = (
            (bid_c[:, None] == bid_c[None, :])
            & has_bid[:, None]
            & has_bid[None, :]
        )
        better = (bid_v[None, :] > bid_v[:, None]) | (
            (bid_v[None, :] == bid_v[:, None])
            & (row_ids[None, :] < row_ids[:, None])
        )
        rank = jnp.sum(same & better, axis=1).astype(jnp.int32)
        onehot = (bid_c[:, None] == node_ids[None, :]) & has_bid[
            :, None
        ]
        maxask = jnp.max(
            jnp.where(
                onehot[:, :, None], inp.ask[:, None, :], 0.0
            ),
            axis=0,
        )  # [C, 3]
        m = jnp.min(
            jnp.where(
                maxask > 0,
                jnp.floor(free / jnp.maximum(maxask, 1e-9)),
                jnp.inf,
            ),
            axis=1,
        )
        accepted = has_bid & ((rank == 0) | (rank < m[bid_c]))
        assigned = jnp.where(accepted, bid_c, assigned)
        acc_round = jnp.where(accepted, rnd, acc_round)
        acc_oh = (onehot & accepted[:, None]).astype(dtype)
        free = free - acc_oh.T @ inp.ask
        price = price + jnp.where(
            jnp.any(onehot, axis=0),
            jnp.asarray(PRICE_EPS, dtype),
            0.0,
        ).astype(dtype)
        return (
            assigned, free, price, acc_round,
            rnd + 1, jnp.any(accepted),
        )

    assigned, _free, _price, acc_round, rounds, _prog = (
        jax.lax.while_loop(
            cond,
            body,
            (
                jnp.full(A, NO_NODE, jnp.int32),
                free0,
                jnp.zeros(C, dtype),
                jnp.full(A, -1, jnp.int32),
                jnp.asarray(0, jnp.int32),
                jnp.asarray(True),
            ),
        )
    )
    solved = assigned >= 0
    kept_walk = solved & (assigned == rows0)
    # pulls: exact serial walk pulls for rows that kept the greedy
    # pick; a diverged pick examined every candidate
    pulls = jnp.where(
        kept_walk, pulls0, si.n_candidates
    ).astype(jnp.int32)
    score = jnp.where(
        solved,
        jnp.take_along_axis(
            scores, jnp.clip(assigned, 0, C - 1)[:, None], axis=1
        )[:, 0],
        jnp.asarray(0.0, dtype=scores.dtype),
    )
    return assigned, pulls, acc_round, score, rows0, rounds


_storm_sharded_cache: dict = {}


def storm_in_specs(weighted: bool = False) -> "StormInputs":
    """The node-sharded solve's `StormInputs` PartitionSpecs — the
    ONE definition shared by `storm_assignment_sharded` (shard_map
    in_specs) and `sched/storm.py stage_for_mesh` (host staging), so
    placement and program can never drift (same contract as
    `parallel/mesh.py chain_in_specs` for the chained runner):
    node-indexed leaves shard `P('nodes')`, per-eval / per-row
    leaves replicate.  ``weighted`` mirrors the input layout: the
    policy leaves stay None (no pytree leaves) for unweighted storms
    and shard like their siblings when staged."""
    from jax.sharding import PartitionSpec as P

    node2 = P(None, "nodes")
    col = P("nodes")
    rep = P()
    return StormInputs(
        feasible=node2,
        affinity=node2,
        collisions=node2,
        perm=rep,
        limit=rep,
        n_cand=rep,
        eval_of=rep,
        penalty=node2,
        ask=rep,
        desired=rep,
        real=rep,
        pre_cpu=col,
        pre_mem=col,
        pre_disk=col,
        policy_tput_term=node2 if weighted else None,
        policy_has_tput=rep if weighted else None,
        policy_mig_term=node2 if weighted else None,
    )


def storm_assignment_sharded(
    mesh, spread_fit: bool, max_rounds: int, weighted: bool = False
):
    """Node-sharded twin of `storm_assignment` for the (multi-host)
    mesh: BIT-IDENTICAL in every output — assignments, pulls,
    acceptance rounds, scores, greedy picks AND the round count — to
    the single-device solve on the same inputs, with the O(A x C)
    work distributed along the node axis the mesh already shards.

    The auction decomposes along exactly that axis (the CvxCluster
    observation: bid/accept rounds are per-node parallel by
    construction):

    * **Score matrix** — each device runs the shared `_score_vectors`
      kernel over its own ``C/D`` node shard of the usage-mirror
      columns: [A, C/D] local scores, zero communication.
    * **Bid phase** — rows bid against their LOCAL node shard: the
      per-shard max of the tie-jittered value plus the lowest local
      index achieving it, then one ``pmax``/``pmin`` pair (O(A)
      scalars, not O(C)) picks each row's global winner — the same
      node argmax-first-index would pick on one device, bit-for-bit,
      because max is exact and the jitter lattice is computed from
      GLOBAL node ids.
    * **Acceptance** — per-node prefix acceptance stays shard-local:
      each node's bidder one-hots, max-ask budget ``m`` and
      capacity/price debits live on the shard that owns the node; the
      [A, A] rank comparison is replicated per-row math.  Reads of a
      single node's value/budget by its (replicated) row resolve by
      ownership: the owning shard contributes, everyone else adds
      0.0, one psum — exact, since only one shard owns any node.
    * **Warm start** — the greedy serial walk needs the full permuted
      score vector, so scores/feasibility all-gather ONCE before the
      round loop ([A, C] f+bool, freed after `_limited_walk_argmax`);
      the per-round auction state never gathers.

    Compiled runners are cached per (mesh, spread_fit, max_rounds);
    inputs follow `sched/storm.py stage_for_mesh`'s placement (node-
    axis leaves sharded P('nodes'), per-row leaves replicated) and the
    sharded usage-mirror columns feed ``cols`` directly.  Requires
    the arena capacity to tile evenly over the mesh (the caller's
    ``mesh_capable`` gate)."""
    key = (mesh, bool(spread_fit), int(max_rounds), bool(weighted))
    fn = _storm_sharded_cache.get(key)
    if fn is not None:
        return fn
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import shard_map

    in_specs = (storm_in_specs(weighted), (P("nodes"),) * 6)
    out_specs = (P(),) * 6

    def _run(inp: StormInputs, cols):
        cpu_t, mem_t, disk_t, cpu_u, mem_u, disk_u = cols
        dtype = cpu_t.dtype
        cpu_u = cpu_u + inp.pre_cpu
        mem_u = mem_u + inp.pre_mem
        disk_u = disk_u + inp.pre_disk
        A = inp.ask.shape[0]
        C = inp.perm.shape[1]  # global arena rows
        S = cpu_t.shape[0]  # this shard's rows
        shard = jax.lax.axis_index("nodes")
        lo = (shard * S).astype(jnp.int32)
        node_l = lo + jnp.arange(S, dtype=jnp.int32)
        eo = inp.eval_of

        si = ScoreInputs(
            cpu_total=cpu_t,
            mem_total=mem_t,
            disk_total=disk_t,
            cpu_used=cpu_u,
            mem_used=mem_u,
            disk_used=disk_u,
            feasible=inp.feasible[eo],
            collisions=inp.collisions[eo],
            penalty=inp.penalty,
            affinity_score=inp.affinity[eo],
            spread_boost=jnp.zeros((), dtype),
            perm=inp.perm[eo],  # global — consumed by the walk only
            ask_cpu=inp.ask[:, 0:1],
            ask_mem=inp.ask[:, 1:2],
            ask_disk=inp.ask[:, 2:3],
            desired_count=inp.desired[:, None],
            limit=inp.limit[eo],
            n_candidates=inp.n_cand[eo],
            policy=(
                None
                if inp.policy_tput_term is None
                else PolicyTerms(
                    # local node shard, same gather as feasible
                    tput_term=inp.policy_tput_term[eo],
                    has_tput=inp.policy_has_tput[eo][:, None],
                    mig_term=inp.policy_mig_term[eo],
                )
            ),
        )
        feas_l, scores_l = _score_vectors(si, spread_fit)
        feas_l = feas_l & inp.real[:, None]

        # warm start: the one full gather of the solve — the serial
        # walk consumes the global permuted ordering
        scores_full = jax.lax.all_gather(
            scores_l, "nodes", axis=1, tiled=True
        )
        feas_full = jax.lax.all_gather(
            feas_l, "nodes", axis=1, tiled=True
        )
        rows0, _best0, _nf, pulls0 = jax.vmap(
            _limited_walk_argmax
        )(feas_full, scores_full, si.perm, si.limit,
          si.n_candidates)

        neg_inf = jnp.asarray(-jnp.inf, dtype=scores_l.dtype)
        big = jnp.asarray(2**31 - 1, jnp.int32)
        row_ids = jnp.arange(A, dtype=jnp.int32)
        jitter_l = (
            (
                (
                    row_ids[:, None] * jnp.int32(-1640531527)
                    + node_l[None, :] * jnp.int32(40503)
                )
                & jnp.int32(0xFFFF)
            ).astype(scores_l.dtype)
            / 65536.0
            * jnp.asarray(TIE_JITTER, scores_l.dtype)
        )
        free0_l = jnp.stack(
            [cpu_t - cpu_u, mem_t - mem_u, disk_t - disk_u],
            axis=1,
        )
        rows0_c = jnp.clip(rows0, 0, C - 1)

        def read_row_at(arr_l, gidx):
            """Ownership read of [A, S]-local ``arr_l`` at the global
            node index ``gidx[A]``: the owning shard contributes its
            value, everyone else 0.0 — exact under psum (one owner)."""
            loc = gidx - lo
            mine = (loc >= 0) & (loc < S)
            safe = jnp.clip(loc, 0, S - 1)
            v = jnp.take_along_axis(
                arr_l, safe[:, None], axis=1
            )[:, 0]
            return jax.lax.psum(
                jnp.where(mine, v, jnp.zeros_like(v)), "nodes"
            )

        def read_node_at(vec_l, gidx):
            """Same ownership read for a node-indexed [S] vector."""
            loc = gidx - lo
            mine = (loc >= 0) & (loc < S)
            safe = jnp.clip(loc, 0, S - 1)
            v = vec_l[safe]
            return jax.lax.psum(
                jnp.where(mine, v, jnp.zeros_like(v)), "nodes"
            )

        def cond(st):
            _assigned, _free, _price, _acc, rnd, progress = st
            return (rnd < max_rounds) & progress

        def body(st):
            assigned, free_l, price_l, acc_round, rnd, _progress = st
            unass = (assigned == NO_NODE) & inp.real
            fits_l = jnp.all(
                free_l[None, :, :] >= inp.ask[:, None, :], axis=2
            )
            ok_l = feas_l & fits_l & unass[:, None]
            value_l = jnp.where(
                ok_l, scores_l - price_l[None, :], neg_inf
            )
            # the bid: per-shard jittered max + lowest local index at
            # it, then one pmax/pmin pair — the single-device
            # ``argmax(value + jitter)`` (first index at the max)
            # reconstructed exactly
            jv_l = value_l + jitter_l
            gmax = jax.lax.pmax(jnp.max(jv_l, axis=1), "nodes")
            cand_l = jv_l == gmax[:, None]
            lidx = jnp.min(
                jnp.where(cand_l, node_l[None, :], big), axis=1
            )
            best_c = jax.lax.pmin(lidx, "nodes").astype(jnp.int32)
            best_v = read_row_at(value_l, best_c)
            # round 0 bids the serial walk winner when it still fits,
            # so an uncontended storm IS the greedy walk
            walk_v = read_row_at(value_l, rows0_c)
            use_walk = (
                (rnd == 0) & (rows0 >= 0) & (walk_v > neg_inf)
            )
            bid_c = jnp.where(use_walk, rows0_c, best_c)
            bid_v = jnp.where(use_walk, walk_v, best_v)
            has_bid = bid_v > neg_inf
            # replicated [A, A] rank math — identical on every shard
            same = (
                (bid_c[:, None] == bid_c[None, :])
                & has_bid[:, None]
                & has_bid[None, :]
            )
            better = (bid_v[None, :] > bid_v[:, None]) | (
                (bid_v[None, :] == bid_v[:, None])
                & (row_ids[None, :] < row_ids[:, None])
            )
            rank = jnp.sum(same & better, axis=1).astype(jnp.int32)
            # shard-local prefix acceptance: bidder one-hots, max-ask
            # budget and the capacity/price debits all live on the
            # shard owning the node
            onehot_l = (
                bid_c[:, None] == node_l[None, :]
            ) & has_bid[:, None]
            maxask_l = jnp.max(
                jnp.where(
                    onehot_l[:, :, None], inp.ask[:, None, :], 0.0
                ),
                axis=0,
            )  # [S, 3]
            m_l = jnp.min(
                jnp.where(
                    maxask_l > 0,
                    jnp.floor(
                        free_l / jnp.maximum(maxask_l, 1e-9)
                    ),
                    jnp.inf,
                ),
                axis=1,
            )
            m_at_bid = read_node_at(m_l, bid_c)
            accepted = has_bid & ((rank == 0) | (rank < m_at_bid))
            assigned = jnp.where(accepted, bid_c, assigned)
            acc_round = jnp.where(accepted, rnd, acc_round)
            acc_oh_l = (onehot_l & accepted[:, None]).astype(dtype)
            free_l = free_l - acc_oh_l.T @ inp.ask
            price_l = price_l + jnp.where(
                jnp.any(onehot_l, axis=0),
                jnp.asarray(PRICE_EPS, dtype),
                0.0,
            ).astype(dtype)
            return (
                assigned, free_l, price_l, acc_round,
                rnd + 1, jnp.any(accepted),
            )

        assigned, _free, _price, acc_round, rounds, _prog = (
            jax.lax.while_loop(
                cond,
                body,
                (
                    jnp.full(A, NO_NODE, jnp.int32),
                    free0_l,
                    jnp.zeros(S, dtype),
                    jnp.full(A, -1, jnp.int32),
                    jnp.asarray(0, jnp.int32),
                    jnp.asarray(True),
                ),
            )
        )
        solved = assigned >= 0
        kept_walk = solved & (assigned == rows0)
        pulls = jnp.where(
            kept_walk, pulls0, si.n_candidates
        ).astype(jnp.int32)
        score = jnp.where(
            solved,
            read_row_at(
                scores_l, jnp.clip(assigned, 0, C - 1)
            ),
            jnp.asarray(0.0, dtype=scores_l.dtype),
        )
        return assigned, pulls, acc_round, score, rows0, rounds

    wrapped = functools.partial(
        shard_map, mesh=mesh, in_specs=in_specs,
        out_specs=out_specs,
    )(_run)
    fn = jax.jit(wrapped)
    fn.__name__ = (
        f"storm_assignment_sharded_r{max_rounds}"
        f"{'_spread' if spread_fit else ''}"
    )
    _storm_sharded_cache[key] = fn
    return fn


def pad_axis(arr: np.ndarray, n: int, fill) -> np.ndarray:
    """Pad ``arr``'s leading axis out to ``n`` rows of ``fill``."""
    if arr.shape[0] == n:
        return arr
    out = np.full((n,) + arr.shape[1:], fill, dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out
