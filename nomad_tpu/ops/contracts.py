"""Kernel shape-ladder contracts: the declared compiled-signature
ladders of the production kernels, checked by ``jax.eval_shape``
WITHOUT compiling anything.

Silent recompiles are the accelerator failure mode the CPU tier-1
suite structurally cannot see: a shape that misses its pow2 bucket,
or a weak-type promotion that forks an extra signature, shows up only
as a p99 latency cliff on the real backend (every novel signature is
a multi-second XLA compile in the hot path).  Each contract here
declares the EXACT ladder of input signatures a kernel is allowed to
compile, and ``check_contracts`` statically asserts:

1. **Ladder closure** — the declared ladder produces exactly
   ``len(ladder)`` distinct input signatures (no accidental bucket
   collapse, no per-size signature explosion);
2. **Dtype closure** — ``eval_shape`` over every rung succeeds and
   every output leaf's dtype stays inside the kernel's declared
   closed set with ``weak_type=False`` (a weak-typed output chained
   back in as an input would re-trace a second signature for the
   same shapes).

The ladders mirror the hot-path padding exactly: chunk widths are
``batch_worker.CHUNK_BUCKETS`` (the nomadlint ``kernel-contract``
rule cross-checks this file's ladder against that literal, so the
two cannot drift), storm problems are pow2-bucketed by
``sched/storm.build_storm_problem`` (E floor 4, A floor 8), and the
mesh ladder expresses the node-axis widths as their shard-local
column sizes (each mesh width partitions the same global C into a
distinct per-shard signature).
"""
from __future__ import annotations

from typing import Callable, Dict, List, NamedTuple, Tuple

import numpy as np

# the device mirror's canonical dtype: production runs x64-off, so
# the float64 host columns land on device as f32 (warm_shapes warms
# with device columns for exactly this reason)
F = np.float32
I = np.int32
B = np.bool_

# chunk-kernel eval-axis ladder — MUST equal
# batch_worker.CHUNK_BUCKETS (AST-cross-checked by nomadlint)
CHUNK_LADDER: Tuple[int, ...] = (2, 4, 8)
# storm (E, A) pow2 rungs exercised by the contract: the builder's
# floors (E>=4, A>=8) upward through the common storm sizes
STORM_LADDER: Tuple[Tuple[int, int], ...] = (
    (4, 8),
    (8, 16),
    (16, 64),
)
# node-axis mesh widths: each width shards the same global arena
# into C/width local columns — a distinct compiled signature per
# width (parallel/mesh.sharded_chained_plan caches one runner per
# (mesh, n_picks, ...) for the same reason)
MESH_WIDTHS: Tuple[int, ...] = (1, 2, 4, 8)
# MULTI-host node-axis widths (ROADMAP item 3): a NOMAD_TPU_DIST pod
# spans hosts x per-host devices, so the GLOBAL device count — and
# with it every shard-local column size, on EVERY process — walks
# this ladder.  A pod resize that silently forked an undeclared
# signature would recompile the chained runner AND the sharded storm
# solve on all hosts at once (a pod-wide p99 cliff); the
# `kernel-contract` nomadlint rule fails when this ladder is absent
# or collapsed
MESH_HOST_WIDTHS: Tuple[int, ...] = (8, 16, 32)
# fan-out pod widths: the GLOBAL device counts a follower-headed
# mesh may span (follower process + its pod peers, hosts x per-host
# devices).  Small by design — a fan-out follower heads a slice of
# the machine, not the whole pod — and a hard gate, not advisory:
# BatchWorker._attach_pod refuses to head a world whose width is
# undeclared here, because every undeclared width would compile a
# fresh chained-runner AND sharded-storm signature on N followers
# at once (the fan-out analogue of the pod-wide p99 cliff above)
MESH_FANOUT_WIDTHS: Tuple[int, ...] = (2, 4, 8)
# pod-scale arena rows (global) for the multi-host rungs: large
# enough that every declared width yields a distinct non-trivial
# shard-local column size
_C_POD = 512

# representative fixed dims (any consistent values work: signatures
# vary only along the declared ladder axis)
_C = 64  # arena rows (global)
_P = 16  # pick slots
_T = 1  # task-group axis
_K = 8  # MAX_PENALTY_NODES (batch_worker.py)


class KernelContract(NamedTuple):
    name: str
    # () -> jitted kernel (lazy: jax imports stay off module import)
    kernel: Callable
    # ladder of (args, kwargs) spec tuples; array leaves are
    # jax.ShapeDtypeStruct, statics are plain Python values
    ladder: List[Tuple[tuple, dict]]
    # allowed output dtypes (closed set)
    out_dtypes: frozenset


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(shape, dtype)


def _cols(c: int) -> tuple:
    return tuple(_sds((c,), F) for _ in range(6))


def _chain_args(e: int, c: int) -> Tuple[tuple, dict]:
    """One chunk-launch spec, mirroring warm_shapes' steady-state
    variant (deltas + pre present, return_carry=True) — the exact
    shape _launch_chunk dispatches."""
    from .batch import ChainInputs, PreDeltas, StepDeltas

    chain = ChainInputs(
        feasible=_sds((e, _T, c), B),
        perm=_sds((e, c), I),
        ask_cpu=_sds((e, _P), F),
        ask_mem=_sds((e, _P), F),
        ask_disk=_sds((e, _P), F),
        desired_count=_sds((e, _P), I),
        limit=_sds((e, _P), I),
        distinct_hosts=_sds((e,), B),
        tg_idx=_sds((e, _P), I),
    )
    deltas = StepDeltas(
        evict_rows=_sds((e, _P), I),
        evict_cpu=_sds((e, _P), F),
        evict_mem=_sds((e, _P), F),
        evict_disk=_sds((e, _P), F),
        evict_coll=_sds((e, _P), I),
        penalty_rows=_sds((e, _P, _K), I),
    )
    pre = PreDeltas(
        rows=_sds((e, 1), I),
        cpu=_sds((e, 1), F),
        mem=_sds((e, 1), F),
        disk=_sds((e, 1), F),
    )
    args = _cols(c) + (chain, _sds((e,), I), _P)
    kwargs = dict(
        spread_fit=False,
        wanted=_sds((e,), I),
        deltas=deltas,
        pre=pre,
        return_carry=True,
    )
    return args, kwargs


def _storm_args(
    e: int, a: int, c: int = _C, weighted: bool = False
) -> Tuple[tuple, dict]:
    from .solve import StormInputs

    inp = StormInputs(
        feasible=_sds((e, c), B),
        affinity=_sds((e, c), F),
        collisions=_sds((e, c), I),
        perm=_sds((e, c), I),
        limit=_sds((e,), I),
        n_cand=_sds((e,), I),
        eval_of=_sds((a,), I),
        penalty=_sds((a, c), B),
        ask=_sds((a, 3), F),
        desired=_sds((a,), I),
        real=_sds((a,), B),
        pre_cpu=_sds((c,), F),
        pre_mem=_sds((c,), F),
        pre_disk=_sds((c,), F),
        # the policy-weighted variant adds three leaves — pre-scaled
        # term rows plus the append-count flag (sched/policy staging);
        # the unweighted pytree keeps them None — absent leaves, so
        # the base ladder's signatures are untouched
        policy_tput_term=_sds((e, c), F) if weighted else None,
        policy_has_tput=_sds((e,), F) if weighted else None,
        policy_mig_term=_sds((e, c), F) if weighted else None,
    )
    return (inp, _cols(c)), dict(
        spread_fit=False, max_rounds=a
    )


def _chunk_kernel():
    from .batch import chained_plan_picks_cols

    return chained_plan_picks_cols


def _storm_kernel():
    from .solve import storm_assignment

    return storm_assignment


def iter_contracts() -> List[KernelContract]:
    """The production contracts: chunk, storm, mesh."""
    chunk = KernelContract(
        name="chunk",
        kernel=_chunk_kernel,
        ladder=[_chain_args(e, _C) for e in CHUNK_LADDER],
        out_dtypes=frozenset({"int32", "float32", "bool"}),
    )
    storm = KernelContract(
        name="storm",
        kernel=_storm_kernel,
        ladder=[_storm_args(e, a) for e, a in STORM_LADDER],
        out_dtypes=frozenset({"int32", "float32", "bool"}),
    )
    # the policy-weighted storm variant: a weighted storm carries
    # three extra pytree leaves (policy_* — sched/storm staging), so
    # every (E, A) rung forks ONE additional declared signature; a
    # policy-less storm stays bit-on the base storm ladder (None
    # fields contribute no leaves)
    storm_weighted = KernelContract(
        name="storm_weighted",
        kernel=_storm_kernel,
        ladder=[
            _storm_args(e, a, weighted=True)
            for e, a in STORM_LADDER
        ],
        out_dtypes=frozenset({"int32", "float32", "bool"}),
    )
    # the mesh ladder: each node-axis width w runs the chained
    # kernel over C/w shard-local columns — the per-width compiled
    # signature the sharded runner cache keys on.  Expressed through
    # the unsharded kernel so the contract needs no multi-device
    # mesh to check (eval_shape of the shard body over local shapes
    # IS the per-device signature).
    mesh = KernelContract(
        name="mesh",
        kernel=_chunk_kernel,
        ladder=[
            _chain_args(CHUNK_LADDER[-1], _C // w)
            for w in MESH_WIDTHS
        ],
        out_dtypes=frozenset({"int32", "float32", "bool"}),
    )
    # the multi-host ladders: a pod of W global devices runs every
    # per-shard program over C_pod/W local columns on EVERY process —
    # one distinct compiled signature per declared pod width, for
    # both the chained runner (mesh_host) and the sharded storm
    # auction (storm_mesh).  Expressed through the unsharded kernels
    # over shard-local shapes so the contract needs no live
    # multi-process world to check: eval_shape of the shard body over
    # local columns IS the per-device signature (modulo the
    # replicated walk inputs, which do not vary along this ladder).
    mesh_host = KernelContract(
        name="mesh_host",
        kernel=_chunk_kernel,
        ladder=[
            _chain_args(CHUNK_LADDER[-1], _C_POD // w)
            for w in MESH_HOST_WIDTHS
        ],
        out_dtypes=frozenset({"int32", "float32", "bool"}),
    )
    storm_mesh = KernelContract(
        name="storm_mesh",
        kernel=_storm_kernel,
        ladder=[
            _storm_args(
                STORM_LADDER[-1][0],
                STORM_LADDER[-1][1],
                _C_POD // w,
            )
            for w in MESH_HOST_WIDTHS
        ],
        out_dtypes=frozenset({"int32", "float32", "bool"}),
    )
    # the fan-out ladders: a follower-headed pod of W global devices
    # (parallel/pod.py streams the launch sequence; every member —
    # head and peers — compiles the same per-shard program over
    # C_pod/W local columns).  Same expression trick as mesh_host:
    # the unsharded kernel over shard-local shapes needs no live
    # world.  _attach_pod gates the live width against
    # MESH_FANOUT_WIDTHS so no follower can compile off-ladder.
    mesh_fanout = KernelContract(
        name="mesh_fanout",
        kernel=_chunk_kernel,
        ladder=[
            _chain_args(CHUNK_LADDER[-1], _C_POD // w)
            for w in MESH_FANOUT_WIDTHS
        ],
        out_dtypes=frozenset({"int32", "float32", "bool"}),
    )
    storm_fanout = KernelContract(
        name="storm_fanout",
        kernel=_storm_kernel,
        ladder=[
            _storm_args(
                STORM_LADDER[-1][0],
                STORM_LADDER[-1][1],
                _C_POD // w,
            )
            for w in MESH_FANOUT_WIDTHS
        ],
        out_dtypes=frozenset({"int32", "float32", "bool"}),
    )
    return [
        chunk, storm, storm_weighted, mesh, mesh_host, storm_mesh,
        mesh_fanout, storm_fanout,
    ]


def _signature(args: tuple, kwargs: dict) -> tuple:
    """Canonical input signature: flattened (shape, dtype) leaves +
    static values — what jit keys its executable cache on."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = [str(treedef)]
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            sig.append((tuple(leaf.shape), str(leaf.dtype)))
        else:
            sig.append(("static", repr(leaf)))
    return tuple(sig)


def check_contracts(contracts=None) -> List[str]:
    """Run every contract; returns human-readable violations (empty
    = all green).  Uses ``eval_shape`` only — nothing compiles, so
    the whole pass runs in milliseconds at lint/import time."""
    import jax

    violations: List[str] = []
    for contract in (
        contracts if contracts is not None else iter_contracts()
    ):
        kernel = contract.kernel()
        sigs: Dict[tuple, int] = {}
        for rung, (args, kwargs) in enumerate(contract.ladder):
            sig = _signature(args, kwargs)
            if sig in sigs:
                violations.append(
                    f"{contract.name}: ladder rung {rung} "
                    f"collapses onto rung {sigs[sig]} — two "
                    "declared shapes compile ONE signature, so "
                    "the ladder overstates its coverage"
                )
                continue
            sigs[sig] = rung
            try:
                eval_shape = getattr(
                    kernel, "eval_shape", None
                )
                if eval_shape is not None:
                    out = eval_shape(*args, **kwargs)
                else:
                    out = jax.eval_shape(
                        kernel, *args, **kwargs
                    )
            except Exception as exc:  # noqa: BLE001
                violations.append(
                    f"{contract.name}: rung {rung} failed "
                    f"eval_shape: {type(exc).__name__}: {exc}"
                )
                continue
            for leaf in jax.tree_util.tree_leaves(out):
                dt = str(getattr(leaf, "dtype", ""))
                if dt not in contract.out_dtypes:
                    violations.append(
                        f"{contract.name}: rung {rung} output "
                        f"dtype {dt} escapes the declared "
                        f"closure {sorted(contract.out_dtypes)}"
                        " — a promoted output chained back in "
                        "forks a second compiled signature"
                    )
                if getattr(leaf, "weak_type", False):
                    violations.append(
                        f"{contract.name}: rung {rung} output "
                        "is weak-typed — weak types silently "
                        "re-trace when mixed with strong inputs"
                    )
        if len(sigs) != len(contract.ladder):
            violations.append(
                f"{contract.name}: {len(sigs)} distinct compiled "
                f"signatures != declared ladder of "
                f"{len(contract.ladder)}"
            )
    return violations
