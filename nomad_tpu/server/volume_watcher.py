"""CSI volume watcher — releases volume claims as their allocations
reach terminal state.

Plays the role of the reference's leader-only volume watcher
(`nomad/volumewatcher/volumes_watcher.go`): there, a per-volume goroutine
follows the volume via blocking queries and unpublishes/releases claims
once claiming allocs are terminal.  Here claims live directly on the
`CSIVolume` record (alloc id -> node id), so the watcher is a single
sweep: any claim whose alloc is gone or terminal is dropped, which
immediately restores claim capacity for blocked placements.
"""
from __future__ import annotations

import threading
from typing import Optional


class VolumeWatcher:
    def __init__(self, server, interval: float = 0.1) -> None:
        self.server = server
        self.store = server.store
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="volume-watcher", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        # claims only change when state changes: block on the store's
        # change condition (the in-proc blocking-query primitive)
        # instead of sweeping on a fixed interval
        last = -1
        while not self._stop.is_set():
            try:
                idx = self.store.wait_for_change(last, timeout=0.5)
                if idx == last:
                    continue
                last = idx
                self._stop.wait(self.interval)  # debounce bursts
                self.sync()
            except Exception:  # noqa: BLE001 — keep the watcher alive
                pass

    def sync(self) -> int:
        """One reconciliation sweep; returns how many allocs had claims
        released (testing hook — the background loop calls this)."""
        released = 0
        for vol in list(self.store.csi_volumes.values()):
            for alloc_id in list(vol.read_claims) + list(vol.write_claims):
                alloc = self.store.alloc_by_id(alloc_id)
                # release only once the CLIENT is done with the volume
                # (reference releases after node unpublish completes):
                # client-terminal, never handed to a client (stopped
                # while still pending), or gone from state entirely
                done = (
                    alloc is None
                    or alloc.client_terminal_status()
                    or (
                        alloc.terminal_status()
                        and alloc.client_status
                        == "pending"
                    )
                )
                if done:
                    # the facade raft-applies on clusters; idempotent
                    self.store.release_csi_claims_for_alloc(alloc_id)
                    released += 1
        if released:
            # freed claim capacity can unblock evals the same way node
            # capacity does (reference volumewatcher -> blocked evals)
            self.server.blocked.unblock_all(self.store.latest_index())
        return released
