from .eval_broker import EvalBroker  # noqa: F401
from .blocked_evals import BlockedEvals  # noqa: F401
from .plan_queue import PlanQueue  # noqa: F401
from .plan_apply import PlanApplier, evaluate_plan  # noqa: F401
from .worker import Worker  # noqa: F401
from .server import Server  # noqa: F401
