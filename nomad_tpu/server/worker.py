"""Scheduling worker (reference nomad/worker.go).

Each worker loops: dequeue an eval from the broker, fence the state at
the eval's modify index (snapshot_min_index, worker.go:228), run the
registered scheduler for the eval type, and ack/nack.  The worker is the
scheduler's `Planner`: plans go to the plan queue and the worker blocks
for the applier's verdict; a partial commit hands back a refreshed
snapshot so the scheduler retries against fresh state (worker.go:277-339
SubmitPlan / RefreshIndex).
"""
from __future__ import annotations

import threading
from typing import List, Optional, Tuple

from ..explain import EXPLAIN
from ..raft import NotLeaderError
from ..sched import new_scheduler
from ..state.store import StateSnapshot, StateStore
from ..structs import Evaluation, Plan, PlanResult, EVAL_STATUS_BLOCKED
from ..trace import TRACE


class Worker:
    def __init__(
        self,
        server,
        schedulers: Optional[List[str]] = None,
        seed: Optional[int] = None,
    ) -> None:
        self.server = server
        self.store: StateStore = server.store
        self.schedulers = schedulers or ["service", "batch", "system", "_core"]
        self.seed = seed
        # when True, sequential eval processing uses the exact host
        # stack even with the TPU scheduler enabled.  The BatchWorker
        # sets it: its fallbacks are precisely the shapes where
        # batching didn't apply, and a per-select device round trip
        # per pick loses to the host oracle there (decisions are
        # bit-identical either way)
        self.host_fallback = False
        self._stop = threading.Event()
        self._paused = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.evals_processed = 0
        # cumulative wall seconds this worker spent BLOCKED on the
        # serialized commit plane (plan-queue verdicts, and for
        # follower fan-out workers the remote submit RPC + local-
        # apply catch-up).  Kept separate from the planning-stage
        # timings: the fan-out bench reports planning busy-time net
        # of commit waits, since commit is the part that stays
        # serialized by design while planning scales with servers.
        self.plan_wait_s = 0.0

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        # leadership can be re-established on the same server (revoke
        # -> establish): the previous generation's thread must not
        # race the new one for the worker's shared pipeline state.
        # Post-revoke threads exit fast (the leadership fence aborts
        # open chains and the broker is disabled), so the join is
        # pro-forma — but a straggler that outlives it (e.g. blocked
        # in a 10s plan wait) is fenced by _current_generation(): the
        # moment self._thread points at the new thread, the old one's
        # next loop check exits it regardless of the cleared _stop.
        prev = self._thread
        if prev is not None and prev.is_alive():
            prev.join(timeout=5.0)
        # the thread name carries the owning server's address (when
        # it has one — cluster servers do) so per-thread accounting
        # (/proc/self/task/*/stat, py-spy, the fan-out bench's
        # planning-CPU attribution) can tell one server's workers
        # from another's inside a multi-server test process
        addr = getattr(self.server, "addr", "")
        thread = threading.Thread(
            target=self.run,
            name=f"worker@{addr}" if addr else "worker",
            daemon=True,
        )
        self._thread = thread
        self._stop.clear()
        thread.start()

    def _current_generation(self) -> bool:
        """Whether the calling thread is this worker's CURRENT run()
        thread.  True as well for direct run() calls outside start()
        (test harnesses)."""
        current = self._thread
        return current is None or current is threading.current_thread()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def set_pause(self, paused: bool) -> None:
        """Leaders pause half their workers to favor broker/plan work
        (reference leader.go establishLeadership)."""
        if paused:
            self._paused.set()
        else:
            self._paused.clear()

    def run(self) -> None:
        while not self._stop.is_set() and self._current_generation():
            if self._paused.is_set():
                self._stop.wait(0.05)
                continue
            ev, token = self.server.broker.dequeue(
                self.schedulers, timeout=0.1
            )
            if ev is None:
                continue
            try:
                self.process_eval(ev, token)
            except Exception:  # noqa: BLE001
                try:
                    self.server.broker.nack(ev.id, token)
                except ValueError:
                    pass

    # -- one eval ------------------------------------------------------

    def process_eval(self, ev: Evaluation, token: str) -> None:
        try:
            snap = self.store.snapshot_min_index(
                max(ev.modify_index, ev.snapshot_index), timeout=5.0
            )
        except TimeoutError:
            self.server.broker.nack(ev.id, token)
            return
        # stamp the state fence, so a later Block() can tell whether a
        # capacity change arrived after this scheduling pass (reference
        # worker.go:277 attaches SnapshotIndex to submitted plans)
        ev.snapshot_index = snap.index
        self._eval_token = token
        self._pending_evals: List[Evaluation] = []
        metrics = getattr(self.server, "metrics", None)
        scheduler = new_scheduler(
            ev.type, snap, self, seed=self.seed,
            # host_fallback only demotes the per-pick generic TPU
            # stack; system evals keep TPUSystemStack — one whole-fleet
            # launch per eval, measured faster than the host chain at
            # scale (tests/test_system_tpu.py)
            use_tpu=(
                self.store.get_scheduler_config().tpu_scheduler_enabled
                and (ev.type == "system" or not self.host_fallback)
            ),
        )
        import time as _time

        start = _time.monotonic()
        try:
            with TRACE.span(
                ev.id, "worker.invoke_scheduler",
                type=ev.type,
                speculative=getattr(scheduler, "speculative", False),
            ):
                scheduler.process(ev)
        except NotLeaderError:
            # leadership moved while this eval was in flight (the plan
            # applier rejected the plan, or the replicated fence
            # tripped): nack for redelivery — the next leader's broker
            # re-runs it against restored state.  Not an error.
            try:
                self.server.broker.nack(ev.id, token)
            except ValueError:
                pass  # the revoke flush already unacked the lease
            return
        except Exception:  # noqa: BLE001
            self.server.broker.nack(ev.id, token)
            raise
        if metrics is not None:
            # (reference worker.go:245 invoke_scheduler timing)
            metrics.add_sample(
                f"worker.invoke_scheduler_{ev.type}",
                (_time.monotonic() - start) * 1000.0,
            )
            metrics.incr("worker.evals_processed")
        # placement explainability: retain this eval's per-TG score
        # decomposition + filter attribution (/v1/evaluation/<id>/
        # placement), cross-linked with its flight-recorder trace
        EXPLAIN.record_eval(ev, scheduler, metrics)
        self.evals_processed += 1
        self.server.broker.ack(ev.id, token)

    # -- Planner interface (scheduler.go:112) --------------------------

    def submit_plan(
        self, plan: Plan
    ) -> Tuple[PlanResult, Optional[StateSnapshot]]:
        import time as _time

        if getattr(plan, "leader_gen", None) is None:
            # serial paths stamp the current generation at submit
            # time (their plans cannot straggle across a leadership
            # change: the plan queue flush kills them on revoke);
            # wave commits stamp their captured generation upstream
            plan.leader_gen = getattr(
                self.server, "_leadership_gen", None
            )
        plan.snapshot_index = self.store.latest_index()
        t0 = _time.monotonic()
        try:
            pending = self.server.plan_queue.enqueue(plan)
            result = pending.wait(timeout=10.0)
            if result is None:
                raise RuntimeError("plan rejected")
            if result.refresh_index:
                snap = self.store.snapshot_min_index(
                    result.refresh_index
                )
                return result, snap
            return result, None
        finally:
            self.plan_wait_s += _time.monotonic() - t0

    def update_eval(self, ev: Evaluation) -> None:
        self.store.upsert_evals([ev])
        self.server.on_eval_update(ev)

    def create_eval(self, ev: Evaluation) -> None:
        self.store.upsert_evals([ev])
        self.server.on_eval_update(ev)

    def reblock_eval(self, ev: Evaluation) -> None:
        self.store.upsert_evals([ev])
        self.server.blocked.block(ev)
