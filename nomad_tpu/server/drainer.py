"""Node drainer: job-aware migration of allocations off draining nodes
(reference nomad/drainer/drainer.go:130, watch_jobs.go, drain_heap.go).

For each draining node, allocations migrate in batches bounded by each
task group's `migrate` stanza max_parallel: a new batch is released only
when the previously-migrated allocs' replacements are healthy elsewhere.
System-job allocs drain last (after all service/batch allocs are gone)
unless ignore_system_jobs is set.  A drain deadline force-migrates
whatever remains.  When a node has nothing left to drain, its drain flag
clears and the node stays ineligible.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ..structs import (
    ALLOC_CLIENT_STATUS_RUNNING,
    Allocation,
    JOB_TYPE_SYSTEM,
    Node,
)


class Drainer:
    def __init__(self, server, interval: float = 0.1) -> None:
        self.server = server
        self.store = server.store
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="drainer", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    # ------------------------------------------------------------------

    def _run(self) -> None:
        # the draining set is recomputed only when the nodes table
        # changes (a full node scan per 100ms tick is O(cluster) of
        # pure Python — at 10k nodes it starves the scheduler of the
        # GIL); alloc-driven migration progress re-checks the cached
        # set every tick
        last_nodes = -1
        draining: list = []
        while not self._stop.wait(self.interval):
            try:
                idx = self.store.table_index("nodes")
                if idx != last_nodes:
                    last_nodes = idx
                    draining = [
                        n.id
                        for n in self.store.iter_nodes()
                        if n.drain
                    ]
                for node_id in draining:
                    node = self.store.node_by_id(node_id)
                    if node is not None and node.drain:
                        self._drain_node(node)
            except Exception:  # noqa: BLE001
                pass

    # ------------------------------------------------------------------

    def _drain_node(self, node: Node) -> None:
        now = time.time()
        strategy = node.drain_strategy
        deadline_hit = (
            strategy is not None
            and strategy.force_deadline_unix > 0
            and now >= strategy.force_deadline_unix
        )
        ignore_system = (
            strategy is not None and strategy.ignore_system_jobs
        )

        allocs = [
            a
            for a in self.store.allocs_by_node(node.id)
            if not a.terminal_status()
        ]
        service_batch = [
            a
            for a in allocs
            if a.job is None or a.job.type != JOB_TYPE_SYSTEM
        ]
        system = [
            a
            for a in allocs
            if a.job is not None and a.job.type == JOB_TYPE_SYSTEM
        ]

        if not allocs or (not service_batch and ignore_system):
            self._finish_drain(node)
            return

        marked_any = False
        if deadline_hit:
            # force-migrate everything remaining
            for alloc in service_batch + ([] if ignore_system else system):
                if not alloc.desired_transition.should_migrate():
                    alloc.desired_transition.migrate = True
                    marked_any = True
            if marked_any:
                self._notify(allocs)
            if not service_batch and not system:
                self._finish_drain(node)
            return

        # per (job, tg) batching bounded by migrate.max_parallel
        by_group: Dict[Tuple[str, str, str], List[Allocation]] = {}
        for alloc in service_batch:
            key = (alloc.namespace, alloc.job_id, alloc.task_group)
            by_group.setdefault(key, []).append(alloc)

        for (ns, job_id, tg_name), group_allocs in by_group.items():
            job = self.store.job_by_id(ns, job_id)
            tg = job.lookup_task_group(tg_name) if job else None
            max_parallel = 1
            if tg is not None and tg.migrate is not None:
                max_parallel = max(1, tg.migrate.max_parallel)

            # in-flight = allocs of this group (anywhere) already marked
            # for migration and not yet replaced by a healthy alloc
            in_flight = 0
            for a in self.store.allocs_by_job(ns, job_id):
                if a.task_group != tg_name:
                    continue
                if (
                    not a.terminal_status()
                    and a.desired_transition.should_migrate()
                ):
                    in_flight += 1
            budget = max_parallel - in_flight
            for alloc in group_allocs:
                if budget <= 0:
                    break
                if alloc.desired_transition.should_migrate():
                    continue
                alloc.desired_transition.migrate = True
                marked_any = True
                budget -= 1

        # system allocs drain only after everything else is gone
        if not service_batch and system and not ignore_system:
            for alloc in system:
                if not alloc.desired_transition.should_migrate():
                    alloc.desired_transition.migrate = True
                    marked_any = True

        if marked_any:
            self._notify(allocs)
        elif not allocs:
            self._finish_drain(node)

    # ------------------------------------------------------------------

    def _notify(self, allocs: List[Allocation]) -> None:
        """Persist the transition marks and create migration evals."""
        self.store.upsert_allocs(allocs)
        seen = set()
        for alloc in allocs:
            if not alloc.desired_transition.should_migrate():
                continue
            key = (alloc.namespace, alloc.job_id)
            if key in seen:
                continue
            seen.add(key)
            job = self.store.job_by_id(*key)
            if job is None:
                continue
            from ..structs import Evaluation, EVAL_STATUS_PENDING

            ev = Evaluation(
                namespace=alloc.namespace,
                priority=job.priority,
                type=job.type,
                triggered_by="node-drain",
                job_id=alloc.job_id,
                status=EVAL_STATUS_PENDING,
            )
            self.store.upsert_evals([ev])
            self.server.on_eval_update(ev)

    def _finish_drain(self, node: Node) -> None:
        """(reference drainer.go handleDoneNode: drain clears, node stays
        ineligible)"""
        node.drain = False
        node.drain_strategy = None
        self.store.upsert_node(node)
