"""Replicated multi-server control plane (reference nomad/server.go +
nomad/leader.go + nomad/rpc.go forwarding).

Each ClusterServer owns a local StateStore applied to exclusively by the
raft FSM; the Server machinery on top sees a ReplicatedStore whose write
methods propose FSM commands through the raft log (reference
nomad/rpc.go:742 raftApply) and whose reads hit local state.  Leadership
changes from raft drive establishLeadership/revokeLeadership exactly as
the reference's monitorLeadership loop does (leader.go:54,222): the eval
broker, plan applier, scheduling workers, deployment watcher, drainer,
periodic dispatcher and heartbeat timers run only on the leader.

Writes issued on a follower forward to the leader transparently at the
store-write level (reference rpc.go:509 forward), so the HTTP/API layer
works unchanged on any server.
"""
from __future__ import annotations

import logging
import os
import pickle
import time
from typing import List, Optional

from ..acl import ACLStore, Token
from ..raft import InmemTransport, NotLeaderError, RaftNode
from ..raft.transport import TransportError
from ..state.store import StateStore
from ..structs import new_id
from ..trace import TRACE
from .fsm import ServerFSM, StaleLeadershipError, encode_command
from .membership import Gossip
from .server import Server

LOG = logging.getLogger(__name__)

_RAFT_METHODS = {"request_vote", "append_entries", "install_snapshot"}


def _forward_retries() -> int:
    """Bounded leader-forward retry budget (attempts AFTER the first);
    each retry rediscovers the leader, so a command survives the
    leadership moving mid-forward instead of being lost."""
    try:
        return max(0, int(os.environ.get("NOMAD_TPU_FORWARD_RETRIES", "4")))
    except ValueError:
        return 4


def _forward_backoff_s() -> float:
    """Initial retry backoff; doubles per attempt (capped at 1s) so a
    leaderless interregnum is waited out, not hammered."""
    try:
        return max(
            0.0,
            float(os.environ.get("NOMAD_TPU_FORWARD_BACKOFF_S", "0.05")),
        )
    except ValueError:
        return 0.05


def obs_fanin_timeout_s() -> float:
    """Whole-query budget for a /v1/cluster/* fan-in: peers not
    answered (or not even asked) inside it are marked `unreachable`
    in the merged result rather than failing the query."""
    try:
        return max(
            0.0,
            float(
                os.environ.get("NOMAD_TPU_OBS_FANIN_TIMEOUT_S", "2.0")
            ),
        )
    except ValueError:
        return 2.0


class ReplicatedStore:
    """StateStore facade: reads are local, writes go through raft.

    Mirrors the split in the reference where endpoint reads use the
    local memdb and writes call raftApply (e.g. node_endpoint.go,
    job_endpoint.go).
    """

    def __init__(
        self, local: StateStore, raft_apply, leader_gen=None
    ) -> None:
        self.local = local
        self._raft_apply = raft_apply
        # callable returning the proposer's current leadership
        # generation; stamped onto plan-result commands so the FSM's
        # replicated fence can reject a deposed leader's wave
        self._leader_gen = leader_gen

    def __getattr__(self, name):
        return getattr(self.local, name)

    # -- replicated write surface (FSM command per method) -------------

    def upsert_node(self, node):
        return self._raft_apply("upsert_node", (node,))

    def delete_node(self, node_id):
        return self._raft_apply("delete_node", (node_id,))

    def update_node_status(self, node_id, status, now=None):
        # timestamps are fixed by the proposer so every replica's FSM
        # applies the identical value
        return self._raft_apply(
            "update_node_status",
            (node_id, status, time.time() if now is None else now),
        )

    def update_node_statuses(
        self, node_ids, status, now=None, message=""
    ):
        # one FSM command for the whole down-node wave: a mass
        # node-death replicates as ONE log entry applied atomically
        # on every replica, not hundreds of raft round trips
        return self._raft_apply(
            "update_node_statuses",
            (
                list(node_ids),
                status,
                time.time() if now is None else now,
                message,
            ),
        )

    def update_node_eligibility(self, node_id, eligibility):
        return self._raft_apply(
            "update_node_eligibility", (node_id, eligibility)
        )

    def upsert_node_events(self, node_id, events):
        return self._raft_apply("upsert_node_events", (node_id, events))

    def update_node_drain(self, node_id, drain, strategy=None):
        return self._raft_apply(
            "update_node_drain", (node_id, drain, strategy)
        )

    def set_job_stability(self, namespace, job_id, version, stable):
        return self._raft_apply(
            "set_job_stability", (namespace, job_id, version, stable)
        )

    def upsert_job(self, job, keep_versions: int = 6):
        return self._raft_apply("upsert_job", (job, keep_versions))

    def delete_job(self, namespace, job_id):
        return self._raft_apply("delete_job", (namespace, job_id))

    def upsert_evals(self, evals, now=None):
        return self._raft_apply(
            "upsert_evals", (evals, time.time() if now is None else now)
        )

    def delete_eval(self, eval_id):
        return self._raft_apply("delete_eval", (eval_id,))

    def upsert_allocs(self, allocs):
        return self._raft_apply("upsert_allocs", (allocs,))

    def upsert_deployment(self, deployment):
        return self._raft_apply("upsert_deployment", (deployment,))

    def upsert_scaling_event(self, namespace, job_id, group, event):
        return self._raft_apply(
            "upsert_scaling_event", (namespace, job_id, group, event)
        )

    def upsert_csi_volume(self, volume):
        return self._raft_apply("upsert_csi_volume", (volume,))

    def upsert_namespace(self, ns):
        return self._raft_apply("upsert_namespace", (ns,))

    def reconcile_job_summaries(self):
        return self._raft_apply("reconcile_job_summaries", ())

    def delete_namespace(self, name):
        return self._raft_apply("delete_namespace", (name,))

    def deregister_csi_volume(self, namespace, volume_id, force=False):
        return self._raft_apply(
            "deregister_csi_volume", (namespace, volume_id, force)
        )

    def release_csi_claims_for_alloc(self, alloc_id):
        return self._raft_apply(
            "release_csi_claims_for_alloc", (alloc_id,)
        )

    def set_autopilot_config(self, config):
        return self._raft_apply("set_autopilot_config", (config,))

    def set_scheduler_config(self, config):
        return self._raft_apply("set_scheduler_config", (config,))

    def upsert_plan_results(self, result, eval_id, leader_gen=None):
        # stops/preemptions replicate as AllocationDiffs; every
        # replica's FSM denormalizes against its own state (reference
        # plan_apply.go:324 normalizePlan).  The command carries a
        # leadership generation: if a newer leader's barrier lands
        # first, every replica's FSM rejects this plan under the
        # apply (StaleLeadershipError) — the fence a deposed leader's
        # host-side checks alone could race past.  ``leader_gen`` is
        # the generation the PRODUCING wave captured when it started
        # (stamped on the Plan); falling back to the current
        # generation only for plans that carry no stamp — a straggler
        # wave must never be re-stamped with a newer generation it
        # did not run under.
        from .fsm import normalize_plan_result

        if leader_gen is None and self._leader_gen is not None:
            leader_gen = self._leader_gen()
        return self._raft_apply(
            "upsert_plan_results",
            (normalize_plan_result(result), eval_id, leader_gen),
        )


class ReplicatedACLStore:
    """ACL writes through raft; resolution against local state
    (reference: ACL tables live in the same raft FSM, fsm.go
    ACLPolicyUpsert/ACLTokenUpsert)."""

    def __init__(self, local: ACLStore, raft_apply) -> None:
        self.local = local
        self._raft_apply = raft_apply

    def __getattr__(self, name):
        return getattr(self.local, name)

    def bootstrap(self) -> Token:
        # generate on the caller, replicate the concrete token (token
        # IDs are random; the FSM must stay deterministic)
        token = Token(name="Bootstrap Token", type="management")
        return self._raft_apply("acl_bootstrap", (token,))

    def upsert_policy(self, policy):
        return self._raft_apply("acl_upsert_policy", (policy,))

    def delete_policy(self, name):
        return self._raft_apply("acl_delete_policy", (name,))

    def create_token(self, token):
        return self._raft_apply("acl_create_token", (token,))

    def delete_token(self, accessor_id):
        return self._raft_apply("acl_delete_token", (accessor_id,))


class ClusterServer(Server):
    """A Server participating in a raft-replicated cluster."""

    def __init__(
        self,
        addr: str,
        peers: List[str],
        transport: Optional[InmemTransport] = None,
        region: str = "global",
        election_timeout: float = 0.15,
        heartbeat_interval: float = 0.04,
        snapshot_threshold: int = 2048,
        acl_enabled: bool = False,
        **kwargs,
    ) -> None:
        self.addr = addr
        self.region = region
        self.transport = transport or InmemTransport()
        local_store = StateStore()
        local_acls = ACLStore(enabled=acl_enabled)
        self.fsm = ServerFSM(local_store, local_acls)
        self.raft = RaftNode(
            addr,
            peers,
            self.transport,
            self.fsm,
            election_timeout=election_timeout,
            heartbeat_interval=heartbeat_interval,
            snapshot_threshold=snapshot_threshold,
            on_leadership=self._on_leadership,
        )
        # the server machinery sees the replicated facades
        super().__init__(
            store=ReplicatedStore(
                local_store,
                self._raft_apply,
                leader_gen=lambda: self._leadership_gen,
            ),
            acls=ReplicatedACLStore(local_acls, self._raft_apply),
            acl_enabled=acl_enabled,
            **kwargs,
        )
        # gossip membership across servers and regions (reference
        # nomad/serf.go; WAN pool gives region federation its routes)
        self.gossip = Gossip(
            addr,
            addr,
            self.transport,
            region=region,
            on_event=self._on_member_event,
        )
        # take over the transport slot: raft RPCs pass through, plus a
        # leader-forwarding channel (reference nomad/rpc.go: one port,
        # multiplexed raft + RPC + serf)
        self.transport.register(addr, self._handle_cluster_rpc)
        # dead-server cleanup (reference nomad/autopilot.go)
        from .autopilot import Autopilot

        self.autopilot = Autopilot(self)
        # follower scheduling fan-out (NOMAD_TPU_FANOUT=1): while this
        # server is a follower, a monitor runs batch workers that
        # lease evals from the leader's broker over the transport,
        # plan on LOCAL replicated state + local device, and submit
        # plans into the leader's serialized plan queue
        from .fanout import FanoutManager

        self.fanout = FanoutManager(self, seed=kwargs.get("seed"))
        # the geo plane: per-server router resolving home regions,
        # forwarding region_call RPCs with bounded retry, fanning
        # Multiregion jobs out, and snapshotting gossip into the
        # region health table behind the shed-redirect hint
        from .federation import FederationRouter

        self.federation = FederationRouter(self)

    # -- raft plumbing --------------------------------------------------

    def _raft_apply(self, kind: str, args: tuple, cmd_id: str = None):
        """Propose a command; on a follower, forward to the leader with
        bounded retry (reference rpc.go:509 forward + rpc.go:742
        raftApply).  Leadership moving mid-forward used to LOSE the
        command (one shot at one hint); now each attempt rediscovers
        the leader and backs off, and the client-supplied cmd_id makes
        the retry idempotent — if the first forward actually committed
        before its ack was lost, the FSM dedup returns that apply's
        result instead of mutating twice.  Callers with their own
        idempotency scope (cross-region fan-out) pass an explicit
        cmd_id so even a WHOLE retried call dedups, not just one
        forward attempt."""
        data = encode_command(kind, args, cmd_id=cmd_id or new_id())
        backoff = _forward_backoff_s()
        retries = _forward_retries()
        last_exc: Exception = NotLeaderError(None)
        for attempt in range(retries + 1):
            if attempt:
                metrics = getattr(self, "metrics", None)
                if metrics is not None:
                    metrics.incr("raft.forward_retries")
                if backoff:
                    time.sleep(min(backoff * (2 ** (attempt - 1)), 1.0))
            leader = None
            try:
                return self.raft.apply(data)
            except StaleLeadershipError:
                raise  # replicated verdict: re-forwarding can't help
            except NotLeaderError as exc:
                leader = exc.leader or self.raft.leader_hint()
                if leader is None and isinstance(
                    last_exc, NotLeaderError
                ):
                    # a previous remote's hint beats no hint at all
                    leader = last_exc.leader
                last_exc = exc
            except TimeoutError as exc:
                # ambiguous: the entry may yet commit.  cmd_id dedup
                # makes the retry safe either way.
                last_exc = exc
                continue
            if leader is None:
                continue  # interregnum: back off and rediscover
            try:
                resp = self.transport.rpc(
                    self.addr, leader, "fsm_apply", {"data": data}
                )
            except (TransportError, TimeoutError) as exc:
                # TimeoutError: the remote's own apply timed out —
                # ambiguous like the local case, idempotent to retry
                last_exc = exc
                continue
            if resp.get("not_leader"):
                # the remote was deposed mid-forward; its hint (if
                # any) seeds the next rediscovery
                last_exc = NotLeaderError(resp.get("leader"))
                continue
            return pickle.loads(resp["result"])
        raise last_exc

    def _handle_cluster_rpc(self, method: str, payload: dict) -> dict:
        if method in _RAFT_METHODS:
            return self.raft._handle_rpc(method, payload)
        if method.startswith("gossip_"):
            return self.gossip.handle(method, payload)
        if method == "fsm_apply":
            # a just-deposed leader must answer with a structured
            # not-leader response (and its best hint), not a pickled
            # crash — the forwarding retry loop reads it and
            # rediscovers.  StaleLeadershipError stays an application
            # error: it is a replicated verdict, not a routing miss.
            try:
                result = self.raft.apply(payload["data"])
            except StaleLeadershipError:
                raise
            except NotLeaderError as exc:
                return {
                    "not_leader": True,
                    "leader": exc.leader or self.raft.leader_hint(),
                }
            return {"result": pickle.dumps(result)}
        if method == "broker_dequeue":
            return self._handle_broker_dequeue(payload)
        if method == "broker_drain_family":
            return self._handle_broker_drain_family(payload)
        if method in ("broker_ack", "broker_nack"):
            return self._handle_broker_settle(method, payload)
        if method == "submit_plan":
            return self._handle_submit_plan(payload)
        if method == "obs_query":
            # cluster observability fan-in: read-only, answered by
            # EVERY server (not leader-gated) — each server's trace
            # ring / metrics / history is its own
            return self._obs_local(
                payload["what"], payload.get("params") or {}
            )
        if method == "server_call":
            fn = getattr(self, payload["op"])
            args, kw = pickle.loads(payload["args"])
            return {"result": pickle.dumps(fn(*args, **kw))}
        if method == "region_call":
            return self._handle_region_call(payload)
        raise ValueError(f"unknown cluster rpc {method!r}")

    def _handle_region_call(self, payload: dict) -> dict:
        """The WAN half of forwardRegion (reference rpc.go:645): a
        request that entered through another region's servers lands
        here.  A pickled remote exception used to surface as a raw
        unpickle crash at the caller; every outcome is now a
        structured envelope — ``wrong_region`` (stale gossip routed
        to the wrong region; carries our actual region + leader
        hint), ``not_leader`` (interregnum; carries the hint),
        ``{error, kind}`` for unknown ops / timeouts / application
        errors — the same contract ``fsm_apply`` answers with, so
        the calling router can tell a retryable routing miss from a
        definitive verdict."""
        op = payload.get("op", "")
        want = payload.get("region")
        if want is not None and want != self.region:
            return {
                "wrong_region": True,
                "region": self.region,
                "leader": self.raft.leader_hint(),
                "error": (
                    f"server {self.addr} is in region "
                    f"{self.region!r}, not {want!r}"
                ),
                "kind": "wrong_region",
            }
        if op not in _REGION_API:
            return {
                "error": f"unknown region op {op!r}",
                "kind": "unknown_op",
            }
        try:
            args, kw = pickle.loads(payload["args"])
            result = self._leader_route(op, *args, **kw)
        except StaleLeadershipError:
            raise  # replicated verdict; the raft layer owns it
        except NotLeaderError as exc:
            return {
                "not_leader": True,
                "leader": exc.leader or self.raft.leader_hint(),
                "error": f"no leader in region {self.region!r}",
                "kind": "not_leader",
            }
        except (TimeoutError, TransportError) as exc:
            return {
                "error": str(exc) or type(exc).__name__,
                "kind": "timeout"
                if isinstance(exc, TimeoutError)
                else "transport",
            }
        except Exception as exc:  # noqa: BLE001 — envelope, not crash
            return {"error": str(exc), "kind": "app"}
        return {"result": pickle.dumps(result)}

    # -- follower fan-out RPC surface (leader side) ---------------------
    #
    # The remote half of the reference's worker/plan-queue split: any
    # server's scheduling workers lease evals from the LEADER's broker
    # and submit plans into the LEADER's serialized plan queue.  Every
    # lease-granting response is stamped with the leadership
    # generation it was issued under, so follower plans carry the
    # generation the replicated StaleLeadershipError fence judges
    # them by.

    def _fanout_not_leader(self) -> dict:
        return {"not_leader": True, "leader": self.raft.leader_hint()}

    def _fanout_serving(self) -> bool:
        return self._leader_established and self.is_leader()

    def _lease_response(self, leases) -> dict:
        """Package granted leases: pickled (the follower must get its
        OWN object graph, never aliases into our store), stamped with
        the current generation, with the ready backlog piggybacked
        for the follower's adaptive sizing."""
        gen = self._leadership_gen
        if leases and not self._leader_established:
            # revoked between the dequeue and this stamp: the broker
            # flush already unacked these tokens — hand back nothing
            # rather than leases that die on first ack
            for ev, token in leases:
                try:
                    self.broker.nack(ev.id, token)
                except ValueError:
                    pass
            return self._fanout_not_leader()
        if leases:
            self.metrics.incr(
                "fanout.remote_leases_granted", float(len(leases))
            )
            self.metrics.set_gauge(
                "fanout.remote_unacked",
                float(self.broker.remote_unacked_count()),
            )
        # distributed trace propagation: every lease ships the trace
        # context its broker-dequeue root was begun under (full trace
        # id — generation counters are per-process — plus the
        # wall-clock anchor), so the follower records its pipeline
        # spans into a segment under OUR trace id
        ctxs = {}
        for ev, _token in leases:
            ctx = TRACE.export_context(ev.id)
            if ctx is not None:
                ctxs[ev.id] = ctx
        return {
            "leases": pickle.dumps(list(leases)),
            "trace_ctx": ctxs,
            "gen": gen,
            "ready": self.broker.ready_count(),
            # the follower's apply fence: enqueued eval OBJECTS carry
            # modify_index=0 (the raft round trip stamps the FSM's
            # copy, not the enqueuer's), and the leader never noticed
            # because its own store has always applied everything it
            # proposed.  A remote planner has no such guarantee, so
            # every lease ships the leader's index AT GRANT TIME — an
            # upper bound on the eval's creating write, which is
            # certainly committed (the eval came out of the broker).
            # The client stamps it as the eval's snapshot_index and
            # the follower waits for local apply to reach it before
            # planning; without this a lagging follower reads the
            # eval's job as nonexistent and completes it as a no-op
            # deregister — a silently lost placement.
            "min_index": self.store.latest_index(),
        }

    def _handle_broker_dequeue(self, payload: dict) -> dict:
        if not self._fanout_serving():
            return self._fanout_not_leader()
        leases = self.broker.dequeue_remote(
            payload["schedulers"],
            timeout=min(1.0, float(payload.get("timeout", 0.0))),
            max_n=int(payload.get("n", 1)),
            peer=payload.get("server", "?"),
        )
        return self._lease_response(leases)

    def _handle_broker_drain_family(self, payload: dict) -> dict:
        if not self._fanout_serving():
            return self._fanout_not_leader()
        leases = self.broker.drain_family_remote(
            payload["schedulers"],
            tuple(payload["family"]),
            max_n=int(payload["max_n"]),
            min_n=int(payload.get("min_n", 1)),
            peer=payload.get("server", "?"),
        )
        return self._lease_response(leases)

    def _absorb_remote_segment(self, payload: dict) -> None:
        """Stitch a follower's shipped span segment into the local
        trace ring.  Runs BEFORE any leadership/token verdict on
        purpose: a segment straggling in from a reclaimed lease still
        documents work that happened, and trace-id routing lands it in
        the generation it ran under — never the redelivered attempt."""
        segment = payload.get("segment")
        if not segment:
            return
        absorbed = TRACE.absorb_segment(segment)
        self.metrics.incr("cluster.segments_absorbed")
        if absorbed:
            self.metrics.incr(
                "cluster.segment_spans", float(absorbed)
            )

    def _handle_broker_settle(self, method: str, payload: dict) -> dict:
        self._absorb_remote_segment(payload)
        if not self._fanout_serving():
            return self._fanout_not_leader()
        settle = (
            self.broker.ack
            if method == "broker_ack"
            else self.broker.nack
        )
        try:
            settle(payload["eval_id"], payload["token"])
        except ValueError:
            # token expired (nack-timeout redelivery beat the remote
            # worker) or died with a broker flush: structured, so the
            # follower raises its local ValueError instead of
            # unpickling a crash
            return {"error": "token"}
        self.metrics.set_gauge(
            "fanout.remote_unacked",
            float(self.broker.remote_unacked_count()),
        )
        return {}

    def _handle_submit_plan(self, payload: dict) -> dict:
        self._absorb_remote_segment(payload)
        if not self._leader_established:
            return self._fanout_not_leader()
        plan = pickle.loads(payload["plan"])
        try:
            pending = self.plan_queue.enqueue(plan)
            result = pending.wait(timeout=10.0)
        except StaleLeadershipError as exc:
            # replicated verdict — definitive, never re-forwarded
            return {"stale_leadership": (exc.gen, exc.fence)}
        except NotLeaderError as exc:
            return {
                "not_leader": True,
                "leader": exc.leader or self.raft.leader_hint(),
            }
        except TimeoutError:
            return {"timeout": True}
        if result is None:
            return {"rejected": True}
        self.metrics.incr("fanout.remote_plans")
        return {"result": pickle.dumps(result)}

    def broadcast_peer_removal(self, peer: str) -> bool:
        """Autopilot removal: commit the config change through the raft
        log so every member — including ones temporarily unreachable —
        converges on the same peer set when it applies the entry
        (reference applies raft.RemoveServer through the log).
        Returns whether the change committed."""
        try:
            self.raft.remove_server(peer)
            return True
        except (NotLeaderError, TimeoutError, TransportError):
            return False  # retried by the next autopilot pass

    def broadcast_peer_add(self, peer: str) -> bool:
        """Autopilot reconcile: commit the re-add through the raft log
        (reference leader.go addRaftPeer applies raft.AddVoter) so
        every member converges on the restored peer set.  Returns
        whether the change committed."""
        try:
            self.raft.add_server(peer)
            return True
        except (NotLeaderError, TimeoutError, TransportError):
            return False  # retried by the next autopilot pass

    # -- membership / federation ---------------------------------------

    def join(self, seed_addr: str) -> int:
        """Join the gossip pool via any known server (serf join)."""
        return self.gossip.join(seed_addr)

    def server_members(self):
        return self.gossip.member_list()

    # -- cluster observability fan-in -----------------------------------

    def cluster_query(self, what: str, params: Optional[dict] = None):
        """Fan a read-only observability query out to every known
        same-region server over the cluster transport and merge the
        answers.  Bounded by ``NOMAD_TPU_OBS_FANIN_TIMEOUT_S``:
        partial results are marked per-server ``unreachable`` rather
        than failing the whole query — a wedged peer must never make
        the CLUSTER unobservable.  Returns
        ``{"servers": {addr: result-or-{"unreachable": True}},
        "asked": n, "unreachable": k}``."""
        params = params or {}
        budget = obs_fanin_timeout_s()
        t0 = time.monotonic()
        servers: dict = {self.addr: self._obs_local(what, params)}
        unreachable = 0
        peers = [
            m
            for m in self.gossip.all_members()
            if m.addr != self.addr and m.region == self.region
            and m.status != "left"
        ]
        for member in peers:
            if time.monotonic() - t0 > budget:
                servers[member.addr] = {"unreachable": True}
                unreachable += 1
                continue
            try:
                servers[member.addr] = self.transport.rpc(
                    self.addr,
                    member.addr,
                    "obs_query",
                    {"what": what, "params": params},
                )
            except (TransportError, TimeoutError, ValueError):
                servers[member.addr] = {"unreachable": True}
                unreachable += 1
        self.metrics.incr("cluster.fanin_queries")
        if unreachable:
            self.metrics.incr(
                "cluster.fanin_unreachable", float(unreachable)
            )
        # per-eval queries mark the fan-in on the eval's own trace —
        # the waterfall shows when the operator came asking
        eval_ref = params.get("eval_id") or (
            params.get("ref", "").rsplit("#", 1)[0]
            if what == "trace"
            else ""
        )
        if eval_ref:
            TRACE.add_span(
                eval_ref,
                "cluster.fanin",
                t0,
                time.monotonic() - t0,
                what=what,
                servers=len(servers),
                unreachable=unreachable,
            )
        return {
            "servers": servers,
            "asked": len(servers),
            "unreachable": unreachable,
        }

    def _on_member_event(self, kind: str, member) -> None:
        # (reference serf.go nodeJoin/nodeFailed -> reconcile); raft
        # peers are static config here, so membership drives routing
        # tables and the agent members view only
        if hasattr(self, "metrics"):
            self.metrics.incr(f"serf.{kind}")

    def forward_region(self, region: str, op: str, *args, **kw):
        """Route an API call to a server in another region (reference
        rpc.go:645 forwardRegion).  Thin compat shim over the
        federation router, which owns retry/backoff and envelope
        interpretation."""
        return self.federation.forward(region, op, *args, **kw)

    def advertise_http(self, http_addr: str) -> None:
        """Record this server's HTTP advertise address into its gossip
        Member record (and rumor it), so every region learns where to
        send redirected HTTP traffic — the retry-region shed hint is
        built from these."""
        self.gossip.advertise_http(http_addr)

    def federated_register(self, job, fed_cmd_id: str):
        """Target-region half of cross-region job fan-out: specialize
        the fanned jobspec for THIS region (per-region count /
        datacenters / meta overrides from its MultiregionRegion
        entry), then propose job+eval as ONE FSM command under the
        fan-out's per-region command id.  A retried fan-out (lost
        ack, coordinator leadership moved) re-proposes the same id
        and dedups in the FSM; the eval id is derived from the same
        id, so the broker's eval-id dedup absorbs the re-enqueue too
        — a retried fan-out can never double-register or
        double-schedule."""
        import hashlib

        job.region = self.region
        self._validate_job(job)
        self._inject_connect_sidecars(job)
        self._interpolate_multiregion(job)
        from ..structs import (
            EVAL_STATUS_PENDING,
            EVAL_TRIGGER_JOB_REGISTER,
            Evaluation,
        )

        if job.periodic is not None or job.parameterized is not None:
            self._raft_apply(
                "upsert_job", (job, 6), cmd_id=fed_cmd_id
            )
            return None
        ev = Evaluation(
            id=hashlib.sha256(
                f"fed-eval:{fed_cmd_id}".encode()
            ).hexdigest()[:32],
            namespace=job.namespace,
            priority=job.priority,
            type=job.type,
            triggered_by=EVAL_TRIGGER_JOB_REGISTER,
            job_id=job.id,
            status=EVAL_STATUS_PENDING,
        )
        applied = self._raft_apply(
            "register_job_federated",
            (job, ev, time.time()),
            cmd_id=fed_cmd_id,
        )
        self.on_eval_update(applied if applied is not None else ev)
        return applied

    def federation_job_status(self, namespace: str, job_id: str):
        """This region's registration/placement summary for one job —
        the per-region leaf the /v1/job/<id>/federation aggregation
        collects."""
        job = self.store.job_by_id(namespace, job_id)
        if job is None:
            return {"registered": False, "region": self.region}
        evals = self.store.evals_by_job(namespace, job_id)
        statuses: dict = {}
        for ev in evals:
            statuses[ev.status] = statuses.get(ev.status, 0) + 1
        return {
            "registered": True,
            "region": self.region,
            "version": job.version,
            "groups": {tg.name: tg.count for tg in job.task_groups},
            "evals": statuses,
            "allocs": len(
                self.store.allocs_by_job(namespace, job_id)
            ),
        }

    def cluster_query_region(
        self,
        what: str,
        params: Optional[dict] = None,
        region: Optional[str] = None,
    ):
        """Observability fan-in with the region boundary enforced:
        no region (or our own) answers from the LOCAL region's
        servers only — reads never cross the WAN implicitly.  An
        explicit foreign region is the ?region= escape hatch: the
        query forwards to that region's leader and counts against
        ``federation.wan_reads`` (asserted zero for region-local
        traffic in the geo harness)."""
        if region is None or region == self.region:
            return self.cluster_query(what, params)
        self.metrics.incr("federation.wan_reads")
        return self.federation.forward(
            region, "cluster_query", what, params
        )

    def remote_call(self, op: str, *args, **kw):
        """Invoke a Server API method on the current leader
        (reference: endpoint forwarding for non-store operations)."""
        return self._leader_route(op, *args, **kw)

    def _leader_route(self, op: str, *args, **kw):
        """Run a Server API method on the leader (reference
        rpc.go:509 forward): locally when we are the leader, otherwise
        over the transport.  Ops resolve on the Server base first —
        cluster-level ops (federation, observability) are real
        ClusterServer methods, never forwarders, so falling back to
        the subclass cannot recurse."""
        if self.is_leader():
            fn = getattr(Server, op, None)
            if fn is None:
                fn = getattr(type(self), op)
            return fn(self, *args, **kw)
        leader = self.raft.leader_hint()
        if leader is None:
            raise NotLeaderError(None)
        resp = self.transport.rpc(
            self.addr, leader, "server_call",
            {"op": op, "args": pickle.dumps((args, kw))},
        )
        return pickle.loads(resp["result"])

    def on_eval_update(self, ev) -> None:
        """Eval routing happens on the leader only (reference
        fsm.go:715); a restarted/late leader recovers anything missed
        via restore_evals."""
        if self.is_leader():
            super().on_eval_update(ev)
        else:
            try:
                self._leader_route("route_eval", ev.id)
            except (NotLeaderError, TransportError):
                pass  # next election's restore_evals picks it up

    def is_leader(self) -> bool:
        return self.raft.is_leader()

    def _on_leadership(self, is_leader: bool, term: int) -> None:
        if not is_leader:
            # park the full leader-only stack: broker (unacking every
            # outstanding token, drain_family members included), plan
            # queue/applier (in-flight plans respond NotLeaderError),
            # workers (the leadership fence aborts open chunk chains
            # and mid-settle storm gulps), watchers, heartbeat timers
            self.revoke_leadership()
            return
        # make sure every committed entry is applied locally before the
        # leader services read state (reference leader.go
        # establishLeadership barrier); retry while we hold leadership —
        # giving up would leave an elected leader with its services off
        while self._running and self.raft.is_leader():
            try:
                self.raft.barrier(timeout=5.0)
                # move the REPLICATED leadership fence to this term
                # before any service starts: from here on, every
                # replica's FSM rejects plan commands stamped by an
                # older generation, however they arrive (raft apply on
                # a zombie leader, or forwarded to us)
                self._raft_apply("leadership_barrier", (term,))
            except (TimeoutError, TransportError):
                continue
            except NotLeaderError:
                return
            # re-check AFTER the barrier: _raft_apply's forwarding
            # retries mean a barrier proposed by a just-deposed
            # leader can still "succeed" (forwarded to the new
            # leader, where max(fence, term) is a no-op) — without
            # this check the deposed server would establish anyway
            # and duplicate-schedule the backlog until its queued
            # revoke notification lands
            stats = self.raft.stats()
            if stats["state"] != "leader" or stats["term"] != term:
                return
            # the broker restore inside establish_leadership reads the
            # replicated state AT OUR COMMIT INDEX (the barrier just
            # flushed the apply pipeline), so no committed eval is
            # missed and none is invented
            self.establish_leadership(gen=term)
            return

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        self._running = True
        # metric history runs on every server, leader or follower —
        # fan-in queries merge the whole cluster's rings
        self.metrics_history.start()
        self.gossip.start()
        self.raft.start()
        self.autopilot.start()
        # follower fan-out workers start/stop with this server's raft
        # role (no-op unless NOMAD_TPU_FANOUT=1)
        self.fanout.start()
        # geo router: snapshots gossip into the region health table
        self.federation.start()

    def stop(self) -> None:
        self._running = False
        # fan-out first: its workers RPC over the transport this stop
        # is about to quiesce; same for the federation router
        self.federation.stop()
        self.fanout.stop()
        self.autopilot.stop()
        self.raft.stop()
        self.metrics_history.stop()
        # graceful departure: broadcast LEFT so peers don't gossip a
        # failure (serf Leave vs. a detected member-failed)
        self.gossip.leave()
        self.revoke_leadership()
        self._heartbeat_deadlines.clear()
        # see Server.stop: a still-open overload incident settles as
        # `shed` rather than dangling in flight forever
        self.overload.close_incident()
        self.log_monitor.uninstall("nomad_tpu")


# Public Server API methods that must execute on the leader — their
# side effects (eval routing into the broker, heartbeat TTL timers,
# blocked-eval unblocking) only exist there.  Calling any of these on a
# follower transparently forwards, so the HTTP/API layer genuinely
# works unchanged on any server (reference rpc.go:509 forward).
_LEADER_API = (
    "register_job",
    "deregister_job",
    "dispatch_job",
    "plan_job",
    "register_node",
    "heartbeat",
    "update_node_status",
    "update_node_drain",
    "update_node_eligibility",
    "update_allocs_from_client",
    "force_gc",
    "route_eval",
    "scale_job",
    "revert_job",
    "stop_alloc",
    "purge_node",
)


def _make_forwarder(op):
    def method(self, *args, **kw):
        return self._leader_route(op, *args, **kw)

    method.__name__ = op
    method.__qualname__ = f"ClusterServer.{op}"
    method.__doc__ = f"Leader-forwarded Server.{op} (rpc.go:509 forward)."
    return method


for _op in _LEADER_API:
    setattr(ClusterServer, _op, _make_forwarder(_op))

# The op surface a region_call may invoke: the leader-forwarded Server
# API plus the cluster-level federation/observability ops.  Anything
# else answers a structured unknown_op envelope — the WAN boundary is
# not a generic RPC into arbitrary attributes.
_REGION_API = frozenset(_LEADER_API) | {
    "federated_register",
    "federation_job_status",
    "cluster_query",
    "fanout_multiregion",
}


def _register_job_federated(self, job):
    """Jobs carry a region (structs.Job.Region); a submission landing
    in the wrong region hops to the right one first (reference
    job_endpoint.go forwarding via rpc.go:645), with the federation
    router owning the retry/backoff and envelope handling.  A job
    that never named a region (the struct default) resolves to the
    receiving server's region, as the reference agent does, unless
    the default region actually exists in the federation.  A job
    carrying a Multiregion block goes to its home region's leader
    and fans out from there."""
    region = self.federation.home_region(job)
    if job.multiregion is not None and job.multiregion.regions:
        if not region or region == self.region:
            ev, _statuses = self._leader_route(
                "fanout_multiregion", job
            )
            return ev
        ev, _statuses = self.federation.forward(
            region, "fanout_multiregion", job
        )
        return ev
    if not region or region == self.region:
        job.region = self.region
        return self._leader_route("register_job", job)
    return self.federation.forward(region, "register_job", job)


def _fanout_multiregion(self, job):
    """Home-region coordinator entry for a Multiregion jobspec: runs
    on the home region's leader, fans per-region registrations out
    through the router (idempotent per-region cmd ids)."""
    return self.federation.fanout_job(job)


ClusterServer.register_job = _register_job_federated
ClusterServer.fanout_multiregion = _fanout_multiregion


class TestCluster:
    """Boots N in-process ClusterServers on a shared transport — the
    shape of the reference's nomad.TestServer + TestJoin clusters
    (nomad/testing.go:44)."""

    __test__ = False  # not a pytest class despite the name

    def __init__(
        self,
        n: int = 3,
        transport: Optional[InmemTransport] = None,
        region: str = "global",
        name_prefix: str = "server",
        **server_kwargs,
    ) -> None:
        self.transport = transport or InmemTransport()
        addrs = [f"{name_prefix}-{i}" for i in range(n)]
        self.servers = [
            ClusterServer(
                addr, addrs, self.transport, region=region,
                **server_kwargs,
            )
            for addr in addrs
        ]

    def start(self) -> None:
        for s in self.servers:
            s.start()
        # gossip-join everyone through the first server (TestJoin)
        seed = self.servers[0]
        for s in self.servers[1:]:
            s.join(seed.addr)

    def stop(self) -> None:
        for s in self.servers:
            s.stop()

    def wait_for_leader(self, timeout: float = 5.0) -> ClusterServer:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            leaders = [s for s in self.servers if s.is_leader()]
            if len(leaders) == 1 and leaders[0]._leader_established:
                return leaders[0]
            time.sleep(0.02)
        raise AssertionError("no established leader")

    def followers(self) -> List[ClusterServer]:
        return [s for s in self.servers if not s.is_leader()]
