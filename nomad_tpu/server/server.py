"""The control plane in one process (reference nomad/server.go +
nomad/leader.go).

Wires the state store, eval broker, blocked-evals tracker, plan queue,
the serialized plan applier, N scheduling workers and the node heartbeat
monitor, and exposes the write-path operations the RPC endpoints perform
in the reference (job register -> eval create, node register/heartbeat ->
node evals, etc.).

Consensus/federation scope for this stage: the reference replicates this
state machine with Raft and gossips membership with Serf
(nomad/server.go:105-186); here a single process owns the store and the
leader services are always enabled.  The store's index plumbing,
snapshot-fencing and the broker/applier protocols are the Raft-facing
surfaces and keep their reference semantics so a replicated log can slot
in underneath.
"""
from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import Dict, List, Optional

LOG = logging.getLogger("nomad_tpu.server")

from ..state.store import StateStore
from ..structs import (
    Allocation,
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_DESIRED_STOP,
    Evaluation,
    EVAL_STATUS_BLOCKED,
    EVAL_STATUS_PENDING,
    EVAL_TRIGGER_JOB_DEREGISTER,
    EVAL_TRIGGER_JOB_REGISTER,
    EVAL_TRIGGER_NODE_UPDATE,
    Job,
    JOB_TYPE_CORE,
    JOB_TYPE_SERVICE,
    Node,
    NODE_STATUS_DOWN,
    NODE_STATUS_READY,
)
from .blocked_evals import BlockedEvals
from .deployment_watcher import DeploymentWatcher
from .drainer import Drainer
from .volume_watcher import VolumeWatcher
from .eval_broker import EvalBroker
from .periodic import PeriodicDispatcher
from .plan_apply import PlanApplier
from .plan_queue import PlanQueue
from .worker import Worker

DEFAULT_HEARTBEAT_TTL = 30.0

# leadership failover telemetry, zero-registered at construction (the
# `leadership-metrics` nomadlint rule enforces registry membership for
# every emission across server.py / batch_worker.py / cluster.py)
LEADERSHIP_COUNTERS = (
    "leadership.establishes",
    "leadership.revokes",
    "leadership.unacked_on_revoke",
    "leadership.chain_aborts",
    "leadership.plan_rejected",
    "leadership.stale_wave_fenced",
    "raft.forward_retries",
)
LEADERSHIP_GAUGES = ("leadership.generation", "leadership.is_leader")


class _PlanRecorder:
    """Records scheduler output without committing (dry-run planner)."""

    def __init__(self, store: StateStore) -> None:
        self.store = store
        self.plans = []
        self.evals = []

    def submit_plan(self, plan):
        from ..structs import PlanResult

        self.plans.append(plan)
        # report everything as committed so the scheduler completes
        result = PlanResult(
            node_update=plan.node_update,
            node_allocation=plan.node_allocation,
            node_preemptions=plan.node_preemptions,
            deployment=plan.deployment,
            deployment_updates=plan.deployment_updates,
            alloc_index=self.store.latest_index(),
        )
        return result, None

    def update_eval(self, ev):
        self.evals.append(ev)

    def create_eval(self, ev):
        self.evals.append(ev)

    def reblock_eval(self, ev):
        self.evals.append(ev)


class Keyring:
    """Gossip encryption keyring (reference serf KeyManager backing
    `operator keyring`): a set of installed base64 keys with one
    primary.  Transport encryption itself rides mTLS in this build
    (raft/tcp.py), so the keyring manages identities/rotation state.

    Scope deviation: ops apply to the ADDRESSED agent only — the
    reference broadcasts key changes through serf; here each server's
    keyring is local state, so rotation tooling must address every
    server (mTLS certs, not these keys, are what gates transport)."""

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self._keys: list = []
        self._primary: str = ""

    @staticmethod
    def _validate(key: str) -> str:
        import base64 as _b64

        try:
            raw = _b64.b64decode(key, validate=True)
        except Exception:
            raise ValueError("key must be base64")
        if len(raw) not in (16, 24, 32):
            raise ValueError("key must decode to 16, 24 or 32 bytes")
        return key

    def install(self, key: str) -> None:
        key = self._validate(key)
        with self._lock:
            if key not in self._keys:
                self._keys.append(key)
            if not self._primary:
                self._primary = key

    def use(self, key: str) -> None:
        with self._lock:
            if key not in self._keys:
                raise ValueError("key is not installed")
            self._primary = key

    def remove(self, key: str) -> None:
        with self._lock:
            if key == self._primary:
                raise ValueError("cannot remove the primary key")
            if key not in self._keys:
                raise ValueError("key is not installed")
            self._keys.remove(key)

    def list(self) -> dict:
        with self._lock:
            return {
                "Keys": {k: 1 for k in self._keys},
                "PrimaryKeys": (
                    {self._primary: 1} if self._primary else {}
                ),
            }


class Server:
    def __init__(
        self,
        num_schedulers: int = 1,
        heartbeat_ttl: float = DEFAULT_HEARTBEAT_TTL,
        seed: Optional[int] = None,
        nack_timeout: float = 60.0,
        acl_enabled: bool = False,
        # the batched TPU pipeline is the default scheduling path; it
        # falls back per eval to the exact sequential scheduler for
        # shapes the kernel doesn't model (networks/devices/multi-TG/
        # sticky), with prescore-rate + fallback counters in /v1/metrics
        batch_pipeline: bool = True,
        store: Optional[StateStore] = None,
        acls=None,
        device_config=None,
    ) -> None:
        from ..acl import ACLStore
        from ..telemetry import Metrics

        # store/acls are injectable so a replicated cluster can hand in
        # raft-backed facades (server/cluster.py); default is the
        # single-process direct store
        self.store = store if store is not None else StateStore()
        self.acls = acls if acls is not None else ACLStore(
            enabled=acl_enabled
        )
        self.metrics = Metrics()
        # placement explainability: zero-register the placement.*
        # counter/gauge families so dashboards see the whole reason
        # vocabulary from process start (absence-of-series must mean
        # absence-of-filtering, not "no eval explained yet")
        from ..explain import preregister as _preregister_placement

        _preregister_placement(self.metrics)
        # accelerator supervisor: owns device liveness (health probes,
        # launch watchdogs, hot CPU failover) for every worker.  Built
        # BEFORE the workers so they can subscribe to backend
        # transitions; idle (no thread) on CPU-only deployments unless
        # forced via NOMAD_TPU_SUPERVISOR=1 or an armed NOMAD_TPU_FAULT
        from ..device import DeviceSupervisor

        self.device_supervisor = DeviceSupervisor(
            metrics=self.metrics, config=device_config
        )
        self.broker = EvalBroker(nack_timeout=nack_timeout)
        # lost-eval accounting: the broker is constructed without a
        # telemetry handle, so wire ours in and zero-register its
        # family — broker.delivery_failures is the zero-lost-evals
        # SLO's burn signal, and absence-of-series must mean "nothing
        # ever lost", not "not exported"
        from .eval_broker import BROKER_COUNTERS

        self.broker.metrics = self.metrics
        self.metrics.preregister(counters=BROKER_COUNTERS)
        self.blocked = BlockedEvals(self.broker)
        self.plan_queue = PlanQueue()
        self.applier = PlanApplier(
            self.store, self.plan_queue, self.blocked, self.metrics,
            # in-flight plans of a deposed leadership respond
            # NotLeaderError (the worker converts it to
            # nack-for-redelivery) instead of committing against state
            # a new leader now owns
            leader_check=lambda: self._leader_established,
        )
        # leadership failover observability: zero-registered so
        # absence-of-series means "no leadership ever changed", never
        # "not exported" (the same contract as device.* incidents)
        self.metrics.preregister(
            counters=LEADERSHIP_COUNTERS, gauges=LEADERSHIP_GAUGES
        )
        # ingress backpressure: overload is a first-class server state
        # (NORMAL -> SHEDDING -> EMERGENCY mode ladder driven by
        # broker depth / oldest-pending-age / flight-recorder p99)
        # with priority-classed shedding at the HTTP ingress.  The
        # overload.* family is zero-registered here so dashboards can
        # tell "never overloaded" from "not exported".
        from .overload import (
            OVERLOAD_COUNTERS,
            OVERLOAD_GAUGES,
            OverloadController,
        )

        self.overload = OverloadController(self)
        self.metrics.preregister(
            counters=OVERLOAD_COUNTERS, gauges=OVERLOAD_GAUGES
        )
        # follower scheduling fan-out: zero-register the fanout.*
        # family (absence-of-series must mean "fan-out never engaged"
        # — single server, or NOMAD_TPU_FANOUT off — not "not
        # exported").  The registries live in server/fanout.py; the
        # manager itself exists only on ClusterServer.
        from .fanout import FANOUT_COUNTERS, FANOUT_GAUGES

        self.metrics.preregister(
            counters=FANOUT_COUNTERS, gauges=FANOUT_GAUGES
        )
        # multi-region federation: zero-register the federation.*
        # family (absence-of-series must mean "single region, nothing
        # ever crossed the WAN", not "not exported").  The registries
        # live in server/federation.py; the router itself exists only
        # on ClusterServer.
        from .federation import FEDERATION_COUNTERS, FEDERATION_GAUGES

        self.metrics.preregister(
            counters=FEDERATION_COUNTERS, gauges=FEDERATION_GAUGES
        )
        # cluster-scope observability: zero-register the obs.* /
        # cluster.* family (absence-of-series must mean "no segment
        # ever stitched / no fan-in ever asked", not "not exported")
        # and stand up the metric time-series history ring — its
        # snapshot thread starts with the server lifecycle
        from ..telemetry import (
            CLUSTER_OBS_COUNTERS,
            CLUSTER_OBS_GAUGES,
            MetricsHistory,
        )

        self.metrics.preregister(
            counters=CLUSTER_OBS_COUNTERS, gauges=CLUSTER_OBS_GAUGES
        )
        self.metrics_history = MetricsHistory(self.metrics)
        # control-loop flight data: the SLO engine grades declared
        # objectives over the history ring just stood up, and the
        # process-wide decision ledger records why every adaptive
        # site chose what it chose.  Both families are
        # zero-registered (absence-of-series must mean "never
        # evaluated" / "site never fired", not "not exported").
        from ..decisions import (
            DECISION_COUNTERS,
            DECISION_GAUGES,
            DECISIONS,
        )
        from ..slo import SLO_COUNTERS, SLO_GAUGES, SLOEngine

        self.metrics.preregister(
            counters=DECISION_COUNTERS, gauges=DECISION_GAUGES
        )
        self.metrics.preregister(
            counters=SLO_COUNTERS, gauges=SLO_GAUGES
        )
        self.decisions = DECISIONS
        self.slo = SLOEngine(self.metrics, self.metrics_history)
        # policy-weighted scoring: zero-register the policy.* family
        # (absence-of-series must mean "no policy-weighted select ever
        # ran" — no job carries a PolicySpec, or NOMAD_TPU_POLICY=0 —
        # not "not exported").  Registered outside the batch_pipeline
        # gate: weighted tensor assembly runs in BOTH pipeline modes.
        from ..sched.policy import POLICY_COUNTERS, POLICY_GAUGES

        self.metrics.preregister(
            counters=POLICY_COUNTERS, gauges=POLICY_GAUGES
        )
        if batch_pipeline:
            from .batch_worker import BatchWorker

            self.workers: List[Worker] = [
                BatchWorker(self, seed=seed)
                for _ in range(num_schedulers)
            ]
        else:
            self.workers = [
                Worker(self, seed=seed) for _ in range(num_schedulers)
            ]
        # pipeline-mode markers on /v1/metrics from construction time,
        # so an operator can tell a batch-pipeline server (and whether
        # its optimistic parallel replay is enabled) before any
        # traffic populates the replay.* counters
        self.metrics.set_gauge(
            "server.batch_pipeline", 1.0 if batch_pipeline else 0.0
        )
        # eval-flight-recorder mode marker (NOMAD_TPU_TRACE=0 opts
        # out), so an operator can tell why /v1/traces is empty
        from ..trace import TRACE as _trace

        self.metrics.set_gauge(
            "server.trace_enabled", 1.0 if _trace.enabled else 0.0
        )
        if batch_pipeline:
            self.metrics.set_gauge(
                "batch_worker.parallel_replay_enabled",
                1.0 if any(
                    getattr(w, "parallel_replay", False)
                    for w in self.workers
                ) else 0.0,
            )
            # continuous micro-batching: zero-register the admission.*
            # counter family (absence-of-series must mean "admission
            # never engaged", not "not exported") and expose the mode
            # flag (NOMAD_TPU_ADMIT=0 restores flush-boundary gulps)
            from .batch_worker import ADMISSION_COUNTERS

            self.metrics.preregister(counters=ADMISSION_COUNTERS)
            # sharded hot path: zero-register the mesh.* family the
            # same way (absence-of-series must mean "mesh never
            # engaged" — NOMAD_TPU_MESH off or a single-device host —
            # not "not exported")
            from .batch_worker import MESH_COUNTERS, MESH_GAUGES

            self.metrics.preregister(
                counters=MESH_COUNTERS, gauges=MESH_GAUGES
            )
            # global storm solver: zero-register the storm.* family
            # (absence-of-series must mean "no storm ever coalesced"
            # — NOMAD_TPU_STORM off or backlog under the trigger —
            # not "not exported") and expose the mode flag
            from .batch_worker import STORM_COUNTERS, STORM_GAUGES

            self.metrics.preregister(
                counters=STORM_COUNTERS, gauges=STORM_GAUGES
            )
            self.metrics.set_gauge(
                "batch_worker.storm_enabled",
                1.0 if any(
                    getattr(w, "storm_enabled", False)
                    for w in self.workers
                ) else 0.0,
            )
            self.metrics.set_gauge(
                "batch_worker.admit_enabled",
                1.0 if any(
                    getattr(w, "admit_enabled", False)
                    for w in self.workers
                ) else 0.0,
            )
        self.deployment_watcher = DeploymentWatcher(self)
        self.drainer = Drainer(self)
        self.periodic = PeriodicDispatcher(self)
        self.volume_watcher = VolumeWatcher(self)
        from .services import ServiceCatalog

        self.catalog = ServiceCatalog(self)
        # raft-index <-> wall-clock witness on every state mutation
        # (reference fsm.go Apply -> timetable.Witness)
        # live log tail for /v1/agent/monitor (reference
        # command/agent/monitor); captures the nomad_tpu logger tree
        from ..monitor import LogMonitor

        self.log_monitor = LogMonitor().install("nomad_tpu")
        # gossip encryption keyring (reference serf keyring backing
        # `operator keyring` / `keyring`: install/use/remove/list)
        self.keyring = Keyring()
        from .timetable import TimeTable

        self.timetable = TimeTable()
        # ReplicatedStore forwards add_watcher to its local store
        self.store.add_watcher(
            lambda _table, index: self.timetable.witness(index)
        )
        self.heartbeat_ttl = heartbeat_ttl
        # node id -> monotonic expiry deadline.  ONE sweeper thread
        # serves every TTL — a threading.Timer per node is an OS thread
        # per node, which at 10k nodes means 10k live threads (the
        # reference's per-node timers are Go runtime timers, not
        # threads; the Python translation must not be thread-per-node)
        self._heartbeat_deadlines: Dict[str, float] = {}
        # mass node-death gather: node id -> monotonic instant its TTL
        # expiry was detected.  A sweep that detects a correlated wave
        # (>= _wave_min expiries) holds the down transition briefly
        # (up to _wave_gather_s, settling one sweep after the last new
        # expiry) so a rack death whose members' heartbeat phases
        # straddle sweep boundaries still commits as ONE batched
        # transition + ONE storm-family replan wave.  A heartbeat
        # arriving mid-gather pulls its node back out (zero false
        # node-downs).  Small waves (< _wave_min) settle just ONE
        # sweep — a single-node death pays one sweep interval of
        # extra detection latency, and a rack death's leading edge
        # merges into the mass wave behind it.
        self._down_wave: Dict[str, float] = {}
        self._wave_counter = itertools.count(1)
        import os as _os

        try:
            self._wave_min = max(
                1,
                int(
                    _os.environ.get("NOMAD_TPU_OVERLOAD_WAVE_MIN", "8")
                ),
            )
        except ValueError:
            self._wave_min = 8
        # gather budget: "auto" (default) derives it from the TTL —
        # a rack death's expiries spread over roughly one heartbeat
        # period (clients beat at a fraction of the TTL), so the
        # budget must exceed the 2s quiet-stream settle or the
        # settle could never engage and every >2s-spread death
        # would fragment
        raw_gather = _os.environ.get(
            "NOMAD_TPU_OVERLOAD_WAVE_GATHER_S", "auto"
        )
        try:
            self._wave_gather_s = max(0.0, float(raw_gather))
        except ValueError:
            self._wave_gather_s = min(
                10.0, max(2.5, heartbeat_ttl / 3.0)
            )
        # node id -> persistent client connection for log/fs
        # proxying (populated from HTTP handler threads)
        self._clients: Dict[str, object] = {}
        self._heartbeat_sweeper: Optional[threading.Thread] = None
        self._sweeper_lock = threading.Lock()
        self._running = False
        self._leader_established = False
        # leadership generation: bumped on every establish (a cluster
        # server passes its raft term, so generations are monotone
        # ACROSS servers).  The batched hot path captures it at
        # wave/chain/storm start and fences commits on it exactly like
        # _backend_epoch fences device buffers — a wave speculated
        # under a deposed leadership can never commit.
        self._leadership_gen = 0
        self._leader_lock = threading.Lock()
        # happens-before sanitizer (NOMAD_TPU_TSAN=1)
        from ..tsan import maybe_instrument

        maybe_instrument(self, "Server")

    # -- lifecycle (reference leader.go:222 establishLeadership) -------

    def start(self) -> None:
        """Single-process mode: this server is always the leader."""
        self._running = True
        # history snapshots run for the whole server lifetime, not
        # just leadership — a follower's metrics are history too
        self.metrics_history.start()
        self.establish_leadership()

    def stop(self) -> None:
        self._running = False
        self.revoke_leadership()
        self.metrics_history.stop()
        self._heartbeat_deadlines.clear()
        # an overload excursion that never walked back to NORMAL must
        # not leave its incident trace dangling in flight
        self.overload.close_incident()
        # detach the monitor handler or stopped servers pile up on the
        # shared logger and keep buffering every record
        self.log_monitor.uninstall("nomad_tpu")

    def establish_leadership(self, gen: Optional[int] = None) -> None:
        """Enable the leader-only services (reference leader.go:222):
        eval broker, blocked evals, plan queue/applier, scheduling
        workers, deployment watcher, drainer, periodic dispatcher,
        heartbeat timers; then restore evals from state.  ``gen`` is
        the new leadership generation (a cluster server passes its
        raft term); single-process servers self-increment."""
        with self._leader_lock:
            if self._leader_established:
                return
            self._leadership_gen = (
                gen if gen is not None else self._leadership_gen + 1
            )
            # flipped BEFORE any service starts (the mirror of revoke
            # flipping it false first): the applier's leader_check and
            # the workers' leadership fences read this latch, and a
            # worker dequeuing in the establish window must not fence
            # its own brand-new leadership's evals into nacks
            self._leader_established = True
            self.metrics.incr("leadership.establishes")
            self.metrics.set_gauge(
                "leadership.generation", float(self._leadership_gen)
            )
            self.metrics.set_gauge("leadership.is_leader", 1.0)
            self.broker.set_enabled(True)
            self.blocked.set_enabled(True)
            self.plan_queue.set_enabled(True)
            self.applier.start()
            # device supervision runs while this server schedules (a
            # no-op on CPU-only deployments: no probe thread starts)
            self.device_supervisor.start()
            for worker in self.workers:
                worker.start()
            # opt-in: pre-compile the pipelined prescore launch shapes
            # off the scheduling path (production deployments set
            # NOMAD_TPU_WARM_ON_START=1; test servers start hundreds
            # of times and must not pay the XLA compiles).  Without it
            # the cold-compile shield routes the first batches to the
            # exact sequential path until the background compile lands.
            # The warmup waits for the node join wave to settle first:
            # compiled shapes embed the node arena capacity, so warming
            # the initial near-empty table would compile executables no
            # later launch matches
            import os as _os

            if _os.environ.get("NOMAD_TPU_WARM_ON_START") == "1":
                for worker in self.workers:
                    warm = getattr(worker, "warm_shapes", None)
                    if warm is not None:
                        threading.Thread(
                            target=self._warm_when_topology_settles,
                            args=(warm,),
                            name="prescore-warmup",
                            daemon=True,
                        ).start()
                        # the same warmup validates a RECOVERING device
                        # before the supervisor flips the pipeline back
                        self.device_supervisor.add_warm_hook(warm)
            self.deployment_watcher.start()
            self.drainer.start()
            self.periodic.start()
            self.volume_watcher.start()
            # rebuild the service catalog once from restored state; all
            # steady-state maintenance is incremental per alloc delta
            self.catalog.sync()
            # re-arm heartbeat TTLs for every known node (reference
            # heartbeat.go initializeHeartbeatTimers on leadership)
            for node in self.store.iter_nodes():
                if node.status != NODE_STATUS_DOWN:
                    self._reset_heartbeat(node.id)
            # even with zero known nodes, arm TTL enforcement now — a
            # sweeper that died under the previous leadership must
            # never stay dead into this one
            self._ensure_sweeper()
            self.restore_evals()

    def _warm_when_topology_settles(
        self, warm, poll_s: float = 5.0, timeout_s: float = 300.0
    ) -> None:
        """Run a worker's warm_shapes once the node table has at least
        one row and its topology generation held still for one poll
        interval (or the timeout passes).  Compiled launch shapes embed
        the arena capacity, so warming before clients register would
        burn the compiles on a capacity no production launch uses."""
        import time as _time

        deadline = _time.monotonic() + timeout_s
        last = None
        while self._running and _time.monotonic() < deadline:
            # re-read the table each poll: a snapshot restore replaces
            # store.node_table, and a stale binding would see a frozen
            # generation and fire mid-join-wave
            table = self.store.node_table
            gen = (table.epoch, table.topo_generation)
            if table.n_rows > 0 and gen == last:
                break
            last = gen
            _time.sleep(poll_s)
        if not self._running:
            return
        try:
            warm()
        except Exception:  # noqa: BLE001 — warmup is best-effort
            LOG.exception("prescore warmup failed")

    def revoke_leadership(self) -> None:
        """Disable leader-only services (reference leader.go
        revokeLeadership on leadership loss).

        Order matters for the batched hot path: ``_leader_established``
        flips FIRST, so every in-flight wave/chain/storm commit hits
        the leadership fence (and the plan applier's leader check)
        before any queue is torn down — an open chunk chain is dropped
        through its abandon path, a mid-settle storm gulp discards its
        solve before decompose, and the worker nacks every lease it
        still holds.  The broker flush then unacks every OUTSTANDING
        token (drain_family shadow-heap members included); nothing is
        committed, and the next leader's restore_evals re-enqueues all
        of it from replicated state."""
        with self._leader_lock:
            if not self._leader_established:
                return
            self._leader_established = False
            self.metrics.incr("leadership.revokes")
            self.metrics.set_gauge("leadership.is_leader", 0.0)
            self.device_supervisor.stop()
            self.periodic.stop()
            self.deployment_watcher.stop()
            self.drainer.stop()
            self.volume_watcher.stop()
            for worker in self.workers:
                worker.stop()
            self.applier.stop()
            self._heartbeat_deadlines.clear()
            self._down_wave.clear()
            self.plan_queue.set_enabled(False)
            self.blocked.set_enabled(False)
            # every token still outstanding at this point — normal
            # dequeues, drain_family shadow-heap members, mid-settle
            # storm gulps, admission-queue leases — is unacked by the
            # disable flush; the count is the failover's "work in
            # flight" exposure on /v1/metrics
            outstanding = self.broker.unacked_count()
            if outstanding:
                self.metrics.incr(
                    "leadership.unacked_on_revoke", float(outstanding)
                )
            self.broker.set_enabled(False)

    def restore_evals(self) -> None:
        """Re-enqueue non-terminal evals from state after (re)start
        (reference leader.go:352 restoreEvals)."""
        for ev in list(self.store.evals.values()):
            if ev.should_enqueue():
                self.broker.enqueue(ev)
            elif ev.should_block():
                self.blocked.block(ev)

    # -- eval routing (reference fsm.go:715) ----------------------------

    def on_eval_update(self, ev: Evaluation) -> None:
        if ev.should_enqueue():
            self.broker.enqueue(ev)
        elif ev.should_block():
            self.blocked.block(ev)

    def route_eval(self, eval_id: str) -> None:
        """Route a persisted eval into the broker/blocked tracker by id
        (the forwarding target for evals created away from the
        leader)."""
        ev = self.store.eval_by_id(eval_id)
        if ev is not None:
            self.on_eval_update(ev)

    # -- job API (reference nomad/job_endpoint.go Register:349) ---------

    def register_job(self, job: Job) -> Evaluation:
        self._validate_job(job)
        self._inject_connect_sidecars(job)
        self._interpolate_multiregion(job)
        self.store.upsert_job(job)
        if job.is_periodic() or job.is_parameterized():
            # launched by the periodic dispatcher / dispatch call instead
            return None
        ev = Evaluation(
            namespace=job.namespace,
            priority=job.priority,
            type=job.type,
            triggered_by=EVAL_TRIGGER_JOB_REGISTER,
            job_id=job.id,
            job_modify_index=job.modify_index,
            status=EVAL_STATUS_PENDING,
        )
        self.store.upsert_evals([ev])
        self.on_eval_update(ev)
        return ev

    def _inject_connect_sidecars(self, job: Job) -> None:
        """Connect admission hook (reference job_endpoint_hooks.go
        jobImplicitConstraints + the connect hook's sidecar injection:
        each service with connect.sidecar_service gets a
        'connect-proxy-<service>' task; upstream addresses surface to
        the group's tasks as NOMAD_UPSTREAM_ADDR_<dest>, the
        reference's env contract).  Our proxy is the in-tree L4
        forwarder (client/connect.py) instead of Envoy."""
        import sys as _sys

        from ..structs import Lifecycle, Resources, Task

        for tg in job.task_groups:
            upstreams = []  # (dest, local_bind_port), deduped
            seen_up = set()
            sidecars = []  # service names needing a proxy
            for task in tg.tasks:
                for svc in getattr(task, "services", None) or []:
                    cn = svc.connect
                    if cn is None or cn.native:
                        continue
                    if cn.sidecar_service:
                        sidecars.append(svc.name)
                    for up in cn.upstreams:
                        if up.local_bind_port <= 0:
                            raise ValueError(
                                f"connect upstream "
                                f"{up.destination_name!r} requires a "
                                "positive local_bind_port"
                            )
                        key = (
                            up.destination_name, up.local_bind_port
                        )
                        if key in seen_up:
                            continue
                        seen_up.add(key)
                        upstreams.append(key)
            if not sidecars and not upstreams:
                continue
            existing = {t.name for t in tg.tasks}
            proxy_name = (
                f"connect-proxy-{sidecars[0]}"
                if sidecars
                else "connect-proxy"
            )
            # expose the upstream binds to every app task (reference
            # taskenv: NOMAD_UPSTREAM_ADDR_<dest>=127.0.0.1:<port>)
            from ..client.connect import env_key

            for task in tg.tasks:
                for dest, port in upstreams:
                    task.env.setdefault(
                        f"NOMAD_UPSTREAM_ADDR_{env_key(dest)}",
                        f"127.0.0.1:{port}",
                    )
            if proxy_name in existing:
                continue  # idempotent across re-registers
            argv = []
            for dest, port in upstreams:
                argv += ["--upstream", f"{dest}:{port}"]
            if not argv and sidecars:
                # inbound-only sidecar: nothing to bind in the lite
                # proxy; skip injecting a no-op task
                continue
            tg.tasks.append(
                Task(
                    name=proxy_name,
                    # exec (executor-backed): the proxy survives agent
                    # restarts via reattach records instead of
                    # orphaning on SIGKILL; chroot off — the proxy
                    # imports this framework from the client's own
                    # package path, which a sandbox wouldn't see
                    driver="exec",
                    config={
                        "command": _sys.executable,
                        "args": ["-m", "nomad_tpu.client.connect"]
                        + argv,
                        "chroot": False,
                        "connect_upstreams": [
                            [dest, port] for dest, port in upstreams
                        ],
                    },
                    resources=Resources(cpu=100, memory_mb=64),
                    lifecycle=Lifecycle(hook="prestart", sidecar=True),
                )
            )

    def _interpolate_multiregion(self, job: Job) -> None:
        """Specialize a multiregion job for the region it landed in
        (reference job_endpoint_hooks.go jobImpliedConstraints +
        multiregion hook: the local region's count/datacenters/meta
        override the job-wide defaults; cross-region deployment
        coordination itself is the enterprise no-op,
        deploymentwatcher/multiregion_oss.go)."""
        if job.multiregion is None:
            return
        region = job.multiregion.region(
            getattr(self, "region", job.region) or job.region
        )
        if region is None:
            return
        job.region = region.name
        if region.datacenters:
            job.datacenters = list(region.datacenters)
        if region.meta:
            job.meta = {**job.meta, **region.meta}
        if region.count:
            # region count takes precedence over the group count
            # (reference multiregion docs for the region stanza)
            for tg in job.task_groups:
                tg.count = region.count

    def revert_job(
        self,
        namespace: str,
        job_id: str,
        job_version: int,
        enforce_prior_version: Optional[int] = None,
    ) -> Evaluation:
        """Re-register a historical version as the newest one
        (reference job_endpoint.go Job.Revert)."""
        import copy as _copy

        current = self.store.job_by_id(namespace, job_id)
        if current is None:
            raise KeyError(job_id)
        if enforce_prior_version is not None and (
            current.version != enforce_prior_version
        ):
            raise ValueError(
                f"current version is {current.version}, not "
                f"{enforce_prior_version}"
            )
        if job_version == current.version:
            raise ValueError(
                "cannot revert to the current version"
            )
        target = self.store.job_by_version(
            namespace, job_id, job_version
        )
        if target is None:
            raise KeyError(
                f"job {job_id!r} has no version {job_version}"
            )
        # deep copy: never mutate the store-resident history entry
        # (register-time interpolation writes into task groups)
        reverted = _copy.deepcopy(target)
        reverted.stop = False
        return self.register_job(reverted)

    def set_job_stability(
        self, namespace: str, job_id: str, version: int, stable: bool
    ) -> None:
        """(reference job_endpoint.go Job.Stable)"""
        self.store.set_job_stability(namespace, job_id, version, stable)

    def job_summary(self, namespace: str, job_id: str) -> Dict:
        """Per-task-group alloc rollup (reference structs.go JobSummary,
        maintained incrementally in state_store.go; derived on read
        here, same shape)."""
        job = self.store.job_by_id(namespace, job_id)
        if job is None:
            raise KeyError(job_id)
        groups: Dict[str, Dict[str, int]] = {
            tg.name: {
                "Queued": 0, "Complete": 0, "Failed": 0,
                "Running": 0, "Starting": 0, "Lost": 0,
            }
            for tg in job.task_groups
        }
        for a in self.store.allocs_by_job(namespace, job_id):
            g = groups.setdefault(
                a.task_group,
                {
                    "Queued": 0, "Complete": 0, "Failed": 0,
                    "Running": 0, "Starting": 0, "Lost": 0,
                },
            )
            cs = a.client_status
            if cs == "running":
                g["Running"] += 1
            elif cs == "complete":
                g["Complete"] += 1
            elif cs == "failed":
                g["Failed"] += 1
            elif cs == "lost":
                g["Lost"] += 1
            elif a.desired_status == "run":
                g["Starting"] += 1
        # queued = asks the blocked machinery is still holding
        for ev in self.store.evals_by_job(namespace, job_id):
            for tg_name, n in (ev.queued_allocations or {}).items():
                if tg_name in groups and ev.status == "blocked":
                    groups[tg_name]["Queued"] = max(
                        groups[tg_name]["Queued"], n
                    )
        return {
            "JobID": job_id,
            "Namespace": namespace,
            "Summary": groups,
            "Children": {
                "Pending": 0,
                "Running": sum(
                    1
                    for j in self.store.iter_jobs()
                    if j.parent_id == job_id and not j.stopped()
                ),
                "Dead": sum(
                    1
                    for j in self.store.iter_jobs()
                    if j.parent_id == job_id and j.stopped()
                ),
            },
        }

    def stop_alloc(self, alloc_id: str) -> Optional[Evaluation]:
        """User-initiated alloc stop: desired=stop + reschedule eval
        (reference alloc_endpoint.go Alloc.Stop)."""
        from dataclasses import replace as _replace

        from ..structs import ALLOC_DESIRED_STOP, EVAL_TRIGGER_ALLOC_STOP

        alloc = self.store.alloc_by_id(alloc_id)
        if alloc is None:
            raise KeyError(alloc_id)
        stopped = _replace(alloc)
        stopped.desired_status = ALLOC_DESIRED_STOP
        self.store.upsert_allocs([stopped])
        ev = Evaluation(
            namespace=alloc.namespace,
            priority=alloc.job.priority if alloc.job else 50,
            type=alloc.job.type if alloc.job else "service",
            triggered_by=EVAL_TRIGGER_ALLOC_STOP,
            job_id=alloc.job_id,
            status=EVAL_STATUS_PENDING,
        )
        self.store.upsert_evals([ev])
        self.on_eval_update(ev)
        return ev

    def restart_alloc(self, alloc_id: str, task: str = "") -> None:
        """Proxy a restart to the owning client (reference
        client_alloc_endpoint.go Allocations.Restart)."""
        self._client_for_alloc(alloc_id).restart_alloc(alloc_id, task)

    def signal_alloc(
        self, alloc_id: str, signal: str = "SIGTERM", task: str = ""
    ) -> None:
        """(reference client_alloc_endpoint.go Allocations.Signal)"""
        self._client_for_alloc(alloc_id).signal_alloc(
            alloc_id, signal, task
        )

    def _client_for_alloc(self, alloc_id: str):
        alloc = self.store.alloc_by_id(alloc_id)
        if alloc is None:
            raise KeyError(alloc_id)
        client = self._clients.get(alloc.node_id)
        if client is None:
            raise KeyError(f"no client connection for {alloc.node_id}")
        return client

    def exec_alloc(
        self,
        alloc_id: str,
        task: str,
        argv,
        timeout: float = 30.0,
    ):
        """(reference command/alloc_exec.go streaming exec, proxied
        server -> client; one-shot request/response here)"""
        return self._client_for_alloc(alloc_id).exec_alloc(
            alloc_id, task, list(argv), timeout
        )

    def exec_alloc_stream(self, alloc_id: str, task: str, argv):
        """Interactive exec handle, proxied to the owning client
        (reference nomad/rpc.go handleStreamingConn topology)."""
        return self._client_for_alloc(alloc_id).exec_alloc_stream(
            alloc_id, task, list(argv)
        )

    def tail_task_log(
        self, alloc_id: str, task: str, kind: str, cursor
    ):
        return self._client_for_alloc(alloc_id).tail_task_log(
            alloc_id, task, kind, cursor
        )

    def list_alloc_files(self, alloc_id: str, rel: str = ""):
        return self._client_for_alloc(alloc_id).list_alloc_files(
            alloc_id, rel
        )

    def read_alloc_file(self, alloc_id: str, rel: str):
        """Returns (data, truncated) from the owning client."""
        return self._client_for_alloc(alloc_id).read_alloc_file(
            alloc_id, rel
        )

    def purge_node(self, node_id: str) -> List[Evaluation]:
        """Remove a node from state entirely (reference
        node_endpoint.go Node.Deregister, PUT /v1/node/:id/purge);
        evals fan out for every job that had allocs there."""
        node = self.store.node_by_id(node_id)
        if node is None:
            raise KeyError(node_id)
        self._heartbeat_deadlines.pop(node_id, None)
        self._down_wave.pop(node_id, None)
        # delete first so the fanned-out evals schedule against a
        # state where the node is already gone
        self.store.delete_node(node_id)
        return self._create_node_evals(node_id)

    def deregister_job(
        self, namespace: str, job_id: str, purge: bool = False
    ) -> Optional[Evaluation]:
        job = self.store.job_by_id(namespace, job_id)
        if job is None:
            return None
        if purge:
            self.store.delete_job(namespace, job_id)
        else:
            job.stop = True
            self.store.upsert_job(job)
        self.blocked.untrack(namespace, job_id)
        ev = Evaluation(
            namespace=namespace,
            priority=job.priority,
            type=job.type,
            triggered_by=EVAL_TRIGGER_JOB_DEREGISTER,
            job_id=job_id,
            status=EVAL_STATUS_PENDING,
        )
        self.store.upsert_evals([ev])
        self.on_eval_update(ev)
        return ev

    def scale_job(
        self,
        namespace: str,
        job_id: str,
        group: str,
        count=None,
        message: str = "",
        error: bool = False,
        meta=None,
        policy_override: bool = False,
    ):
        """Scale one task group's count and record a scaling event
        (reference nomad/job_endpoint.go Job.Scale).  ``count=None``
        records the event without changing the job — the autoscaler's
        status-report path."""
        import copy

        from ..structs import ScalingEvent

        job = self.store.job_by_id(namespace, job_id)
        if job is None:
            raise KeyError(f"job {job_id!r} not found")
        # never mutate the store-resident object: it is also the
        # newest entry in the version history
        job = copy.deepcopy(job)
        tg = job.lookup_task_group(group)
        if tg is None:
            raise ValueError(f"unknown task group {group!r}")
        ev = None
        previous = tg.count
        if count is not None:
            count = int(count)
            pol = self.store.scaling_policy_by_target(
                namespace, job_id, group
            )
            if pol is not None and not policy_override:
                if count < pol.min:
                    raise ValueError(
                        f"group count {count} below scaling policy "
                        f"minimum {pol.min}"
                    )
                if pol.max and count > pol.max:
                    raise ValueError(
                        f"group count {count} above scaling policy "
                        f"maximum {pol.max}"
                    )
            tg.count = count
            ev = self.register_job(job)
        event = ScalingEvent(
            count=count,
            previous_count=previous,
            message=message,
            error=error,
            eval_id=ev.id if ev else None,
            meta=dict(meta or {}),
        )
        self.store.upsert_scaling_event(namespace, job_id, group, event)
        return ev, event

    def validate_job(self, job: Job) -> None:
        """Public validation surface (reference Job.Validate RPC
        backing /v1/validate/job)."""
        self._validate_job(job)

    def _validate_job(self, job: Job) -> None:
        if not job.id:
            raise ValueError("missing job ID")
        if not job.task_groups:
            raise ValueError("job requires at least one task group")
        names = set()
        for tg in job.task_groups:
            if tg.name in names:
                raise ValueError(f"duplicate task group {tg.name!r}")
            names.add(tg.name)
            if tg.count < 0:
                raise ValueError("task group count must be >= 0")
            if not tg.tasks and job.type != JOB_TYPE_CORE:
                raise ValueError(
                    f"task group {tg.name!r} requires at least one task"
                )
        if job.type not in ("service", "batch", "system"):
            raise ValueError(f"invalid job type {job.type!r}")
        if (
            job.namespace != "default"
            and self.store.namespace_by_name(job.namespace) is None
        ):
            raise ValueError(
                f"namespace {job.namespace!r} does not exist"
            )

    # -- node API (reference nomad/node_endpoint.go) --------------------

    def register_node(self, node: Node) -> None:
        first_seen = self.store.node_by_id(node.id) is None
        if node.status == "initializing":
            node.status = NODE_STATUS_READY
        self.store.upsert_node(node)
        self._emit_node_event(
            node.id,
            "Node registered" if first_seen else "Node re-registered",
        )
        self._reset_heartbeat(node.id)
        self.blocked.unblock(
            node.computed_class, self.store.latest_index()
        )
        self._create_node_evals(node.id)

    def heartbeat(self, node_id: str) -> None:
        """(reference nomad/heartbeat.go resetHeartbeatTimer)"""
        node = self.store.node_by_id(node_id)
        if node is None:
            raise KeyError(node_id)
        if node.status == NODE_STATUS_DOWN:
            self.update_node_status(node_id, NODE_STATUS_READY)
        self._reset_heartbeat(node_id)

    def _reset_heartbeat(self, node_id: str) -> None:
        # TTL deadlines are a leader-only service (reference
        # heartbeat.go runs on the leader; followers forward
        # Node.UpdateStatus)
        if not (self._running and self._leader_established):
            self._heartbeat_deadlines.pop(node_id, None)
            self._down_wave.pop(node_id, None)
            return
        self._heartbeat_deadlines[node_id] = (
            time.monotonic() + self.heartbeat_ttl
        )
        # a node heartbeating while its expiry sits in a gathering
        # down-wave was never dead: pull it back out before the wave
        # commits (zero false node-downs under mass-death gather)
        self._down_wave.pop(node_id, None)
        self._ensure_sweeper()

    def _ensure_sweeper(self) -> None:
        """(Re)spawn the heartbeat sweeper if it is missing or died.
        Called from every heartbeat reset AND from leadership
        establish — a crashed sweeper must never silently stop TTL
        enforcement for as long as traffic flows."""
        if not (self._running and self._leader_established):
            return
        with self._sweeper_lock:
            if self._heartbeat_sweeper is None or not (
                self._heartbeat_sweeper.is_alive()
            ):
                self._heartbeat_sweeper = threading.Thread(
                    target=self._sweep_heartbeats,
                    name="heartbeat-sweeper",
                    daemon=True,
                )
                self._heartbeat_sweeper.start()

    def _sweep_heartbeats(self) -> None:
        while self._running:
            interval = max(
                0.02, min(0.5, self.heartbeat_ttl / 5.0)
            )
            time.sleep(interval)
            if not self._leader_established:
                self._down_wave.clear()
                continue
            try:
                self._sweep_once(interval)
            except Exception:  # noqa: BLE001 — TTL enforcement must
                # survive any single sweep's failure; a dead sweeper
                # silently stops node-death detection cluster-wide
                LOG.exception("heartbeat sweep failed")

    def _sweep_once(self, interval: float) -> None:
        """One sweep: collect every TTL expiry, fold it into the
        pending down-wave, and commit the wave as ONE batched
        transition when it has settled (or immediately when it is
        below the mass-death gather threshold)."""
        now = time.monotonic()
        expired = [
            node_id
            for node_id, deadline in list(
                self._heartbeat_deadlines.items()
            )
            if deadline <= now
        ]
        for node_id in expired:
            current = self._heartbeat_deadlines.get(node_id)
            if current is None or current > now:
                continue  # heartbeated (refreshed) since the scan
            self._heartbeat_deadlines.pop(node_id, None)
            self._down_wave[node_id] = now
        if not self._down_wave:
            return
        stamps = list(self._down_wave.values())
        wave_started = min(stamps)
        last_new = max(stamps)
        if len(self._down_wave) >= self._wave_min:
            # correlated failure: settle until the expiry stream has
            # been quiet for two full seconds (heartbeat phases
            # spread a rack death across sweeps, and scheduler work
            # under overload stalls sweeps mid-stream — a short
            # settle fragments the wave, and a fragment whose jobs
            # overlap the first wave's outstanding evals trickles
            # through the per-job pending heaps into extra storm
            # solves), capped by the gather budget.
            settle_s = max(interval, min(2.0, self._wave_gather_s))
        else:
            # below the mass threshold: hold ONE extra sweep.  A
            # rack death's leading edge (the first sweep sees only a
            # couple of nodes, which may host dozens of jobs) must
            # merge into the mass wave behind it instead of
            # committing — and storming — on its own; a genuinely
            # single node death pays one sweep interval of extra
            # detection latency.
            settle_s = interval
        if (
            now - last_new < settle_s
            and now - wave_started < self._wave_gather_s
        ):
            return
        wave = list(self._down_wave.keys())
        self._down_wave.clear()
        self._heartbeats_expired(wave)

    def _heartbeats_expired(self, node_ids: List[str]) -> None:
        """Missed TTLs: the whole wave goes down in ONE batched state
        transition (one FSM apply — a 500-node rack death is one
        replicated command, not 500 serialized writes under the store
        lock), and its replan evals are enqueued as ONE storm family
        so the batch worker coalesces the replanning into a global
        assignment solve instead of per-eval chunk-chain walks
        (reference heartbeat.go:135 invalidateHeartbeat, batched)."""
        from ..trace import TRACE

        node_ids = [
            node_id
            for node_id in node_ids
            # a member whose deadline was RE-ARMED between the wave
            # snapshot and this commit heartbeated through the race
            # window — it was never dead, drop it (the last line of
            # the zero-false-node-downs defense; the mid-gather pop
            # in _reset_heartbeat covers the gather window, this
            # covers the snapshot->commit window)
            if node_id not in self._heartbeat_deadlines
            and (node := self.store.node_by_id(node_id)) is not None
            and node.status != NODE_STATUS_DOWN
        ]
        if not node_ids:
            return
        self.store.update_node_statuses(
            node_ids,
            NODE_STATUS_DOWN,
            message="Node heartbeat missed",
        )
        # one family hint per wave: replan evals across MANY unrelated
        # jobs still coalesce into one storm drain (job_family honors
        # the hint); single-node waves carry it too — harmless below
        # the storm trigger threshold
        wave_n = next(self._wave_counter)
        hint = f"node-down:w{wave_n}"
        evals = self._create_node_evals_batch(
            node_ids, family_hint=hint
        )
        self.metrics.incr("overload.node_down_waves")
        self.metrics.set_gauge(
            "overload.last_wave_nodes", float(len(node_ids))
        )
        # flight-recorder incident: one trace per down-wave, the
        # operator's handle for "which nodes, how many evals, which
        # storm family" after a mass death
        incident = f"node_down_wave:{wave_n}"
        TRACE.begin(
            incident,
            root_span="server.node_down_wave",
            nodes=len(node_ids),
            evals=len(evals),
            family=hint,
            sample_nodes=node_ids[:8],
        )
        TRACE.finish(incident, "recorded")

    def _emit_node_event(
        self, node_id: str, message: str, subsystem: str = "Cluster"
    ) -> None:
        """(reference node_endpoint.go emitting NodeEvents via
        UpsertNodeEventsType raft entries)"""
        from ..structs import NodeEvent

        try:
            self.store.upsert_node_events(
                node_id,
                [NodeEvent(message=message, subsystem=subsystem)],
            )
        except KeyError:
            pass

    def update_node_status(self, node_id: str, status: str) -> None:
        prev = self.store.node_by_id(node_id)
        prev_status = prev.status if prev is not None else ""
        self.store.update_node_status(node_id, status)
        if status != prev_status:
            self._emit_node_event(
                node_id,
                (
                    "Node heartbeat missed"
                    if status == NODE_STATUS_DOWN
                    else f"Node status changed to {status}"
                ),
            )
        node = self.store.node_by_id(node_id)
        if status == NODE_STATUS_READY:
            self._reset_heartbeat(node_id)
            self.blocked.unblock(
                node.computed_class, self.store.latest_index()
            )
        self._create_node_evals(node_id)

    def update_node_drain(
        self, node_id: str, drain: bool, strategy=None
    ) -> None:
        self.store.update_node_drain(node_id, drain, strategy)
        self._emit_node_event(
            node_id,
            "Node drain strategy set" if drain else "Node drain complete",
            subsystem="Drain",
        )
        self._create_node_evals(node_id)

    def update_node_eligibility(
        self, node_id: str, eligibility: str
    ) -> None:
        self.store.update_node_eligibility(node_id, eligibility)
        self._emit_node_event(
            node_id, f"Node marked {eligibility}", subsystem="Cluster"
        )
        node = self.store.node_by_id(node_id)
        if eligibility == "eligible":
            self.blocked.unblock(
                node.computed_class, self.store.latest_index()
            )

    def _create_node_evals(self, node_id: str) -> List[Evaluation]:
        """One eval per job with allocs on the node, plus system jobs
        (reference node_endpoint.go:1316 createNodeEvals)."""
        return self._create_node_evals_batch([node_id])

    def _create_node_evals_batch(
        self, node_ids: List[str], family_hint: str = ""
    ) -> List[Evaluation]:
        """The wave form of ``_create_node_evals``: ONE eval per
        affected (namespace, job) across the whole node wave — a
        500-node death whose allocs span 120 jobs creates 120 evals,
        not 500 x per-node fan-outs — persisted in one upsert and
        stamped with the wave's ``family_hint`` so the broker's
        storm detector sees them as one family."""
        evals = []
        seen_jobs = set()
        for node_id in node_ids:
            for alloc in self.store.allocs_by_node(node_id):
                key = (alloc.namespace, alloc.job_id)
                if key in seen_jobs:
                    continue
                seen_jobs.add(key)
                job = self.store.job_by_id(*key)
                sched_type = (
                    job.type if job is not None else JOB_TYPE_SERVICE
                )
                ev = Evaluation(
                    namespace=alloc.namespace,
                    priority=job.priority if job else 50,
                    type=sched_type,
                    triggered_by=EVAL_TRIGGER_NODE_UPDATE,
                    job_id=alloc.job_id,
                    node_id=node_id,
                    family_hint=family_hint,
                    status=EVAL_STATUS_PENDING,
                )
                evals.append(ev)
        # system jobs: ONE pass for the whole wave (seen_jobs dedups
        # to one eval per job anyway — scanning iter_jobs once per
        # node made a 500-node death O(nodes x jobs) store calls in
        # the sweeper's critical replan path); a job fires off the
        # first wave node matching its datacenters
        wave_nodes = [
            (node_id, node)
            for node_id in node_ids
            if (node := self.store.node_by_id(node_id)) is not None
        ]
        for job in self.store.iter_jobs():
            if job.type != "system" or job.stopped():
                continue
            key = (job.namespace, job.id)
            if key in seen_jobs:
                continue
            trigger = next(
                (
                    node_id
                    for node_id, node in wave_nodes
                    if not job.datacenters
                    or node.datacenter in job.datacenters
                ),
                None,
            )
            if trigger is None:
                continue
            seen_jobs.add(key)
            evals.append(
                Evaluation(
                    namespace=job.namespace,
                    priority=job.priority,
                    type="system",
                    triggered_by=EVAL_TRIGGER_NODE_UPDATE,
                    job_id=job.id,
                    node_id=trigger,
                    family_hint=family_hint,
                    status=EVAL_STATUS_PENDING,
                )
            )
        if evals:
            self.store.upsert_evals(evals)
            if family_hint:
                # the whole wave lands in ONE broker lock acquisition:
                # per-eval enqueues trickle the family in, and a GIL
                # hiccup mid-loop lets the storm detector's settle
                # beat cut the stream — fragmenting a 500-node death
                # into several solves
                self.broker.enqueue_all(
                    [ev for ev in evals if ev.should_enqueue()]
                )
                for ev in evals:
                    if not ev.should_enqueue():
                        self.on_eval_update(ev)
            else:
                for ev in evals:
                    self.on_eval_update(ev)
        return evals

    # -- client-side alloc updates (reference node_endpoint.go:1065) ----

    # -- job plan: dry-run an eval without committing
    # (reference nomad/job_endpoint.go Plan + scheduler/annotate.go) ----

    def plan_job(self, job: Job, diff: bool = True) -> Dict:
        """Run the scheduler against a snapshot with plan submission
        rejected, returning the would-be changes per task group."""
        from ..sched.generic_sched import BatchScheduler, ServiceScheduler
        from ..sched.system_sched import SystemScheduler
        from ..sched.testing import Harness
        from ..structs import EVAL_TRIGGER_JOB_REGISTER

        self._validate_job(job)
        # same admission hooks as register: the dry-run must predict
        # the job as it would actually be stored (connect sidecars
        # included), or `nomad plan` under-reports the placements
        self._inject_connect_sidecars(job)
        self._interpolate_multiregion(job)
        # run against a snapshot with the new job overlaid — the store
        # itself is never touched, so a replicated store can't diverge
        prev = self.store.job_by_id(job.namespace, job.id)
        recorder = _PlanRecorder(self.store)
        ev = Evaluation(
            namespace=job.namespace,
            priority=job.priority,
            type=job.type,
            triggered_by=EVAL_TRIGGER_JOB_REGISTER,
            job_id=job.id,
            annotate_plan=True,
            status=EVAL_STATUS_PENDING,
        )
        factory = {
            "service": ServiceScheduler,
            "batch": BatchScheduler,
            "system": SystemScheduler,
        }[job.type]
        if job.version == 0 and prev is not None:
            job.version = prev.version + 1
        snap = self.store.snapshot()
        snap.override_job(job)
        scheduler = factory(snap, recorder, seed=0)
        scheduler.process(ev)
        annotations = {}
        if recorder.plans and recorder.plans[-1].annotations:
            raw = recorder.plans[-1].annotations.get(
                "desired_tg_updates", {}
            )
            annotations = {
                tg: {
                    "Place": du.place,
                    "Stop": du.stop,
                    "Migrate": du.migrate,
                    "InPlaceUpdate": du.in_place_update,
                    "DestructiveUpdate": du.destructive_update,
                    "Canary": du.canary,
                    "Ignore": du.ignore,
                }
                for tg, du in raw.items()
            }
        from ..explain import alloc_metric_to_api

        failed = {}
        for e in recorder.evals:
            for tg, metric in (e.failed_tg_allocs or {}).items():
                # full Nomad API AllocMetric shape (ScoreMetaData is
                # top-K trimmed on this read)
                failed[tg] = alloc_metric_to_api(metric)
        return {
            "Annotations": annotations,
            "FailedTGAllocs": failed,
            "Diff": self._job_diff(prev, job) if diff else None,
        }

    @staticmethod
    def _job_diff(old: Optional[Job], new: Job) -> Dict:
        """Field-level diff summary (reference nomad/structs/diff.go,
        condensed to the fields the plan UX shows)."""
        if old is None:
            return {"Type": "Added"}
        changes = {}
        for attr in ("type", "priority", "datacenters"):
            a, b = getattr(old, attr), getattr(new, attr)
            if a != b:
                changes[attr] = {"Old": a, "New": b}
        old_groups = {tg.name: tg for tg in old.task_groups}
        new_groups = {tg.name: tg for tg in new.task_groups}
        group_changes = {}
        for name in old_groups.keys() | new_groups.keys():
            og, ng = old_groups.get(name), new_groups.get(name)
            if og is None:
                group_changes[name] = {"Type": "Added"}
            elif ng is None:
                group_changes[name] = {"Type": "Deleted"}
            elif og != ng:
                entry = {"Type": "Edited"}
                if og.count != ng.count:
                    entry["Count"] = {"Old": og.count, "New": ng.count}
                group_changes[name] = entry
        if group_changes:
            changes["TaskGroups"] = group_changes
        return {"Type": "Edited" if changes else "None", **changes}

    # -- parameterized jobs (reference nomad/job_endpoint.go Dispatch) --

    def dispatch_job(
        self,
        namespace: str,
        job_id: str,
        meta: Optional[Dict[str, str]] = None,
        payload: Optional[bytes] = None,
    ) -> Job:
        from dataclasses import replace as _replace

        parent = self.store.job_by_id(namespace, job_id)
        if parent is None:
            raise KeyError(job_id)
        if not parent.is_parameterized():
            raise ValueError(f"job {job_id!r} is not parameterized")
        spec = parent.parameterized or {}
        required = set(spec.get("meta_required", ()))
        optional = set(spec.get("meta_optional", ()))
        meta = dict(meta or {})
        missing = required - set(meta)
        if missing:
            raise ValueError(f"missing required meta: {sorted(missing)}")
        unexpected = set(meta) - required - optional
        if unexpected:
            raise ValueError(
                f"unpermitted meta keys: {sorted(unexpected)}"
            )
        if payload and spec.get("payload") == "forbidden":
            raise ValueError("payload is forbidden for this job")
        if not payload and spec.get("payload") == "required":
            raise ValueError("payload is required for this job")

        from ..structs import new_id

        child = _replace(parent)
        child.id = f"{parent.id}/dispatch-{new_id()[:8]}"
        child.name = child.id
        child.parent_id = parent.id
        child.parameterized = None
        child.meta = {**parent.meta, **meta}
        child.payload = bytes(payload or b"")
        self.register_job(child)
        return child

    # -- client registry for log/fs proxying (reference
    # nomad/client_rpc.go persistent connections) -----------------------

    def register_client(self, node_id: str, client) -> None:
        self._clients[node_id] = client

    def read_task_log(
        self, alloc_id: str, task: str, kind: str = "stdout",
        max_bytes: int = 64 * 1024,
    ) -> bytes:
        """(reference client fs/logs endpoints via server proxy)"""
        alloc = self.store.alloc_by_id(alloc_id)
        if alloc is None:
            raise KeyError(alloc_id)
        client = self._clients.get(alloc.node_id)
        if client is None:
            raise KeyError(f"no client connection for {alloc.node_id}")
        if hasattr(client, "read_task_log"):
            # remote client proxy: the files live on ITS disk
            return client.read_task_log(
                alloc_id, task, kind, max_bytes
            )
        import os

        # rotated logs first (client/logmon layout under alloc/logs/),
        # then the flat legacy path
        from ..client.logmon import read_task_log as _read_rotated

        log_dir = os.path.join(
            client.data_dir, "allocs", alloc_id, "alloc", "logs"
        )
        data = _read_rotated(log_dir, task, kind, max_bytes)
        if data:
            return data
        path = os.path.join(
            client.data_dir, "allocs", alloc_id, f"{task}.{kind}"
        )
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - max_bytes))
                return f.read()
        except OSError:
            return b""

    def update_allocs_from_client(self, updates: List[Allocation]) -> None:
        """Client pushes alloc status changes; terminal transitions free
        capacity and may trigger reschedule evals."""
        self.store.upsert_allocs(updates)
        evals = []
        seen = set()
        for alloc in updates:
            if not alloc.terminal_status():
                continue
            node = self.store.node_by_id(alloc.node_id)
            if node is not None:
                self.blocked.unblock(
                    node.computed_class, self.store.latest_index()
                )
            key = (alloc.namespace, alloc.job_id)
            if key in seen:
                continue
            job = self.store.job_by_id(*key)
            if job is None or job.stopped():
                continue
            if alloc.client_status == ALLOC_CLIENT_STATUS_FAILED:
                seen.add(key)
                evals.append(
                    Evaluation(
                        namespace=alloc.namespace,
                        priority=job.priority,
                        type=job.type,
                        triggered_by="alloc-failure",
                        job_id=alloc.job_id,
                        status=EVAL_STATUS_PENDING,
                    )
                )
        if evals:
            self.store.upsert_evals(evals)
            for ev in evals:
                self.on_eval_update(ev)

    # -- GC (reference nomad/core_sched.go; system gc endpoint) ----------

    def force_gc(self) -> None:
        from ..sched.core_sched import CORE_JOB_FORCE_GC
        from ..structs import JOB_TYPE_CORE

        ev = Evaluation(
            priority=100,
            type=JOB_TYPE_CORE,
            triggered_by="scheduled",
            job_id=CORE_JOB_FORCE_GC,
            status=EVAL_STATUS_PENDING,
        )
        self.store.upsert_evals([ev])
        self.on_eval_update(ev)

    # -- cluster observability (one server's share of a fan-in) ----------

    def _obs_local(self, what: str, params: dict) -> dict:
        """Serve this server's share of a cluster observability query
        (the `obs_query` RPC target, and the local half of every
        /v1/cluster/* merge).  Read-only and NOT leader-gated: every
        server's trace ring / metrics / history is its own."""
        from ..trace import TRACE

        if what == "traces":
            slow_ms = params.get("slow_ms")
            limit = int(params.get("limit", 64))
            return {
                "traces": TRACE.recent(
                    slow_ms=float(slow_ms)
                    if slow_ms is not None
                    else None,
                    outcome=params.get("outcome"),
                    limit=max(1, min(limit, 1024)),
                    full=bool(params.get("full")),
                )
            }
        if what == "trace":
            return {"trace": TRACE.get(params.get("ref", ""))}
        if what == "metrics":
            return {"metrics": self.metrics.dump()}
        if what == "metrics_history":
            return {"history": self.metrics_history.to_dict()}
        if what == "explain":
            from ..explain import EXPLAIN

            return {"explain": EXPLAIN.get(params.get("eval_id", ""))}
        if what == "slo":
            return {"slo": self.slo.status()}
        if what == "decisions":
            limit = int(params.get("limit", 64))
            return {
                "decisions": self.decisions.to_dict(
                    site=params.get("site"),
                    outcome=params.get("outcome"),
                    trace=params.get("trace"),
                    limit=max(1, min(limit, 1024)),
                )
            }
        raise ValueError(f"unknown obs query {what!r}")

    # -- helpers ---------------------------------------------------------

    def drain_to_idle(self, timeout: float = 10.0) -> bool:
        """Wait until no evals are in flight (test/bench helper)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if (
                self.broker.ready_count() == 0
                and self.broker.stats["total_unacked"] == 0
                and self.plan_queue.stats["depth"] == 0
            ):
                return True
            time.sleep(0.01)
        return False
