"""Cluster fan-out bench: scheduling throughput vs server count.

Boots 1 / 3 / 5-server raft clusters with follower fan-out enabled
(``NOMAD_TPU_FANOUT=1``) and plays the SAME workload through each
topology: by default the swarm shape (hundreds of independent
single-alloc jobs staged as one standing backlog — ``--jobs-per`` >1
switches to dispatch-family storm shape, where each family is
coalescible into global assignment solves).  With one server every
placement is planned on the leader; with 3/5 the same backlog fans
out across follower planners while commit stays serialized on the
leader's plan queue.

Two throughput numbers per topology, deliberately distinct:

* ``wall_placements_per_s`` — raw wall-clock drain rate.  The whole
  bench runs in ONE process (``TestCluster``), so on a single-core
  harness host every "server" shares one CPU and one GIL and this
  number CANNOT scale however well planning distributes — the same
  situation as the PR 8 mesh bench, whose virtual CPU devices
  measure per-device FLOP scaling rather than wall clock.
* ``capacity_placements_per_s`` — evals divided by the BOTTLENECK
  server's worker-thread CPU time (``/proc/self/task/<tid>/stat``,
  threads named ``worker@<addr>``; parallel replay is pinned off so
  replay work lands on the worker thread).  Planning CPU is what
  each server's own cores must serially grind through on a real
  deployment, so the busiest server bounds cluster scheduling
  throughput — and unlike wall-clock stage timings it does not
  inflate with GIL waits on a contended core.  The headline
  ``speedup_3v1`` is computed on THIS number: the measured
  load-spread of the planning plane, including every fan-out
  overhead that burns worker CPU (lease/plan pickling, remote
  snapshot staleness, rescore loops, conflict fallbacks).  Each
  topology runs ``reps`` times and the best-capacity rep represents
  it — on a shared core every noise source (GIL-lottery imbalance,
  cache thrash) biases capacity strictly downward, so best-of-N is
  the least-biased estimator of the machine-independent value; even
  so, expect run-to-run swing on a 1-CPU harness (``host_cpus`` is
  exported so readers can judge).

A warmup pass (untimed, same workload, blocking compiles) runs
first so XLA compiles land outside every measured topology —
without it the first topology eats multi-second kernel compiles and
the comparison measures compile order, not scheduling.

Correctness is gated alongside throughput: every run must place
every job (zero lost evals, empty failed queue, no leaked remote
leases) and every topology's placement set must match the
single-server oracle's placement-key set (order-independent ``(job,
task-group, alloc-name)`` keys — fan-out must change WHERE planning
happens, never WHAT gets placed).

Usage::

    python -m nomad_tpu.server.fanout_bench [--servers 1,3,5]
        [--families F] [--jobs-per M] [--nodes N] [--json PATH]

Exit code 0 = every invariant held (speedups are reported, not gated
here — the BENCH acceptance asserts the 1->3 ratio); 2 = a lost
eval / parity violation (the JSON names it).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Set, Tuple

HEARTBEAT_TTL = 300.0  # no TTL expiries during the bench

# worker stage timings that constitute PLANNING work — everything the
# batch pipeline burns CPU on per eval.  Waits and RPC round trips
# are deliberately absent: on a real deployment they overlap planning
PLANNING_STAGES = (
    "simulate",
    "assemble",
    "admit",
    "launch",
    "fetch",
    "mesh_launch",
    "mesh_fetch",
    "storm_solve",
    "storm_decompose",
    "replay",
    "sequential",
)


def _live_placements(store) -> Set[Tuple[str, str, str]]:
    out: Set[Tuple[str, str, str]] = set()
    for alloc in store.allocs.values():
        if alloc.terminal_status():
            continue
        out.add((alloc.job_id, alloc.task_group, alloc.name))
    return out


def _make_nodes(n: int):
    import random

    from .. import mock

    rng = random.Random(7)
    out = []
    for i in range(n):
        node = mock.node(id=f"fan-node-{i:05d}")
        node.node_resources.cpu = rng.choice([8000, 16000])
        node.node_resources.memory_mb = rng.choice([16384, 32768])
        out.append(node)
    return out


def _family_jobs(families: int, jobs_per: int, tag: str = ""):
    """Storm-shaped load: ``families`` dispatch families of
    ``jobs_per`` sibling jobs each — the broker's family detector
    coalesces each contiguous family prefix into one global solve,
    and distinct families fan out across servers."""
    from .. import mock

    out = []
    for f in range(families):
        for i in range(jobs_per):
            job = mock.job(
                id=f"fanfam{tag}-{f:03d}/dispatch-{i:04d}"
            )
            job.type = "batch"
            job.task_groups[0].count = 1
            job.task_groups[0].tasks[0].resources.cpu = 500
            job.task_groups[0].tasks[0].resources.memory_mb = 1024
            out.append(job)
    return out


def _worker_cpu_by_server(cluster) -> Dict[str, float]:
    """Per-server worker-thread CPU seconds, read from
    ``/proc/self/task/*/stat`` by thread name (``worker@<addr>``).

    CPU time is the contention-proof planning metric on a shared
    host: wall-clock stage timings inflate with every other runnable
    thread (a GIL wait is "busy" wall time), while CPU time counts
    only executed work — and a commit-plane wait or an idle dequeue
    burns none.  With parallel replay off (the bench pins it off so
    replay work lands on the worker thread), a worker thread's CPU
    IS that server's planning compute."""
    import threading

    hz = float(os.sysconf("SC_CLK_TCK"))
    out: Dict[str, float] = {
        server.addr: 0.0 for server in cluster.servers
    }
    for thread in threading.enumerate():
        name = thread.name
        if not name.startswith("worker@"):
            continue
        addr = name.split("@", 1)[1]
        if addr not in out:
            continue
        tid = thread.native_id
        if tid is None:
            continue
        try:
            with open(f"/proc/self/task/{tid}/stat") as fh:
                data = fh.read()
        except OSError:
            continue  # thread exited mid-scan
        fields = data[data.rindex(")") + 2 :].split()
        out[addr] += (int(fields[11]) + int(fields[12])) / hz
    return {addr: round(cpu, 4) for addr, cpu in out.items()}


def _planning_busy_by_server(
    cluster,
) -> Tuple[Dict[str, float], Dict[str, float]]:
    """Per-server (planning busy, commit wait) wall seconds: the sum
    of every worker's planning-stage timings net of its
    ``plan_wait_s``, plus that wait itself — the leader's own batch
    workers and any follower fan-out workers.  Captured BEFORE
    cluster.stop() tears the fan-out fleets down."""
    busy_out: Dict[str, float] = {}
    wait_out: Dict[str, float] = {}
    for server in cluster.servers:
        busy = 0.0
        wait = 0.0
        workers = list(getattr(server, "workers", ()))
        fanout = getattr(server, "fanout", None)
        if fanout is not None:
            workers.extend(fanout.workers)
        for worker in workers:
            timings = getattr(worker, "timings", None)
            if not timings:
                continue
            busy += sum(
                timings.get(stage, 0.0) for stage in PLANNING_STAGES
            )
            # the replay/sequential stages contain the time the
            # worker sat BLOCKED on the serialized commit plane
            # (plan-queue verdict; for fan-out workers the remote
            # submit RPC + local-apply catch-up) — commit latency,
            # not planning compute.  Tracked uniformly by
            # Worker.plan_wait_s and netted out, then reported
            # separately: commit is the part that stays serialized
            # by design.
            wait += getattr(worker, "plan_wait_s", 0.0)
        busy_out[server.addr] = round(max(0.0, busy - wait), 4)
        wait_out[server.addr] = round(wait, 4)
    return busy_out, wait_out


def _run_topology(
    n_servers: int,
    nodes: int,
    families: int,
    jobs_per: int,
    seed: int = 0,
    tag: str = "",
) -> Dict:
    from ..raft import NotLeaderError
    from ..raft.transport import TransportError
    from .cluster import TestCluster

    cluster = TestCluster(
        n_servers,
        heartbeat_ttl=HEARTBEAT_TTL,
        name_prefix=f"fan{tag}{n_servers}",
    )
    try:
        cluster.start()
        leader = cluster.wait_for_leader(timeout=30.0)
        for node in _make_nodes(nodes):
            leader.register_node(node)

        # NOTE: job ids are identical across every topology and rep
        # (the tag names only the throwaway cluster) — placement-set
        # parity compares keys that embed the job id
        # stage the backlog with every consumer PAUSED, then release:
        # the measured drain starts from a standing same-family
        # backlog — the mass-drain / restore-wave shape the storm
        # detector exists for (PR 9's bench registers its family
        # before leadership for the same reason).  Unpaused
        # submission would let N racing consumers hold queue depth
        # at ~zero and the comparison would measure arrival pacing,
        # not scheduling throughput.
        def _all_workers():
            out = []
            for server in cluster.servers:
                out.extend(getattr(server, "workers", ()))
                fanout = getattr(server, "fanout", None)
                if fanout is not None:
                    out.extend(fanout.workers)
            return out

        if n_servers > 1:
            # fan-out fleets spawn async once a leader is known
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                fleets = [
                    s.fanout.workers
                    for s in cluster.servers
                    if not s.is_leader()
                ]
                if fleets and all(fleets):
                    break
                time.sleep(0.02)
        for worker in _all_workers():
            worker.set_pause(True)
        jobs = _family_jobs(families, jobs_per)
        rr = 0
        for job in jobs:
            for _attempt in range(100):
                server = cluster.servers[rr % n_servers]
                rr += 1
                try:
                    server.register_job(job)
                    break
                except (
                    NotLeaderError,
                    TransportError,
                    TimeoutError,
                ):
                    time.sleep(0.02)
            else:
                raise AssertionError(f"could not submit {job.id}")
        t0 = time.monotonic()
        for worker in _all_workers():
            worker.set_pause(False)
        # settle: every job fully placed and the pipeline idle
        deadline = time.monotonic() + 240.0
        placed = 0
        while time.monotonic() < deadline:
            leader = cluster.wait_for_leader(timeout=30.0)
            store = leader.store
            placed = sum(
                1
                for job in jobs
                if any(
                    not a.terminal_status()
                    for a in store.allocs_by_job("default", job.id)
                )
            )
            if placed == len(jobs) and leader.drain_to_idle(
                timeout=1.0
            ):
                break
            time.sleep(0.05)
        elapsed = time.monotonic() - t0
        busy, commit_wait = _planning_busy_by_server(cluster)
        cpu = _worker_cpu_by_server(cluster)
        bottleneck = max(cpu.values()) if cpu else 0.0
        store = leader.store
        placements = _live_placements(store)
        lost = len(jobs) - placed
        counters = {
            name: sum(
                s.metrics.get_counter(name)
                for s in cluster.servers
            )
            for name in (
                "fanout.remote_dequeues",
                "fanout.leases",
                "fanout.plans_submitted",
                "fanout.remote_leases_granted",
                "storm.solves",
                "storm.evals",
            )
        }
        return {
            "servers": n_servers,
            "wall_s": round(elapsed, 3),
            "placements": placements,
            "placements_total": len(placements),
            "wall_placements_per_s": round(
                len(placements) / elapsed, 1
            )
            if elapsed > 0
            else 0.0,
            "planning_wall_s": busy,
            "planning_cpu_s": cpu,
            "commit_wait_s": commit_wait,
            "bottleneck_planning_s": round(bottleneck, 4),
            "capacity_placements_per_s": round(
                len(placements) / bottleneck, 1
            )
            if bottleneck > 0
            else 0.0,
            "lost": lost,
            "failed_queue": len(leader.broker.failed()),
            "remote_unacked_after": (
                leader.broker.remote_unacked_count()
            ),
            "follower_plans": counters["fanout.plans_submitted"],
            "counters": counters,
        }
    finally:
        cluster.stop()


def run_fanout_bench(
    server_counts: Tuple[int, ...] = (1, 3, 5),
    families: int = 600,
    jobs_per: int = 1,
    nodes: int = 2048,
    seed: int = 0,
    reps: int = 5,
) -> Dict:
    """The ``cluster_fanout`` bench block: an untimed warmup,
    ``reps`` runs per topology on the same workload (the BEST
    capacity run represents each topology: on a shared-core harness
    every noise source — GIL-lottery load imbalance, cache thrash,
    background threads — biases measured capacity strictly DOWNWARD
    from the machine-independent ideal, extra CPU inflates the
    denominator and imbalance can only raise the bottleneck share
    above total/N, so best-of-N is the least-biased estimator),
    wall + planning-capacity throughput ratios against the
    single-server oracle, and the correctness gates (zero lost
    across EVERY rep, placement-set parity, no leaked remote
    leases)."""
    knobs = {
        "NOMAD_TPU_FANOUT": "1",
        "NOMAD_TPU_STORM": "1",
        "NOMAD_TPU_STORM_MIN": "8",
        "NOMAD_TPU_STORM_MAX": "512",
        # replay on the worker thread: the per-server planning-CPU
        # attribution reads worker-thread CPU clocks, and on the
        # bench's single-core harness the replay pool gains nothing
        # anyway
        "NOMAD_TPU_PARALLEL_REPLAY": "0",
        # fine-grained work units: small gulps and small lease
        # batches are the work-stealing grain that keeps the
        # planning load balanced across servers (a 64-eval hoard on
        # one server would become the bottleneck), and they pin the
        # compiled-shape universe to a closed, warmable set
        "NOMAD_TPU_BATCH_MAX": "8",
        "NOMAD_TPU_FANOUT_LEASE_N": "4",
    }
    saved = {k: os.environ.get(k) for k in knobs}
    saved["NOMAD_TPU_SYNC_COMPILE"] = os.environ.get(
        "NOMAD_TPU_SYNC_COMPILE"
    )
    os.environ.update(knobs)
    try:
        # warmup: the FULL workload through throwaway clusters with
        # blocking compiles — a fragmented multi-consumer backlog
        # exercises the NARROW chunk widths (2/4) and partial storm
        # buckets a 1-server warmup never compiles, and the measured
        # topologies would otherwise eat those compiles as
        # cold-shape sequential fallbacks (measuring compile order,
        # not scheduling)
        os.environ["NOMAD_TPU_SYNC_COMPILE"] = "1"
        for i, warm_n in enumerate(
            sorted({min(n, 3) for n in server_counts})
        ):
            _run_topology(
                warm_n,
                nodes=nodes,
                families=families,
                jobs_per=jobs_per,
                seed=seed,
                tag=f"w{i}",
            )
        if saved["NOMAD_TPU_SYNC_COMPILE"] is None:
            os.environ.pop("NOMAD_TPU_SYNC_COMPILE", None)
        else:
            os.environ["NOMAD_TPU_SYNC_COMPILE"] = saved[
                "NOMAD_TPU_SYNC_COMPILE"
            ]
        all_runs: Dict[int, List[Dict]] = {}
        for n in server_counts:
            all_runs[n] = [
                _run_topology(
                    n,
                    nodes=nodes,
                    families=families,
                    jobs_per=jobs_per,
                    seed=seed,
                    tag=f"r{rep}" if rep else "",
                )
                for rep in range(max(1, reps))
            ]
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    def best_run(candidates: List[Dict]) -> Dict:
        return max(
            candidates,
            key=lambda r: r["capacity_placements_per_s"],
        )

    runs = [best_run(all_runs[n]) for n in server_counts]
    flat = [r for rs in all_runs.values() for r in rs]
    oracle = runs[0]
    expected = families * jobs_per
    parity_ok = all(
        r["placements"] == oracle["placements"] for r in flat
    )
    lost_total = sum(r["lost"] for r in flat)
    fanout_engaged = all(
        r["follower_plans"] > 0 for r in flat if r["servers"] > 1
    )
    leaked = sum(r["remote_unacked_after"] for r in flat)
    ok = (
        parity_ok
        and lost_total == 0
        and leaked == 0
        and oracle["placements_total"] == expected
        and all(r["failed_queue"] == 0 for r in flat)
        and fanout_engaged
    )
    by_servers = {r["servers"]: r for r in runs}

    def speedup(n: int, key: str) -> Optional[float]:
        run = by_servers.get(n)
        if run is None or oracle[key] <= 0:
            return None
        return round(run[key] / oracle[key], 2)

    return {
        "ok": ok,
        "host_cpus": len(os.sched_getaffinity(0)),
        "nodes": nodes,
        "families": families,
        "jobs_per_family": jobs_per,
        "reps_per_topology": max(1, reps),
        "evals_total": expected,
        "parity_ok": parity_ok,
        "lost_total": lost_total,
        "leaked_remote_leases": leaked,
        "fanout_engaged": fanout_engaged,
        # headline: planning-plane load-spread (the scheduling-
        # throughput bound once each server owns its cores); wall
        # ratios ride along for the honest single-process view
        "speedup_3v1": speedup(3, "capacity_placements_per_s"),
        "speedup_5v1": speedup(5, "capacity_placements_per_s"),
        "wall_speedup_3v1": speedup(3, "wall_placements_per_s"),
        "wall_speedup_5v1": speedup(5, "wall_placements_per_s"),
        "runs": [
            {k: v for k, v in r.items() if k != "placements"}
            for r in runs
        ],
    }


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="follower fan-out scheduling-throughput bench"
    )
    parser.add_argument("--servers", default="1,3,5")
    parser.add_argument("--families", type=int, default=600)
    parser.add_argument("--jobs-per", type=int, default=1)
    parser.add_argument("--nodes", type=int, default=2048)
    parser.add_argument("--reps", type=int, default=5)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--json", default="", help="also write the block to this path"
    )
    args = parser.parse_args(argv)
    counts = tuple(
        int(tok) for tok in args.servers.split(",") if tok.strip()
    )
    block = run_fanout_bench(
        server_counts=counts,
        families=args.families,
        jobs_per=args.jobs_per,
        nodes=args.nodes,
        seed=args.seed,
        reps=args.reps,
    )
    out = {"cluster_fanout": block}
    print(json.dumps(out, indent=2, default=str))
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(out, fh, indent=2, default=str)
    sys.stdout.flush()
    sys.stderr.flush()
    if not block["ok"]:
        print("FANOUT_BENCH: FAIL", file=sys.stderr)
        # hard-exit (bench.py does the same): daemon threads may sit
        # inside XLA calls and CPython teardown then aborts
        os._exit(2)
    ratios = ", ".join(
        f"{r['servers']}s={r['capacity_placements_per_s']}/s"
        for r in block["runs"]
    )
    print(
        "FANOUT_BENCH: ok — capacity %s (3v1 %sx, wall 3v1 %sx)"
        % (
            ratios,
            block["speedup_3v1"],
            block["wall_speedup_3v1"],
        )
    )
    os._exit(0)


if __name__ == "__main__":
    main()
