"""Blocked evaluations tracker (reference nomad/blocked_evals.go).

Evals that failed placement wait here keyed by computed-class
eligibility; capacity changes (node updates, alloc stops) unblock the
evals that could now succeed.  Escaped evals (constraints outside the
computed-class system) are always re-run.  Deduped per job: a newer
blocked eval replaces an older one.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Set, Tuple

from ..structs import Evaluation, EVAL_TRIGGER_MAX_PLANS


class BlockedEvals:
    def __init__(self, broker) -> None:
        self.broker = broker
        self._lock = threading.Lock()
        self._enabled = False
        # eval id -> eval
        self._captured: Dict[str, Evaluation] = {}
        # evals whose constraints escaped computed classes
        self._escaped: Set[str] = set()
        # (namespace, job_id) -> eval id (dedup)
        self._job_blocked: Dict[Tuple[str, str], str] = {}
        # classes that saw capacity changes while nothing was blocked
        self._unblock_indexes: Dict[str, int] = {}
        self.stats = {"total_blocked": 0, "total_escaped": 0}

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
            if not enabled:
                self._captured.clear()
                self._escaped.clear()
                self._job_blocked.clear()
                self._unblock_indexes.clear()
                self.stats = {"total_blocked": 0, "total_escaped": 0}

    # ------------------------------------------------------------------

    def block(self, ev: Evaluation) -> None:
        with self._lock:
            if not self._enabled:
                return
            job_key = (ev.namespace, ev.job_id)
            # dedup: keep the newer eval per job
            existing_id = self._job_blocked.get(job_key)
            if existing_id is not None:
                existing = self._captured.get(existing_id)
                if (
                    existing is not None
                    and existing.create_index >= ev.create_index
                    and existing_id != ev.id
                ):
                    return
                self._remove_locked(existing_id)

            # missed unblock: capacity changed for an eligible class since
            # the eval was created -> requeue immediately
            # (reference blocked_evals.go:missedUnblock)
            for klass, index in self._unblock_indexes.items():
                if index <= ev.snapshot_index:
                    continue
                eligible = ev.class_eligibility.get(klass)
                if eligible or (
                    eligible is None and not ev.escaped_computed_class
                ) or ev.escaped_computed_class:
                    self.broker.enqueue(ev)
                    return

            self._captured[ev.id] = ev
            self._job_blocked[job_key] = ev.id
            self.stats["total_blocked"] += 1
            if ev.escaped_computed_class:
                self._escaped.add(ev.id)
                self.stats["total_escaped"] += 1

    def _remove_locked(self, eval_id: str) -> None:
        ev = self._captured.pop(eval_id, None)
        if ev is None:
            return
        self._job_blocked.pop((ev.namespace, ev.job_id), None)
        self.stats["total_blocked"] -= 1
        if eval_id in self._escaped:
            self._escaped.discard(eval_id)
            self.stats["total_escaped"] -= 1

    def untrack(self, namespace: str, job_id: str) -> None:
        """Stop tracking a job's blocked eval (job was stopped/GC'd)."""
        with self._lock:
            eval_id = self._job_blocked.get((namespace, job_id))
            if eval_id:
                self._remove_locked(eval_id)

    # ------------------------------------------------------------------

    def unblock(self, computed_class: str, index: int) -> None:
        """Capacity became available for a node class
        (reference blocked_evals.go:418 Unblock)."""
        with self._lock:
            if not self._enabled:
                return
            self._unblock_indexes[computed_class] = index
            to_run = []
            for eval_id, ev in list(self._captured.items()):
                if eval_id in self._escaped:
                    to_run.append(eval_id)
                    continue
                eligible = ev.class_eligibility.get(computed_class)
                if eligible is True or eligible is None:
                    # unknown class: the eval never saw it, so it may now
                    # be feasible there
                    to_run.append(eval_id)
            for eval_id in to_run:
                ev = self._captured[eval_id]
                self._remove_locked(eval_id)
                self.broker.enqueue(ev)

    def unblock_all(self, index: int) -> None:
        with self._lock:
            if not self._enabled:
                return
            for eval_id in list(self._captured):
                ev = self._captured[eval_id]
                self._remove_locked(eval_id)
                self.broker.enqueue(ev)

    def unblock_quota(self, quota: str, index: int) -> None:
        with self._lock:
            for eval_id, ev in list(self._captured.items()):
                if ev.quota_limit_reached == quota:
                    self._remove_locked(eval_id)
                    self.broker.enqueue(ev)

    # ------------------------------------------------------------------

    def blocked_count(self) -> int:
        return self.stats["total_blocked"]
