"""Plan queue (reference nomad/plan_queue.go): priority heap of pending
plans awaiting the serialized applier; each entry carries a future the
submitting worker blocks on.
"""
from __future__ import annotations

import heapq
import itertools
import threading
from typing import List, Optional, Tuple

from ..raft import NotLeaderError
from ..structs import Plan, PlanResult


class PendingPlan:
    def __init__(self, plan: Plan) -> None:
        self.plan = plan
        self._event = threading.Event()
        self._result: Optional[PlanResult] = None
        self._error: Optional[Exception] = None

    def respond(
        self, result: Optional[PlanResult], error: Optional[Exception]
    ) -> None:
        self._result = result
        self._error = error
        self._event.set()

    def wait(self, timeout: Optional[float] = None) -> PlanResult:
        if not self._event.wait(timeout):
            raise TimeoutError("plan apply timed out")
        if self._error is not None:
            raise self._error
        return self._result


class PlanQueue:
    def __init__(self) -> None:
        self._lock = threading.Condition()
        self._enabled = False
        self._heap: List[Tuple[int, int, PendingPlan]] = []
        self._counter = itertools.count()
        self.stats = {"depth": 0}

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._enabled = enabled
            if not enabled:
                self.flush()
            self._lock.notify_all()

    def flush(self) -> None:
        # the queue only runs on a leader: a flush IS a leadership
        # (or lifecycle) boundary, and pending submitters must nack
        # their evals for redelivery rather than fail them
        for _, _, pending in self._heap:
            pending.respond(None, NotLeaderError(None))
        self._heap = []
        self.stats["depth"] = 0

    def enqueue(self, plan: Plan) -> PendingPlan:
        with self._lock:
            if not self._enabled:
                raise NotLeaderError(None)
            pending = PendingPlan(plan)
            heapq.heappush(
                self._heap,
                (-plan.priority, next(self._counter), pending),
            )
            self.stats["depth"] += 1
            self._lock.notify_all()
            return pending

    def dequeue(self, timeout: Optional[float] = None) -> Optional[PendingPlan]:
        with self._lock:
            if not self._heap:
                self._lock.wait(timeout)
            if not self._heap:
                return None
            _, _, pending = heapq.heappop(self._heap)
            self.stats["depth"] -= 1
            return pending
