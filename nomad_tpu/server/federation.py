"""Multi-region federation: the geo plane (reference nomad/rpc.go:645
forwardRegion + nomad/serf.go WAN gossip + the enterprise multiregion
job deployer, stripped to its OSS contract).

The scaling argument (Tesserae, PAPERS.md) is that placement state must
stay partitioned to scale — here the partition is the region.  Each
region is a complete, self-sufficient control plane: its own raft
quorum, eval broker, TPU batch pipeline, storm solver and fan-out
followers, none of which know federation exists.  Only three things
cross the WAN, all through this module's :class:`FederationRouter`:

* **Job routing** — a submission landing in the wrong region hops to
  its home region's leader (``Job.region`` resolves the home; the
  ``region_call`` RPC carries it) with bounded retries/backoff
  mirroring the ``_raft_apply`` leader-forward loop: every retry
  re-resolves the region's membership from gossip, honors structured
  ``not_leader`` / ``wrong_region`` responses (each with a leader
  hint), and backs off through an interregnum instead of hammering it.
* **Cross-region job fan-out** — one jobspec carrying a ``Multiregion``
  block is fanned by the receiving (home) region's leader to every
  listed region.  Each target region's leader specializes the job
  locally (per-region ``count``/``datacenters``/``meta`` overrides)
  and proposes job+eval as ONE FSM command under a fan-out-scoped
  command id, so a retried fan-out dedups in the FSM and never
  double-registers; placement stays entirely region-local.
* **Health rumors** — the WAN gossip pool (membership.py) carries every
  server's region, liveness and HTTP advertise address.  The router
  thread snapshots it into a routing/health table that serves the
  ``X-Nomad-Retry-Region`` shed hint: a SHEDDING/EMERGENCY region
  answers sheds with the nearest healthy region's HTTP address, so
  global traffic degrades to the next region instead of hammering a
  dying one.

Reads NEVER cross the WAN implicitly: blocking queries and the
``/v1/cluster/*`` observability fan-in are answered from the local
region's servers only; the explicit ``?region=`` escape hatch forwards
and is the only path that increments ``federation.wan_reads`` (the
geo harness asserts the counter stays zero for region-local traffic).
"""
from __future__ import annotations

import copy
import itertools
import os
import pickle
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..decisions import DECISIONS
from ..raft import NotLeaderError
from ..raft.transport import TransportError
from ..structs import DEFAULT_REGION, new_id
from ..trace import TRACE

# federation telemetry, zero-registered at Server construction (the
# `federation-metrics` nomadlint rule enforces registry membership for
# every federation.* emission across federation.py / cluster.py /
# server.py / api/http.py): absence of a federation.* series must mean
# "single region, nothing ever crossed the WAN", never "not exported"
FEDERATION_COUNTERS = (
    "federation.forwarded",  # cross-region calls that succeeded
    "federation.rpc_errors",  # failed cross-region attempts (any kind)
    "federation.retries",  # forward attempts after the first
    "federation.wrong_region",  # structured wrong_region responses
    "federation.fanout_jobs",  # multiregion jobs fanned by this server
    "federation.fanout_regions",  # per-region registrations dispatched
    "federation.wan_reads",  # reads explicitly forwarded (?region=)
    "federation.shed_redirects",  # sheds answered with a region hint
)
FEDERATION_GAUGES = (
    "federation.regions",  # regions with >=1 ALIVE member in gossip
    "federation.healthy_regions",  # non-local regions usable as a hint
)


def fed_retries() -> int:
    """Bounded cross-region forward retry budget (attempts AFTER the
    first); each retry re-resolves the target region's membership, so
    a forward survives the remote leadership moving mid-call."""
    try:
        return max(0, int(os.environ.get("NOMAD_TPU_FED_RETRIES", "4")))
    except ValueError:
        return 4


def fed_backoff_s() -> float:
    """Initial cross-region retry backoff; doubles per attempt (capped
    at 1s) so a remote interregnum is waited out, not hammered."""
    try:
        return max(
            0.0,
            float(os.environ.get("NOMAD_TPU_FED_BACKOFF_S", "0.05")),
        )
    except ValueError:
        return 0.05


def region_probe_s() -> float:
    """Router-thread cadence: how often the per-region health/routing
    snapshot (and the federation.regions gauges) refresh from
    gossip."""
    try:
        return max(
            0.05,
            float(os.environ.get("NOMAD_TPU_REGION_PROBE_S", "0.5")),
        )
    except ValueError:
        return 0.5


class FederationError(RuntimeError):
    """Structured cross-region failure.  ``kind`` is one of
    ``not_leader`` / ``unknown_region`` / ``wrong_region`` /
    ``timeout`` / ``transport`` / ``unknown_op`` / ``app`` — the same
    vocabulary the hardened ``region_call`` envelope carries, so a
    caller can tell a routing miss (retryable) from a replicated
    application verdict (definitive) without unpickling a crash."""

    def __init__(
        self,
        message: str,
        kind: str = "app",
        leader: Optional[str] = None,
    ) -> None:
        super().__init__(message)
        self.kind = kind
        self.leader = leader


class FederationRouter:
    """Per-server geo router: resolves home regions, forwards
    ``region_call`` RPCs with bounded retry, fans multiregion jobs
    out, and maintains the gossip-derived region health table behind
    the shed-redirect hint.

    The router thread only REFRESHES the snapshot; every read path
    (``nearest_healthy_region``, ``http_addr_in``) falls back to a
    synchronous refresh when the snapshot is empty, so a hint is
    available before the first tick."""

    def __init__(self, server) -> None:
        self.server = server
        self.retries = fed_retries()
        self.backoff_s = fed_backoff_s()
        self._probe_s = region_probe_s()
        self._lock = threading.Lock()
        # region -> {"members": int, "http": [addr, ...]}
        self._snapshot: Dict[str, Dict] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seq = itertools.count(1)
        # decision-ledger dedup: the retry-region pick is read on
        # every shed redirect, so the federation_retry site ledgers
        # only when the CHOICE changes (membership churn, region
        # death/heal), not on every hint read
        self._last_retry_pick: Optional[str] = "unset"

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop,
            name=f"federation-router@{self.server.addr}",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.refresh()
            except Exception:  # noqa: BLE001 — keep the router alive
                pass
            self._stop.wait(self._probe_s)

    # -- region health table -------------------------------------------

    def refresh(self) -> Dict[str, Dict]:
        """Rebuild the per-region health snapshot from gossip and
        update the federation.* gauges."""
        snap: Dict[str, Dict] = {}
        for m in self.server.gossip.alive_members():
            entry = snap.setdefault(
                m.region, {"members": 0, "http": []}
            )
            entry["members"] += 1
            http = getattr(m, "http_addr", "")
            if http:
                entry["http"].append(http)
        with self._lock:
            self._snapshot = snap
        metrics = getattr(self.server, "metrics", None)
        if metrics is not None:
            metrics.set_gauge("federation.regions", float(len(snap)))
            metrics.set_gauge(
                "federation.healthy_regions",
                float(
                    sum(
                        1
                        for r, e in snap.items()
                        if r != self.server.region and e["members"]
                    )
                ),
            )
        return snap

    def _snap(self) -> Dict[str, Dict]:
        with self._lock:
            snap = self._snapshot
        if not snap:
            snap = self.refresh()
        return snap

    def regions(self) -> Dict[str, Dict]:
        """Routing-table view: region -> member count + HTTP addrs."""
        return {
            region: {
                "members": e["members"],
                "http": sorted(e["http"]),
                "local": region == self.server.region,
            }
            for region, e in self._snap().items()
        }

    def nearest_healthy_region(self) -> Optional[Tuple[str, str]]:
        """The shed-redirect hint: the non-local region with the most
        ALIVE members (name tiebreak — deterministic; gossip carries
        no geo distance), plus one of its HTTP advertise addresses.
        None when this server is the only region standing."""
        snap = self._snap()
        candidates = [
            (region, e)
            for region, e in snap.items()
            if region != self.server.region and e["members"] > 0
        ]
        if not candidates:
            if DECISIONS.enabled and self._last_retry_pick is not None:
                self._last_retry_pick = None
                DECISIONS.record(
                    "federation_retry",
                    "none",
                    inputs={"local_region": self.server.region},
                    outcome="no_healthy_region",
                    metrics=getattr(self.server, "metrics", None),
                )
            return None
        region, entry = min(
            candidates, key=lambda kv: (-kv[1]["members"], kv[0])
        )
        if DECISIONS.enabled and region != self._last_retry_pick:
            self._last_retry_pick = region
            DECISIONS.record(
                "federation_retry",
                f"region={region}",
                inputs={
                    "local_region": self.server.region,
                    "members": entry["members"],
                },
                alternatives=[
                    f"region={r}(members={e['members']})"
                    for r, e in sorted(candidates)
                ],
                outcome="redirect_hint",
                metrics=getattr(self.server, "metrics", None),
            )
        http = sorted(entry["http"])
        return region, (http[0] if http else "")

    def http_addr_in(self, region: str) -> Optional[str]:
        """One HTTP advertise address in ``region`` (deterministic
        pick), or None when the region has no reachable member with
        an advertised HTTP endpoint."""
        entry = self._snap().get(region)
        if not entry or not entry["http"]:
            return None
        return sorted(entry["http"])[0]

    # -- home-region resolution ----------------------------------------

    def home_region(self, job) -> str:
        """Home region of a job: ``Job.region``, except that the
        struct default resolves to the receiving server's region (as
        the reference agent does) unless a region by that name
        actually exists in the federation."""
        region = job.region or DEFAULT_REGION
        if (
            region == DEFAULT_REGION
            and region != self.server.region
            and not self.server.gossip.members_in_region(region)
        ):
            region = self.server.region
        return region

    # -- cross-region forwarding ---------------------------------------

    def forward(self, region: str, op: str, *args, **kw):
        """Route one call to ``region``'s leader (reference rpc.go:645
        forwardRegion) with bounded retries/backoff mirroring the
        ``_raft_apply`` leader-forward loop.  Local region short-
        circuits to ``_leader_route``.  Raises
        :class:`FederationError` with a structured ``kind`` when the
        budget is exhausted or the remote answers a definitive
        application error."""
        srv = self.server
        if region == srv.region:
            return srv._leader_route(op, *args, **kw)
        trace_id = f"federation:{next(self._seq)}"
        TRACE.begin(
            trace_id,
            root_span="federation.forward",
            region=region,
            op=op,
        )
        try:
            result = self._forward_with_retry(
                region, op, args, kw, trace_id
            )
        except Exception as exc:
            TRACE.annotate(trace_id, error=str(exc))
            TRACE.finish(trace_id, "error")
            raise
        TRACE.finish(trace_id, "forwarded")
        return result

    def _forward_with_retry(
        self, region: str, op: str, args, kw, trace_id: str
    ):
        srv = self.server
        payload_args = pickle.dumps((args, kw))
        metrics = getattr(srv, "metrics", None)
        backoff = self.backoff_s
        last: Exception = FederationError(
            f"no path to region {region!r}", kind="unknown_region"
        )
        target: Optional[str] = None  # leader hint from a reply
        for attempt in range(self.retries + 1):
            if attempt:
                if metrics is not None:
                    metrics.incr("federation.retries")
                if backoff:
                    time.sleep(min(backoff * (2 ** (attempt - 1)), 1.0))
            if target is None:
                members = srv.gossip.members_in_region(region)
                if not members:
                    last = FederationError(
                        f"no path to region {region!r}",
                        kind="unknown_region",
                    )
                    if metrics is not None:
                        metrics.incr("federation.rpc_errors")
                    continue  # churn may restore it within the budget
                target = random.choice(members).addr
            addr, target = target, None
            t0 = time.monotonic()
            try:
                resp = srv.transport.rpc(
                    srv.addr,
                    addr,
                    "region_call",
                    {
                        "op": op,
                        "region": region,
                        "args": payload_args,
                    },
                )
            except (TransportError, TimeoutError) as exc:
                if metrics is not None:
                    metrics.incr("federation.rpc_errors")
                last = FederationError(
                    str(exc) or type(exc).__name__,
                    kind=(
                        "timeout"
                        if isinstance(exc, TimeoutError)
                        else "transport"
                    ),
                )
                continue
            if resp.get("wrong_region"):
                # stale gossip routed us to a server that is not in
                # the region we meant: structured, with the server's
                # actual region and its leader hint; re-resolve
                if metrics is not None:
                    metrics.incr("federation.wrong_region")
                    metrics.incr("federation.rpc_errors")
                last = FederationError(
                    f"server {addr} is in region "
                    f"{resp.get('region')!r}, not {region!r}",
                    kind="wrong_region",
                    leader=resp.get("leader"),
                )
                continue
            if resp.get("not_leader"):
                # remote had no established leader (or was deposed
                # mid-call); its hint — a server in the SAME region —
                # seeds the next attempt
                if metrics is not None:
                    metrics.incr("federation.rpc_errors")
                target = resp.get("leader")
                last = FederationError(
                    f"no leader in region {region!r}",
                    kind="not_leader",
                    leader=target,
                )
                continue
            if resp.get("error"):
                # structured application error from the remote leader:
                # definitive (the remote's own forwarding already
                # retried routing misses) — never re-forwarded
                if metrics is not None:
                    metrics.incr("federation.rpc_errors")
                raise FederationError(
                    resp["error"], kind=resp.get("kind", "app")
                )
            if metrics is not None:
                metrics.incr("federation.forwarded")
            TRACE.add_span(
                trace_id,
                "federation.forward",
                t0,
                time.monotonic() - t0,
                region=region,
                op=op,
                attempt=attempt,
                server=addr,
            )
            return pickle.loads(resp["result"])
        raise last

    # -- cross-region job fan-out --------------------------------------

    def fanout_job(self, job):
        """Coordinator half of cross-region job federation: fan one
        ``Multiregion`` jobspec from the home region's leader to every
        listed region.  Each region gets a deep copy (target-side
        interpolation mutates) under the per-region command id
        ``<fanout_id>:<region>`` — a retried forward (lost ack, moved
        leadership) re-proposes the SAME id and the target FSM's
        dedup returns the first apply instead of double-registering.
        Returns ``(home_eval, {region: status})``; per-region failures
        are recorded, not raised (the OSS on_failure strategy), so one
        dead region cannot veto the rest of the fan-out."""
        srv = self.server
        metrics = getattr(srv, "metrics", None)
        fanout_id = new_id()
        regions = [
            r.name for r in job.multiregion.regions if r.name
        ] or [srv.region]
        trace_id = f"federation:fanout:{fanout_id[:8]}"
        TRACE.begin(
            trace_id,
            root_span="federation.fanout",
            job=job.id,
            regions=len(regions),
        )
        if metrics is not None:
            metrics.incr("federation.fanout_jobs")
        statuses: Dict[str, Dict] = {}
        home_ev = None
        for region in regions:
            cmd_id = f"{fanout_id}:{region}"
            regional_job = copy.deepcopy(job)
            t0 = time.monotonic()
            try:
                if region == srv.region:
                    ev = srv._leader_route(
                        "federated_register", regional_job, cmd_id
                    )
                else:
                    ev = self.forward(
                        region, "federated_register", regional_job,
                        cmd_id,
                    )
                if metrics is not None:
                    metrics.incr("federation.fanout_regions")
            except FederationError as exc:
                statuses[region] = {
                    "ok": False,
                    "error": str(exc),
                    "kind": exc.kind,
                }
                continue
            except (
                NotLeaderError, TransportError, TimeoutError,
            ) as exc:
                statuses[region] = {
                    "ok": False,
                    "error": str(exc) or type(exc).__name__,
                    "kind": "not_leader"
                    if isinstance(exc, NotLeaderError)
                    else "transport",
                }
                continue
            statuses[region] = {
                "ok": True,
                "eval": ev.id if ev is not None else "",
            }
            TRACE.add_span(
                trace_id,
                "federation.forward",
                t0,
                time.monotonic() - t0,
                region=region,
                op="federated_register",
            )
            if region == srv.region or home_ev is None:
                home_ev = ev
        ok = sum(1 for s in statuses.values() if s.get("ok"))
        TRACE.annotate(trace_id, ok=ok, failed=len(statuses) - ok)
        TRACE.finish(
            trace_id, "federated" if ok == len(statuses) else "partial"
        )
        return home_ev, statuses

    def federation_status(self, namespace: str, job_id: str) -> Dict:
        """Per-region registration/placement status for one federated
        job (the ``/v1/job/<id>/federation`` aggregation): the local
        region answers from local state; every other region listed in
        the job's ``Multiregion`` block is asked live over
        ``region_call``.  Served by any server holding a local copy
        of the job."""
        srv = self.server
        job = srv.store.job_by_id(namespace, job_id)
        if job is None:
            raise KeyError(job_id)
        regions: List[str] = (
            [r.name for r in job.multiregion.regions if r.name]
            if job.multiregion is not None
            else []
        )
        if job.region and job.region not in regions:
            regions.insert(0, job.region)
        out: Dict[str, Dict] = {}
        for region in regions:
            if region == srv.region:
                out[region] = srv.federation_job_status(
                    namespace, job_id
                )
                continue
            try:
                out[region] = self.forward(
                    region, "federation_job_status", namespace, job_id
                )
            except (FederationError, NotLeaderError) as exc:
                out[region] = {
                    "registered": False,
                    "region": region,
                    "error": str(exc),
                    "kind": getattr(exc, "kind", "not_leader"),
                }
        return {
            "job": job_id,
            "namespace": namespace,
            "home": srv.region,
            "multiregion": job.multiregion is not None,
            "regions": out,
        }
