"""Operator snapshot save/restore (reference helper/snapshot/,
/v1/operator/snapshot, and the FSM Snapshot/Restore paths in
nomad/fsm.go).

Serializes the full state-machine contents — nodes, jobs (+versions),
allocations, evaluations, deployments, scheduler config, ACL policies and
tokens — to a single file, and restores a server from it.  The columnar
node table and all secondary indexes are rebuilt on restore (they are
derived state, like the reference's memdb indexes).

Format: a gzip'd pickle of plain dataclass trees with a version header.
The wire-format stability story mirrors the reference: snapshots are for
backup/restore within a version family, not a cross-version exchange
format.
"""
from __future__ import annotations

import gzip
import pickle
from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from .server import Server

SNAPSHOT_VERSION = 1


def save_snapshot(server: "Server", path: str) -> None:
    store = server.store
    with store._lock:
        payload = {
            "version": SNAPSHOT_VERSION,
            "index": store.latest_index(),
            "nodes": list(store.nodes.values()),
            "jobs": list(store.jobs.values()),
            "job_versions": {
                k: list(v) for k, v in store.job_versions.items()
            },
            "allocs": list(store.allocs.values()),
            "evals": list(store.evals.values()),
            "deployments": list(store.deployments.values()),
            "scheduler_config": store.scheduler_config,
            "acl_policies": list(server.acls.policies.values()),
            "acl_tokens": list(server.acls.tokens_by_accessor.values()),
            "acl_enabled": server.acls.enabled,
        }
    with gzip.open(path, "wb") as f:
        pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)


def restore_snapshot(server: "Server", path: str) -> int:
    """Restore a server's state from a snapshot file.  Returns the
    restored index.  Must be called on a stopped or freshly-created
    server; leader services re-derive their state on start()
    (reference leader.go restoreEvals)."""
    with gzip.open(path, "rb") as f:
        payload = pickle.load(f)
    if payload.get("version") != SNAPSHOT_VERSION:
        raise ValueError(
            f"unsupported snapshot version {payload.get('version')}"
        )
    store = server.store
    with store._lock:
        store.nodes.clear()
        store.jobs.clear()
        store.job_versions.clear()
        store.allocs.clear()
        store.evals.clear()
        store.deployments.clear()
        store._allocs_by_node.clear()
        store._allocs_by_job.clear()
        store._allocs_by_eval.clear()
        store._evals_by_job.clear()
        store._deployments_by_job.clear()

        for node in payload["nodes"]:
            store.nodes[node.id] = node
            store.node_table.upsert_node(node)
        for job in payload["jobs"]:
            store.jobs[(job.namespace, job.id)] = job
        for key, versions in payload["job_versions"].items():
            store.job_versions[key] = versions
        for alloc in payload["allocs"]:
            store.allocs[alloc.id] = alloc
            store._allocs_by_node[alloc.node_id].add(alloc.id)
            store._allocs_by_job[(alloc.namespace, alloc.job_id)].add(
                alloc.id
            )
            if alloc.eval_id:
                store._allocs_by_eval[alloc.eval_id].add(alloc.id)
        for node_id in {a.node_id for a in payload["allocs"]}:
            store.node_table.update_node_usage(
                node_id, store._live_usage_for_node(node_id)
            )
        for ev in payload["evals"]:
            store.evals[ev.id] = ev
            store._evals_by_job[(ev.namespace, ev.job_id)].add(ev.id)
        for d in payload["deployments"]:
            store.deployments[d.id] = d
            store._deployments_by_job[(d.namespace, d.job_id)].add(d.id)
        store.scheduler_config = payload["scheduler_config"]
        store._index = payload["index"]

    server.acls.enabled = payload.get("acl_enabled", False)
    for policy in payload.get("acl_policies", ()):
        server.acls.upsert_policy(policy)
    for token in payload.get("acl_tokens", ()):
        server.acls.tokens_by_accessor[token.accessor_id] = token
        server.acls.tokens_by_secret[token.secret_id] = token
    return payload["index"]
