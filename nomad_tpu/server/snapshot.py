"""Operator snapshot save/restore (reference helper/snapshot/,
/v1/operator/snapshot, and the FSM Snapshot/Restore paths in
nomad/fsm.go).

Thin file wrapper over the FSM's state payload helpers (server/fsm.py
state_payload/install_payload) — the operator snapshot and the raft
snapshot are the same serialization, exactly as the reference's
operator snapshot is a raft snapshot in a file.

Format: a gzip'd pickle of plain dataclass trees with a version header.
The wire-format stability story mirrors the reference: snapshots are for
backup/restore within a version family, not a cross-version exchange
format.
"""
from __future__ import annotations

import gzip
import pickle
from typing import TYPE_CHECKING

from .fsm import install_payload, state_payload

if TYPE_CHECKING:  # pragma: no cover
    from .server import Server

SNAPSHOT_VERSION = 1


def save_snapshot(server: "Server", path: str) -> None:
    local_store = getattr(server.store, "local", server.store)
    local_acls = getattr(server.acls, "local", server.acls)
    payload = state_payload(local_store, local_acls)
    with gzip.open(path, "wb") as f:
        pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)


def restore_snapshot(server: "Server", path: str) -> int:
    """Restore a server's state from a snapshot file.  Returns the
    restored index.  Must be called on a stopped or freshly-created
    server; leader services re-derive their state on start()
    (reference leader.go restoreEvals)."""
    with gzip.open(path, "rb") as f:
        payload = pickle.load(f)
    local_store = getattr(server.store, "local", server.store)
    local_acls = getattr(server.acls, "local", server.acls)
    return install_payload(local_store, local_acls, payload)
