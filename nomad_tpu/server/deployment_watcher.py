"""Deployment watcher: drives rolling updates
(reference nomad/deploymentwatcher/deployments_watcher.go:60).

Watches active deployments, derives allocation health, updates per-group
deployment state, creates follow-up evals so the scheduler places the
next max_parallel batch, promotes canaries (manually or auto_promote),
fails deployments on unhealthy allocs or missed progress deadlines, and
auto-reverts the job to the latest stable version when configured.

Health derivation: the reference's client-side allochealth hooks report
health over RPC (deployments_watcher.go:336 SetAllocHealth).  Clients
here push task states; the watcher applies the "task_states" health
check: an alloc is healthy once all its tasks have been running for
min_healthy_time, unhealthy if it fails.  `set_alloc_health` remains the
external override hook ("checks"-based health can feed it)."""
from __future__ import annotations

import threading
import time
from dataclasses import replace as _replace
from typing import Dict, List, Optional

from ..structs import (
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_RUNNING,
    AllocDeploymentStatus,
    Deployment,
    DEPLOYMENT_STATUS_FAILED,
    DEPLOYMENT_STATUS_RUNNING,
    DEPLOYMENT_STATUS_SUCCESSFUL,
    Evaluation,
    EVAL_STATUS_PENDING,
    EVAL_TRIGGER_DEPLOYMENT_WATCHER,
)

DESC_PROGRESS_DEADLINE = "Failed due to progress deadline"
DESC_UNHEALTHY_ALLOCS = "Failed due to unhealthy allocations"
DESC_PROMOTED = "Deployment promoted"
DESC_SUCCESSFUL = "Deployment completed successfully"


class DeploymentWatcher:
    def __init__(self, server, interval: float = 0.1) -> None:
        self.server = server
        self.store = server.store
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # deployment id -> last time healthy count improved
        self._last_progress: Dict[str, float] = {}

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="deployment-watcher", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    # ------------------------------------------------------------------

    def _run(self) -> None:
        # blocking-query style: sweep when state changed; when idle,
        # wake only for progress-deadline checks (reference watchers
        # block on state via blocking queries, deployments_watcher.go)
        last = -1
        last_deadline_check = 0.0
        while not self._stop.wait(self.interval):
            try:
                idx = self.store.latest_index()
                now = time.monotonic()
                if idx == last and now - last_deadline_check < 1.0:
                    continue
                last = idx
                last_deadline_check = now
                for deployment in list(self.store.deployments.values()):
                    if deployment.active():
                        self._watch_one(deployment)
            except Exception:  # noqa: BLE001
                pass

    # ------------------------------------------------------------------

    def _watch_one(self, d: Deployment) -> None:
        job = self.store.job_by_id(d.namespace, d.job_id)
        if job is None or job.version != d.job_version:
            return

        allocs = [
            a
            for a in self.store.allocs_by_job(d.namespace, d.job_id)
            if a.deployment_id == d.id
        ]
        now = time.time()
        changed = False
        unhealthy_seen = False

        for alloc in allocs:
            ds = alloc.deployment_status
            if ds is not None and ds.healthy is not None:
                if ds.is_unhealthy():
                    unhealthy_seen = True
                continue
            health = self._derive_health(job, alloc, now)
            if health is None:
                continue
            if alloc.deployment_status is None:
                alloc.deployment_status = AllocDeploymentStatus()
            alloc.deployment_status.healthy = health
            alloc.deployment_status.timestamp = now
            changed = True
            if health is False:
                unhealthy_seen = True

        # recompute per-group counters
        healthy_total = 0
        for group, state in d.task_groups.items():
            group_allocs = [a for a in allocs if a.task_group == group]
            state.placed_allocs = len(group_allocs)
            state.healthy_allocs = sum(
                1
                for a in group_allocs
                if a.deployment_status is not None
                and a.deployment_status.is_healthy()
            )
            state.unhealthy_allocs = sum(
                1
                for a in group_allocs
                if a.deployment_status is not None
                and a.deployment_status.is_unhealthy()
            )
            healthy_total += state.healthy_allocs

        entry = self._last_progress.get(d.id)
        if entry is None or healthy_total > entry[0]:
            self._last_progress[d.id] = (healthy_total, now)

        if unhealthy_seen:
            self._fail_deployment(d, job, DESC_UNHEALTHY_ALLOCS)
            return

        # progress deadline
        for group, state in d.task_groups.items():
            deadline = state.progress_deadline_s
            if deadline <= 0:
                continue
            entry = self._last_progress.get(d.id)
            last = entry[1] if entry is not None else now
            if (
                state.healthy_allocs
                < max(state.desired_total, state.desired_canaries)
                and now - last > deadline
            ):
                self._fail_deployment(d, job, DESC_PROGRESS_DEADLINE)
                return

        # auto-promotion: all canaries healthy
        if d.requires_promotion() and d.has_auto_promote():
            ready = all(
                s.desired_canaries == 0
                or s.healthy_allocs >= s.desired_canaries
                for s in d.task_groups.values()
            )
            if ready:
                self.promote(d.id)
                return

        # completion: every group fully healthy and promoted
        complete = all(
            s.healthy_allocs >= s.desired_total
            and (s.desired_canaries == 0 or s.promoted)
            for s in d.task_groups.values()
        ) and bool(d.task_groups)
        if complete:
            d.status = DEPLOYMENT_STATUS_SUCCESSFUL
            d.status_description = DESC_SUCCESSFUL
            self.store.upsert_deployment(d)
            # the deployed version becomes the stable version
            job.stable = True
            self._last_progress.pop(d.id, None)
            self._create_eval(d, job)
            return

        if changed:
            self.store.upsert_deployment(d)
            # health progress unblocks the next max_parallel batch
            self._create_eval(d, job)

    # ------------------------------------------------------------------

    def _derive_health(self, job, alloc, now: float) -> Optional[bool]:
        tg = job.lookup_task_group(alloc.task_group)
        update = tg.update if tg is not None else None
        min_healthy = (
            update.min_healthy_time_s if update is not None else 10.0
        )
        deadline = (
            update.healthy_deadline_s if update is not None else 300.0
        )
        if alloc.client_status == ALLOC_CLIENT_STATUS_FAILED:
            return False
        if alloc.client_status == ALLOC_CLIENT_STATUS_RUNNING:
            started = max(
                (s.started_at for s in alloc.task_states.values()),
                default=alloc.create_time,
            ) or alloc.create_time
            if now - started >= min_healthy:
                return True
        if now - alloc.create_time > deadline:
            return False
        return None

    # ------------------------------------------------------------------

    def set_alloc_health(
        self, alloc_ids: List[str], healthy: bool
    ) -> None:
        """(reference Deployment.SetAllocHealth RPC)"""
        now = time.time()
        for alloc_id in alloc_ids:
            alloc = self.store.alloc_by_id(alloc_id)
            if alloc is None:
                continue
            if alloc.deployment_status is None:
                alloc.deployment_status = AllocDeploymentStatus()
            alloc.deployment_status.healthy = healthy
            alloc.deployment_status.timestamp = now

    def promote(self, deployment_id: str, groups: Optional[List[str]] = None):
        """(reference deployments_watcher.go PromoteDeployment)"""
        d = self.store.deployment_by_id(deployment_id)
        if d is None or not d.active():
            return
        job = self.store.job_by_id(d.namespace, d.job_id)
        for group, state in d.task_groups.items():
            if groups is not None and group not in groups:
                continue
            unhealthy_canaries = state.desired_canaries - min(
                state.healthy_allocs, state.desired_canaries
            )
            if state.desired_canaries and unhealthy_canaries > 0:
                raise ValueError(
                    f"group {group!r} has unpromotable canaries"
                )
            state.promoted = True
        d.status_description = DESC_PROMOTED
        self.store.upsert_deployment(d)
        if job is not None:
            self._create_eval(d, job)

    def fail(self, deployment_id: str) -> None:
        d = self.store.deployment_by_id(deployment_id)
        if d is None or not d.active():
            return
        job = self.store.job_by_id(d.namespace, d.job_id)
        self._fail_deployment(d, job, "Deployment marked as failed")

    def pause(self, deployment_id: str, pause: bool) -> None:
        d = self.store.deployment_by_id(deployment_id)
        if d is None:
            return
        from ..structs import DEPLOYMENT_STATUS_PAUSED

        if pause and d.status == DEPLOYMENT_STATUS_RUNNING:
            d.status = DEPLOYMENT_STATUS_PAUSED
        elif not pause and d.status == DEPLOYMENT_STATUS_PAUSED:
            d.status = DEPLOYMENT_STATUS_RUNNING
        self.store.upsert_deployment(d)

    # ------------------------------------------------------------------

    def _fail_deployment(self, d: Deployment, job, desc: str) -> None:
        d.status = DEPLOYMENT_STATUS_FAILED
        d.status_description = desc
        self.store.upsert_deployment(d)
        self._last_progress.pop(d.id, None)

        # auto-revert to the latest stable version
        if job is not None and any(
            s.auto_revert for s in d.task_groups.values()
        ):
            stable = self._latest_stable_version(job)
            if stable is not None and stable.version != job.version:
                reverted = _replace(stable)
                reverted.stable = True
                self.store.upsert_job(reverted)
                job = reverted
        if job is not None:
            self._create_eval(d, job)

    # -- multiregion hooks (reference deploymentwatcher/
    # multiregion_oss.go: cross-region rollout coordination is an
    # enterprise feature; OSS carries the spec and runs the local
    # region's deployment, with these hooks as no-ops) ----------------

    def next_region(self, deployment_id: str, status: str) -> None:
        """Called when the local region's deployment finishes; would
        unblock the next region in the multiregion strategy."""

    def run_deployment(self, deployment_id: str) -> None:
        """Would transition a multiregion deployment out of 'pending'
        once its turn arrives."""

    def pause_deployments_for_job(self, namespace: str, job_id: str):
        """Would pause sibling-region deployments on fail_all."""

    def _latest_stable_version(self, job):
        versions = self.store.job_versions.get(
            (job.namespace, job.id), []
        )
        for v in versions:
            if v.stable and v.version != job.version:
                return v
        return None

    def _create_eval(self, d: Deployment, job) -> None:
        ev = Evaluation(
            namespace=d.namespace,
            priority=job.priority,
            type=job.type,
            triggered_by=EVAL_TRIGGER_DEPLOYMENT_WATCHER,
            job_id=d.job_id,
            deployment_id=d.id,
            status=EVAL_STATUS_PENDING,
        )
        self.store.upsert_evals([ev])
        self.server.on_eval_update(ev)
