"""Ingress backpressure: overload as a first-class, observable server
state (reference model: nomad's pending-eval limits + the classic
Breakwater/SEDA admission-control shape).

The control plane's failure mode under a traffic storm is not a crash
— it is an unbounded broker backlog whose queueing delay blows every
SLO while the server still answers 200s.  The
:class:`OverloadController` makes that state explicit: a three-rung
degradation ladder driven by the broker's backlog signals and the
flight recorder's latency tail, with **priority-classed shedding** at
the HTTP ingress.

Mode ladder (the ``overload.mode`` gauge)::

    NORMAL (0)     everything admitted
    SHEDDING (1)   job submissions (class >= shed floor) shed with
                   429 + Retry-After; blocking queries degrade to
                   non-blocking (counted as overload.deferred)
    EMERGENCY (2)  every class except node heartbeats shed

Priority classes (lower = more protected)::

    PRI_HEARTBEAT (0)  node heartbeats / registrations / alloc-status
                       pushes — the cluster's liveness plane.  NEVER
                       shed below EMERGENCY (an overloaded leader that
                       drops heartbeats manufactures a false mass
                       node-death wave, turning overload into a
                       replanning storm); this build never sheds them
                       at EMERGENCY either — the class exists so a
                       future rung above EMERGENCY has somewhere to go.
    PRI_QUERY (1)      reads, blocking queries, plan dry-runs
    PRI_SUBMIT (2)     job submissions / scaling / operator writes

Ladder inputs, each with a NORMAL->SHEDDING threshold and a 4x
EMERGENCY threshold:

* **broker depth** (``EvalBroker.pending_depth()``): ready backlog +
  per-job pending heaps — the work already accepted but not started;
* **oldest pending age** (``EvalBroker.oldest_pending_age()``): the
  commit-wave lag the next accepted eval will experience before its
  wave even starts — queueing delay measured, not modeled;
* **flight-recorder p99** (``batch_worker.eval_latency_ms`` p99, off
  by default — ``NOMAD_TPU_OVERLOAD_P99_MS``): the end-to-end latency
  tail with trace exemplars attached.

Escalation is immediate; de-escalation drops one rung at a time after
the signals have stayed below the lower rung's thresholds for a
cooldown, so the mode gauge can't flap at threshold noise.  Every
excursion from NORMAL is recorded as ONE flight-recorder incident
trace (``overload:<n>``, rooted at the ``ingress.shed`` span) whose
annotations carry the trigger signals and final shed counts.

The controller is passive (no thread): the mode re-evaluates lazily —
at most every ``_EVAL_INTERVAL_S`` — from the admission path, which
under overload is exactly the path that runs hottest.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Dict, Optional, Tuple

# modes (the overload.mode gauge values)
MODE_NORMAL = 0
MODE_SHEDDING = 1
MODE_EMERGENCY = 2
MODE_NAMES = ("NORMAL", "SHEDDING", "EMERGENCY")

# ingress priority classes (lower = more protected)
PRI_HEARTBEAT = 0
PRI_QUERY = 1
PRI_SUBMIT = 2

# overload.* telemetry, zero-registered at Server construction (the
# `overload-metrics` nomadlint rule enforces registry membership for
# every emission across overload.py / server.py / api/http.py):
# absence of an overload.* series must mean "never overloaded", not
# "not exported"
OVERLOAD_COUNTERS = (
    "overload.accepted",
    "overload.shed",
    "overload.deferred",
    "overload.node_down_waves",
)
OVERLOAD_GAUGES = (
    "overload.mode",
    "overload.broker_depth",
    "overload.oldest_age_s",
    "overload.last_wave_nodes",
)

# mode recompute cadence: signals are cheap (two O(1)-ish broker
# reads), but not per-request cheap at thousands of req/s
_EVAL_INTERVAL_S = 0.05
# de-escalation hold: signals must stay below the lower rung this
# long before the mode drops one rung (escalation is immediate)
_COOLDOWN_S = 1.0
# EMERGENCY engages at this multiple of the SHEDDING thresholds
_EMERGENCY_FACTOR = 4.0
# Retry-After advice per mode (seconds); SHEDDING backs clients off
# briefly, EMERGENCY tells them the backlog needs real draining
_RETRY_AFTER_S = {MODE_SHEDDING: 1.0, MODE_EMERGENCY: 5.0}
# flight-recorder p99 input needs this many samples before it counts
# (a 3-sample "p99" is just the max of a cold start)
_P99_MIN_COUNT = 16

# observability/liveness endpoints that must answer DURING overload —
# shedding the endpoints an operator needs to see the overload would
# make every incident a blind one
_EXEMPT_PREFIXES = (
    "/v1/metrics",
    "/v1/overload",
    "/v1/device",
    "/v1/agent",
    "/v1/status",
    "/v1/operator",
    "/v1/traces",
    # control-loop flight data: the SLO burn-rate view and the
    # decision ledger are exactly what an operator reads to judge an
    # overload excursion — shedding them defeats their purpose
    "/v1/slo",
    "/v1/decisions",
    # cluster fan-in queries: an overloaded leader shedding the
    # cluster-wide views would blind the operator to the overload
    "/v1/cluster",
)

# the liveness plane: heartbeats, node/client registration and
# client alloc-status pushes (dropping those turns overload into
# false alloc-loss churn)
_HEARTBEAT_SUFFIXES = ("/heartbeat", "/allocs")
_HEARTBEAT_PATHS = ("/v1/node/register", "/v1/client/register")

# read-shaped write endpoints that belong with the query class
_QUERY_PATHS = ("/v1/search", "/v1/validate/job")


def classify_request(method: str, path: str) -> Optional[int]:
    """Priority class of one HTTP request, or None for exempt
    (observability/liveness) endpoints that are never shed."""
    if path.startswith(_EXEMPT_PREFIXES):
        return None
    if path in _HEARTBEAT_PATHS or (
        path.startswith("/v1/node/")
        and path.endswith(_HEARTBEAT_SUFFIXES)
    ):
        return PRI_HEARTBEAT
    if method == "GET" or path in _QUERY_PATHS or path.endswith(
        "/plan"
    ):
        return PRI_QUERY
    return PRI_SUBMIT


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class OverloadController:
    """Admission backpressure + the NORMAL->SHEDDING->EMERGENCY mode
    ladder for one server.  Passive: no thread; the mode re-evaluates
    lazily from the admission path (throttled to
    ``_EVAL_INTERVAL_S``)."""

    def __init__(self, server) -> None:
        self.server = server
        self.enabled = (
            os.environ.get("NOMAD_TPU_OVERLOAD", "1") != "0"
        )
        # SHEDDING thresholds (EMERGENCY = 4x each)
        self.depth_threshold = max(
            1.0, _env_float("NOMAD_TPU_OVERLOAD_DEPTH", 512.0)
        )
        self.age_threshold_s = max(
            0.1, _env_float("NOMAD_TPU_OVERLOAD_AGE_S", 30.0)
        )
        # flight-recorder p99 input (ms); 0 disables the signal
        self.p99_threshold_ms = max(
            0.0, _env_float("NOMAD_TPU_OVERLOAD_P99_MS", 0.0)
        )
        # lowest (numerically) priority class SHEDDING may shed;
        # EMERGENCY always sheds every class above heartbeats
        try:
            self.shed_floor = int(
                os.environ.get("NOMAD_TPU_OVERLOAD_SHED_FLOOR", "2")
            )
        except ValueError:
            self.shed_floor = PRI_SUBMIT
        self.shed_floor = max(PRI_QUERY, self.shed_floor)
        self._lock = threading.Lock()
        self._mode = MODE_NORMAL
        self._last_eval = 0.0
        # monotonic instant the signals last SUPPORTED the current
        # mode (de-escalation cooldown anchor)
        self._last_supported = time.monotonic()
        self._incident_seq = itertools.count(1)
        self._incident_id: Optional[str] = None
        self._incident_shed_at_start = 0.0
        # last computed signals, for /v1/overload
        self._signals: Dict[str, float] = {
            "depth": 0.0, "age_s": 0.0, "p99_ms": 0.0,
        }

    # -- signals -------------------------------------------------------

    def _read_signals(self) -> Tuple[float, float, float]:
        broker = getattr(self.server, "broker", None)
        depth = float(broker.pending_depth()) if broker else 0.0
        age = float(broker.oldest_pending_age()) if broker else 0.0
        p99 = 0.0
        if self.p99_threshold_ms > 0:
            metrics = getattr(self.server, "metrics", None)
            snap = (
                metrics.get_sample("batch_worker.eval_latency_ms")
                if metrics is not None
                else None
            )
            if snap is not None and snap["count"] >= _P99_MIN_COUNT:
                p99 = float(snap["p99"])
        return depth, age, p99

    def _severity(self, depth: float, age: float, p99: float) -> int:
        """Worst rung any single signal supports."""

        def rung(value: float, threshold: float) -> int:
            if threshold <= 0 or value < threshold:
                return MODE_NORMAL
            if value < threshold * _EMERGENCY_FACTOR:
                return MODE_SHEDDING
            return MODE_EMERGENCY

        return max(
            rung(depth, self.depth_threshold),
            rung(age, self.age_threshold_s),
            rung(p99, self.p99_threshold_ms),
        )

    # -- mode ladder ---------------------------------------------------

    def evaluate(self, force: bool = False) -> int:
        """Recompute (throttled) and return the current mode."""
        if not self.enabled:
            return MODE_NORMAL
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_eval < _EVAL_INTERVAL_S:
                return self._mode
            self._last_eval = now
        # signals are read OUTSIDE self._lock: pending_depth /
        # oldest_pending_age take the broker lock, and holding two
        # locks across modules here would add an edge to the static
        # lock graph for no benefit (a stale signal read costs one
        # _EVAL_INTERVAL_S of mode lag)
        depth, age, p99 = self._read_signals()
        target = self._severity(depth, age, p99)
        with self._lock:
            self._signals = {"depth": depth, "age_s": age, "p99_ms": p99}
            mode = self._mode
            if target >= mode:
                # the signals support (or exceed) the current rung
                self._last_supported = now
            if target > mode:
                self._transition_locked(target, depth, age, p99)
            elif (
                target < mode
                and now - self._last_supported >= _COOLDOWN_S
            ):
                # one rung at a time, re-anchoring the cooldown, so a
                # deep EMERGENCY walks down through SHEDDING instead
                # of snapping open the floodgates
                self._transition_locked(mode - 1, depth, age, p99)
                self._last_supported = now
            return self._mode

    def _transition_locked(
        self, new_mode: int, depth: float, age: float, p99: float
    ) -> None:
        from ..decisions import DECISIONS
        from ..trace import TRACE

        old = self._mode
        self._mode = new_mode
        metrics = getattr(self.server, "metrics", None)
        if metrics is not None:
            metrics.set_gauge("overload.mode", float(new_mode))
            metrics.set_gauge("overload.broker_depth", depth)
            metrics.set_gauge("overload.oldest_age_s", age)
        # every eval in flight right now ran through this regime
        # shift — stamp its waterfall (bounded broadcast) so a
        # shed/degraded eval explains itself without a /v1/overload
        # join; the incident trace gets the same mark below via its
        # annotations
        for eid in TRACE.in_flight_ids(limit=64):
            TRACE.event(
                eid, "overload.mode_change",
                old=MODE_NAMES[old], new=MODE_NAMES[new_mode],
            )
        prev_incident = self._incident_id
        if old == MODE_NORMAL and new_mode > MODE_NORMAL:
            # one incident trace per excursion from NORMAL: the
            # operator's post-mortem handle for "what shed, and why"
            n = next(self._incident_seq)
            self._incident_id = f"overload:{n}"
            self._incident_shed_at_start = (
                metrics.get_counter("overload.shed")
                if metrics is not None
                else 0.0
            )
            TRACE.begin(
                self._incident_id,
                root_span="ingress.shed",
                mode=MODE_NAMES[new_mode],
                broker_depth=depth,
                oldest_age_s=round(age, 3),
                p99_ms=round(p99, 1),
            )
        elif self._incident_id is not None:
            TRACE.annotate(
                self._incident_id,
                mode=MODE_NAMES[new_mode],
                broker_depth=depth,
                oldest_age_s=round(age, 3),
            )
            if new_mode == MODE_NORMAL:
                shed = (
                    metrics.get_counter("overload.shed")
                    - self._incident_shed_at_start
                    if metrics is not None
                    else 0.0
                )
                TRACE.annotate(self._incident_id, shed_total=shed)
                TRACE.finish(self._incident_id, "recovered")
                self._incident_id = None
        DECISIONS.record(
            "overload_mode",
            f"{MODE_NAMES[old]}->{MODE_NAMES[new_mode]}",
            inputs={
                "broker_depth": depth,
                "oldest_age_s": round(age, 3),
                "p99_ms": round(p99, 1),
                "leader_gen": getattr(
                    self.server, "_leadership_gen", 0
                ),
            },
            alternatives=[
                name
                for i, name in enumerate(MODE_NAMES)
                if i != new_mode
            ],
            outcome="escalate" if new_mode > old else "recover",
            # joins the excursion's incident trace: the id minted on
            # the way up, retained here on the final walk-down too
            trace_id=self._incident_id or prev_incident or "",
            metrics=metrics,
        )

    @property
    def mode(self) -> int:
        return self._mode

    def close_incident(self) -> None:
        """Teardown hook (server stop / leadership revoke): an
        excursion that never walked back to NORMAL would otherwise
        leave its incident trace dangling in flight forever — settle
        it with an explicit `shed` outcome so /v1/traces?outcome=
        filters and trace_report's in-flight header stay honest."""
        from ..trace import TRACE

        with self._lock:
            incident = self._incident_id
            self._incident_id = None
            if incident is None:
                return
            metrics = getattr(self.server, "metrics", None)
            shed = (
                metrics.get_counter("overload.shed")
                - self._incident_shed_at_start
                if metrics is not None
                else 0.0
            )
        TRACE.annotate(incident, shed_total=shed)
        TRACE.finish(incident, "shed")

    # -- admission -----------------------------------------------------

    def admit(self, pclass: Optional[int]) -> Tuple[bool, float]:
        """(admitted, retry_after_s) for one ingress request.
        ``pclass=None`` (exempt endpoints) always admits without
        counting."""
        if pclass is None:
            return True, 0.0
        mode = self.evaluate()
        metrics = getattr(self.server, "metrics", None)
        shed = False
        if mode == MODE_SHEDDING:
            shed = pclass >= self.shed_floor
        elif mode == MODE_EMERGENCY:
            # heartbeats are the one class an overloaded leader must
            # keep answering: shedding them converts ingress overload
            # into a false mass node-death wave — strictly more work
            shed = pclass >= PRI_QUERY
        if shed:
            if metrics is not None:
                metrics.incr("overload.shed")
            return False, _RETRY_AFTER_S.get(mode, 1.0)
        if metrics is not None:
            metrics.incr("overload.accepted")
        return True, 0.0

    def blocking_wait_budget(self, wait_s: float) -> float:
        """Long-poll budget under the current mode: at SHEDDING and
        above, blocking queries degrade to non-blocking (answer the
        current state immediately) so overload can't also pin server
        threads for the full wait — the degradation between "served
        normally" and "shed"."""
        if wait_s <= 0 or self.evaluate() == MODE_NORMAL:
            return wait_s
        metrics = getattr(self.server, "metrics", None)
        if metrics is not None:
            metrics.incr("overload.deferred")
        return 0.0

    # -- surfaces ------------------------------------------------------

    def status(self) -> Dict:
        """/v1/overload payload."""
        mode = self.evaluate(force=True)
        with self._lock:
            signals = dict(self._signals)
            incident = self._incident_id
        return {
            "enabled": self.enabled,
            "mode": mode,
            "mode_name": MODE_NAMES[mode],
            "signals": signals,
            "thresholds": {
                "depth": self.depth_threshold,
                "age_s": self.age_threshold_s,
                "p99_ms": self.p99_threshold_ms,
                "emergency_factor": _EMERGENCY_FACTOR,
            },
            "shed_floor": self.shed_floor,
            "incident": incident,
        }
