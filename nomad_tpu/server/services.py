"""Service catalog: registration + discovery for job services.

The reference delegates service registration to Consul
(command/agent/consul/ syncs task services into the Consul catalog;
clients register/deregister as allocs start and stop).  nomad-tpu carries
the catalog in-framework: a store watcher keeps it in sync with
allocation state, and the HTTP API exposes discovery
(/v1/catalog/services, /v1/catalog/service/<name>).

An instance is healthy when its allocation is running; check definitions
(tcp/http) are evaluated by the client's check runner and fold into
health via `set_check_status`.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..structs import (
    ALLOC_CLIENT_STATUS_RUNNING,
    Allocation,
)


@dataclass
class ServiceInstance:
    service: str
    alloc_id: str
    node_id: str
    job_id: str
    task: str
    address: str = ""
    port: int = 0
    tags: List[str] = field(default_factory=list)
    healthy: bool = True
    checks_passing: bool = True


class ServiceCatalog:
    def __init__(self, server) -> None:
        self.server = server
        self.store = server.store
        self._lock = threading.Lock()
        # service name -> {alloc_id/task -> instance}
        self._services: Dict[str, Dict[str, ServiceInstance]] = {}
        # external check results: (alloc_id, task, service) -> bool
        self._check_status: Dict[Tuple[str, str, str], bool] = {}
        # reverse index for incremental removal: alloc -> (service, key)
        self._by_alloc: Dict[str, List[Tuple[str, str]]] = {}
        self.store.add_alloc_watcher(self.update_allocs)

    # ------------------------------------------------------------------

    def sync(self) -> None:
        """Full rebuild from allocation state (used on startup/restore;
        steady-state maintenance is incremental via `update_allocs` —
        the reference's consul sync is likewise push-based per alloc,
        command/agent/consul/client.go)."""
        with self._lock:
            self._services = {}
            self._by_alloc = {}
            self._update_locked(list(self.store.allocs.values()))

    def update_allocs(self, allocs) -> None:
        """Incremental catalog maintenance for exactly the allocations a
        state write touched — O(delta), not O(alloc table).  ``None``
        means the table was replaced wholesale (snapshot restore):
        rebuild."""
        if allocs is None:
            self.sync()
            return
        with self._lock:
            self._update_locked(allocs)

    def _update_locked(self, allocs) -> None:
        for alloc in allocs:
            # drop this alloc's existing registrations, then re-add
            for service_name, key in self._by_alloc.pop(alloc.id, ()):
                insts = self._services.get(service_name)
                if insts is not None:
                    insts.pop(key, None)
                    if not insts:
                        self._services.pop(service_name, None)
            if alloc.terminal_status():
                continue
            job = alloc.job or self.store.job_by_id(
                alloc.namespace, alloc.job_id
            )
            if job is None:
                continue
            tg = job.lookup_task_group(alloc.task_group)
            if tg is None:
                continue
            node = self.store.node_by_id(alloc.node_id)
            address = ""
            if node is not None and node.node_resources.networks:
                address = node.node_resources.networks[0].ip
            running = (
                alloc.client_status == ALLOC_CLIENT_STATUS_RUNNING
            )
            port_by_label = {}
            if alloc.allocated_resources is not None:
                for p in alloc.allocated_resources.shared.ports:
                    port_by_label[p.label] = p.value
                for tr in alloc.allocated_resources.tasks.values():
                    for net in tr.networks:
                        for p in list(net.reserved_ports) + list(
                            net.dynamic_ports
                        ):
                            port_by_label[p.label] = p.value
            entries = []
            for task in tg.tasks:
                for service in task.services:
                    if not service.name:
                        continue
                    key = f"{alloc.id}/{task.name}"
                    checks_ok = self._check_status.get(
                        (alloc.id, task.name, service.name), True
                    )
                    inst = ServiceInstance(
                        service=service.name,
                        alloc_id=alloc.id,
                        node_id=alloc.node_id,
                        job_id=alloc.job_id,
                        task=task.name,
                        address=address,
                        # label lookup, falling back to literal static
                        # ports (reference: numeric port labels)
                        port=port_by_label.get(service.port_label, 0)
                        or (
                            int(service.port_label)
                            if str(service.port_label).isdigit()
                            else 0
                        ),
                        tags=list(service.tags),
                        healthy=running and checks_ok,
                        checks_passing=checks_ok,
                    )
                    self._services.setdefault(service.name, {})[
                        key
                    ] = inst
                    entries.append((service.name, key))
            if entries:
                self._by_alloc[alloc.id] = entries

    # ------------------------------------------------------------------

    def set_check_status(
        self, alloc_id: str, task: str, service: str, passing: bool
    ) -> None:
        self._check_status[(alloc_id, task, service)] = passing
        self.sync()

    def services(self) -> List[str]:
        with self._lock:
            return sorted(self._services)

    def instances(
        self, name: str, healthy_only: bool = False
    ) -> List[ServiceInstance]:
        with self._lock:
            out = list(self._services.get(name, {}).values())
        if healthy_only:
            out = [i for i in out if i.healthy]
        return sorted(out, key=lambda i: (i.alloc_id, i.task))
