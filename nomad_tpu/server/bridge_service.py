"""TPU scheduler bridge service.

The process seam of BASELINE.json's north star: an external control plane
(the reference's Go scheduling worker, loading native/libnomadwire.so as
its cgo shim) dispatches evaluations to this service over the framed wire
protocol, and the service answers with placement decisions computed by
the batched score kernel — leaving the caller's eval broker, plan applier
and replication machinery untouched.

RPC surface (method -> body -> response):

  TPUScheduler.Ping      {}                      -> {"ok": true, ...}
  TPUScheduler.ScoreBatch
      {"evals": [{"eval_id": ..., "job_id": ..., "seed": int,
                  "count": int, "cpu": int, "memory_mb": int,
                  "disk_mb": int}, ...]}
      -> {"results": [{"eval_id": ..., "nodes": [node_id, ...]}, ...]}

Each eval's `seed` drives the shuffled visit order exactly as the
in-process schedulers do, so decisions remain bit-identical regardless of
which side of the bridge asks.
"""
from __future__ import annotations

import math
import random
import socket
import socketserver
import threading
from typing import Dict, List, Optional

import numpy as np

from ..wire import decode, encode, recv_frame, send_frame
from ..sched.feasible import shuffle_permutation


class BridgeService:
    def __init__(self, server, host: str = "127.0.0.1", port: int = 0):
        self.server = server
        self.store = server.store

        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                while True:
                    try:
                        frame = recv_frame(self.request)
                    except (ConnectionError, ValueError, OSError):
                        return
                    if frame is None:
                        return
                    try:
                        method, body = decode(frame)
                        response = outer.dispatch(method, body)
                    except Exception as exc:  # noqa: BLE001
                        response = {"error": f"{type(exc).__name__}: {exc}"}
                    try:
                        send_frame(self.request, encode(response))
                    except OSError:
                        return

        class TCP(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self.tcp = TCP((host, port), Handler)
        self.port = self.tcp.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.tcp.serve_forever, name="tpu-bridge", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self.tcp.shutdown()
        self.tcp.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    # ------------------------------------------------------------------

    def dispatch(self, method: str, body: Dict) -> Dict:
        if method == "TPUScheduler.Ping":
            return {
                "ok": True,
                "nodes": len(self.store.nodes),
                "arena": self.store.node_table.capacity,
            }
        if method == "TPUScheduler.ScoreBatch":
            return self.score_batch(body)
        return {"error": f"unknown method {method!r}"}

    # ------------------------------------------------------------------

    def score_batch(self, body: Dict) -> Dict:
        """Run a batch of simple binpack evals through the batched kernel
        (ops/batch.py) against the live node table."""
        from ..ops.batch import batch_plan_picks_shared

        evals = body.get("evals") or []
        if not evals:
            return {"results": []}

        table = self.store.node_table
        C = table.capacity
        ready_rows = [
            row
            for node_id, row in table.row_of.items()
            if table.eligible[row]
        ]
        n_cand = len(ready_rows)
        if n_cand == 0:
            return {
                "results": [
                    {"eval_id": e.get("eval_id", ""), "nodes": []}
                    for e in evals
                ]
            }
        base_rows = np.asarray(sorted(ready_rows), dtype=np.int32)
        present = set(base_rows.tolist())
        rest = np.asarray(
            [r for r in range(C) if r not in present], dtype=np.int32
        )
        feasible = np.zeros(C, dtype=bool)
        feasible[base_rows] = True

        limit = max(2, math.ceil(math.log2(n_cand)))
        max_picks = max(int(e.get("count", 1)) for e in evals)

        perms = np.empty((len(evals), C), dtype=np.int32)
        asks = np.zeros((len(evals), 3))
        counts = np.zeros(len(evals), np.int32)
        for k, e in enumerate(evals):
            rng = random.Random(int(e.get("seed", 0)))
            order = shuffle_permutation(rng, n_cand)
            perms[k, :n_cand] = base_rows[order]
            perms[k, n_cand:] = rest
            asks[k] = (
                float(e.get("cpu", 100)),
                float(e.get("memory_mb", 300)),
                float(e.get("disk_mb", 300)),
            )
            counts[k] = int(e.get("count", 1))

        rows = np.asarray(
            batch_plan_picks_shared(
                table.cpu_total,
                table.mem_total,
                table.disk_total,
                feasible,
                table.cpu_used,
                table.mem_used,
                table.disk_used,
                perms,
                asks[:, 0],
                asks[:, 1],
                asks[:, 2],
                counts,
                np.full(len(evals), limit, np.int32),
                np.int32(n_cand),
                int(max_picks),
            )
        )

        results = []
        for k, e in enumerate(evals):
            chosen = [
                table.node_ids[r]
                for r in rows[k, : counts[k]]
                if r >= 0
            ]
            results.append(
                {"eval_id": e.get("eval_id", ""), "nodes": chosen}
            )
        return {"results": results}
