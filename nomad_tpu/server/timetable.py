"""Raft-index <-> wall-clock mapping (reference nomad/timetable.go).

The core GC scheduler needs "what raft index was current N hours ago"
to turn time thresholds into index cutoffs.  The table witnesses
(index, time) pairs at a fixed granularity and answers nearest-index /
nearest-time queries; entries beyond the retention limit roll off.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

DEFAULT_GRANULARITY_S = 60.0
DEFAULT_LIMIT_S = 72 * 3600.0


class TimeTable:
    def __init__(
        self,
        granularity_s: float = DEFAULT_GRANULARITY_S,
        limit_s: float = DEFAULT_LIMIT_S,
    ) -> None:
        self.granularity_s = granularity_s
        self.limit_s = limit_s
        self._lock = threading.Lock()
        # newest first (reference timetable.go table ordering)
        self._table: List[Tuple[int, float]] = []

    def witness(self, index: int, when: Optional[float] = None) -> None:
        """Record that `index` was current at `when`
        (reference timetable.go Witness)."""
        when = time.time() if when is None else when
        with self._lock:
            if self._table and (
                when - self._table[0][1] < self.granularity_s
            ):
                return
            self._table.insert(0, (index, when))
            # expire entries past the retention limit
            cutoff = when - self.limit_s
            while self._table and self._table[-1][1] < cutoff:
                self._table.pop()

    def nearest_index(self, when: float) -> int:
        """Largest witnessed index at-or-before `when`, 0 if none
        (reference timetable.go NearestIndex)."""
        with self._lock:
            for index, ts in self._table:
                if ts <= when:
                    return index
        return 0

    def nearest_time(self, index: int) -> float:
        """Time of the oldest witness at-or-after `index`, 0 if none
        (reference timetable.go NearestTime)."""
        with self._lock:
            for idx, ts in self._table:
                if idx <= index:
                    return ts
        return 0.0

    # snapshot support (reference fsm.go persists the table)

    def serialize(self) -> List[Tuple[int, float]]:
        with self._lock:
            return list(self._table)

    def deserialize(self, table: List[Tuple[int, float]]) -> None:
        with self._lock:
            self._table = [(int(i), float(t)) for i, t in table]
