"""Networked cluster-server entrypoint: one OS process = one server.

Boots a ClusterServer whose raft/gossip/forwarding RPCs travel over the
framed-TCP transport (nomad_tpu/raft/tcp.py) and serves the HTTP API —
the cross-process deployment shape of the reference agent in server
mode (command/agent: one process, one RPC port multiplexing raft + RPC
+ serf, plus the HTTP API).

Usage (what tests/test_cluster_tcp.py drives):

    python -m nomad_tpu.server.netagent \
        --addr 127.0.0.1:7101 \
        --peers 127.0.0.1:7101,127.0.0.1:7102,127.0.0.1:7103 \
        --http-port 8101 [--join 127.0.0.1:7102]

Prints ``READY addr=<addr> http=<port>`` on stdout once the RPC
listener and HTTP API are up, then runs until SIGTERM/SIGINT.
"""
from __future__ import annotations

import argparse
import signal
import sys
import threading


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="nomad-tpu-server")
    parser.add_argument("--addr", required=True, help="host:port RPC bind")
    parser.add_argument(
        "--peers", required=True,
        help="comma-separated raft peer addresses (including self)",
    )
    parser.add_argument("--http-port", type=int, default=0)
    parser.add_argument("--http-host", default="127.0.0.1")
    parser.add_argument("--region", default="global")
    parser.add_argument(
        "--join", default="",
        help="gossip seed address (any live server)",
    )
    parser.add_argument(
        "--election-timeout", type=float, default=0.6,
        help="raft election timeout seconds (network default is "
        "longer than the in-process default: dial timeouts must fit "
        "inside it)",
    )
    parser.add_argument(
        "--heartbeat-interval", type=float, default=0.15
    )
    parser.add_argument(
        "--heartbeat-ttl", type=float, default=None,
        help="node liveness TTL seconds (missed heartbeats mark the "
        "node down and reschedule its allocs); default 30",
    )
    parser.add_argument(
        "--num-schedulers", type=int, default=None,
    )
    parser.add_argument(
        "--seed-world", default="",
        help="JSON bigworld spec (loadgen/bigworld.py); once a leader "
        "is known the spec is raft-applied and every replica expands "
        "it deterministically — prints 'SEEDED nodes=N allocs=M' when "
        "the apply commits",
    )
    parser.add_argument(
        "--tls-ca", default="",
        help="CA bundle for mutual-TLS server<->server RPC "
        "(reference helper/tlsutil; requires --tls-cert/--tls-key)",
    )
    parser.add_argument("--tls-cert", default="")
    parser.add_argument("--tls-key", default="")
    parser.add_argument(
        "--tls-server-name", default="",
        help="pin outgoing connections to this server identity, e.g. "
        "server.global.nomad (reference verify_server_hostname): a "
        "CA-signed client cert then cannot impersonate a server",
    )
    args = parser.parse_args(argv)

    from ..api.http import start_http_server
    from ..raft.tcp import TcpTransport, TLSConfig
    from .cluster import ClusterServer

    tls = None
    if args.tls_ca or args.tls_cert or args.tls_key:
        if not (args.tls_ca and args.tls_cert and args.tls_key):
            parser.error("--tls-ca, --tls-cert and --tls-key go together")
        tls = TLSConfig(
            ca_file=args.tls_ca,
            cert_file=args.tls_cert,
            key_file=args.tls_key,
            server_name=args.tls_server_name,
        )
    import os

    if os.environ.get("NOMAD_TPU_DIST") == "1":
        # bring up this process's jax.distributed world BEFORE any
        # code can touch the local backend: a server that wins the
        # first election compiles exact-path kernels immediately, and
        # a backend initialized single-process cannot join a
        # multi-process world afterwards.  Failure is non-fatal — the
        # fan-out worker simply runs meshless (exact path).
        try:
            from ..parallel.mesh import distributed_init

            distributed_init()
        except Exception as exc:  # noqa: BLE001
            print(
                f"distributed init failed: {exc}", file=sys.stderr
            )

    transport = TcpTransport(tls=tls)
    extra = {}
    if args.heartbeat_ttl is not None:
        extra["heartbeat_ttl"] = args.heartbeat_ttl
    if args.num_schedulers is not None:
        extra["num_schedulers"] = args.num_schedulers
    server = ClusterServer(
        args.addr,
        [p for p in args.peers.split(",") if p],
        transport,
        region=args.region,
        election_timeout=args.election_timeout,
        heartbeat_interval=args.heartbeat_interval,
        **extra,
    )
    server.start()
    if args.join:
        try:
            server.join(args.join)
        except Exception as exc:  # noqa: BLE001 — seed may lag behind
            print(f"join {args.join} failed: {exc}", file=sys.stderr)
    http = start_http_server(
        server, host=args.http_host, port=args.http_port
    )
    print(f"READY addr={args.addr} http={http.port}", flush=True)

    if args.seed_world:
        import json

        spec = json.loads(args.seed_world)

        def _seed():
            # _raft_apply forwards to the leader with bounded retry;
            # loop across interregnums until the apply commits (the
            # harness watches for the SEEDED line)
            while True:
                try:
                    out = server._raft_apply("seed_world", (spec,))
                except Exception as exc:  # noqa: BLE001
                    print(
                        f"seed-world retry: {exc}",
                        file=sys.stderr,
                        flush=True,
                    )
                    import time

                    time.sleep(0.5)
                    continue
                print(
                    "SEEDED nodes={nodes} allocs={allocs}".format(
                        nodes=out.get("nodes"),
                        allocs=out.get("allocs"),
                    ),
                    flush=True,
                )
                return

        threading.Thread(
            target=_seed, name="seed-world", daemon=True
        ).start()

    stop = threading.Event()

    def _terminate(_sig, _frm):
        stop.set()

    signal.signal(signal.SIGTERM, _terminate)
    signal.signal(signal.SIGINT, _terminate)
    stop.wait()
    http.stop()
    server.stop()
    transport.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
