"""Periodic job dispatcher (reference nomad/periodic.go:22).

Tracks periodic jobs, computes next launch times from their cron specs,
and at each fire forces a child job (`<parent>/periodic-<ts>`) plus its
eval — the leader-side cron launcher.

Cron support: the five-field subset (minute hour dom month dow) with
"*", "*/n", single values and comma lists — the overwhelmingly common
shapes; arbitrary ranges can be added in the parser without touching the
dispatcher.
"""
from __future__ import annotations

import threading
import time
from dataclasses import replace as _replace
from datetime import datetime, timedelta
from typing import Dict, List, Optional

from ..structs import Job


def _field_matches(spec: str, value: int, base: int = 0) -> bool:
    if spec == "*":
        return True
    for part in spec.split(","):
        if part.startswith("*/"):
            step = int(part[2:])
            if (value - base) % step == 0:
                return True
        elif "-" in part:
            lo, hi = part.split("-")
            if int(lo) <= value <= int(hi):
                return True
        elif part and int(part) == value:
            return True
    return False


def next_cron_launch(spec: str, after: float) -> Optional[float]:
    """Next time matching a 5-field cron spec strictly after `after`."""
    fields = spec.split()
    if len(fields) != 5:
        return None
    minute, hour, dom, month, dow = fields
    t = datetime.fromtimestamp(int(after) - int(after) % 60)
    t += timedelta(minutes=1)
    for _ in range(366 * 24 * 60):  # search up to a year
        if (
            _field_matches(minute, t.minute)
            and _field_matches(hour, t.hour)
            and _field_matches(dom, t.day, base=1)
            and _field_matches(month, t.month, base=1)
            and _field_matches(dow, t.isoweekday() % 7)
        ):
            return t.timestamp()
        t += timedelta(minutes=1)
    return None


class PeriodicDispatcher:
    def __init__(self, server, interval: float = 0.25) -> None:
        self.server = server
        self.store = server.store
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # (namespace, job_id) -> next launch time
        self._next: Dict[tuple, float] = {}

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="periodic-dispatch", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    # ------------------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._tick()
            except Exception:  # noqa: BLE001
                pass

    def _tick(self) -> None:
        now = time.time()
        for job in list(self.store.iter_jobs()):
            if not job.is_periodic() or job.stopped():
                continue
            if not job.periodic.enabled:
                continue
            key = (job.namespace, job.id)
            nxt = self._next.get(key)
            if nxt is None:
                nxt = next_cron_launch(job.periodic.spec, now)
                if nxt is None:
                    continue
                self._next[key] = nxt
                continue
            if now < nxt:
                continue
            if job.periodic.prohibit_overlap and self._has_running_child(
                job
            ):
                # skip this launch window
                self._next[key] = next_cron_launch(job.periodic.spec, now)
                continue
            self.force_launch(job, launch_time=nxt)
            self._next[key] = next_cron_launch(job.periodic.spec, now)

    def _has_running_child(self, parent: Job) -> bool:
        for job in self.store.iter_jobs():
            if job.parent_id != parent.id:
                continue
            status = self.store.derive_job_status(job.namespace, job.id)
            if status in ("pending", "running"):
                return True
        return False

    # ------------------------------------------------------------------

    def force_launch(
        self, parent: Job, launch_time: Optional[float] = None
    ) -> Job:
        """Create and register the child job for one launch
        (reference periodic.go createEval / derivedJob)."""
        ts = int(launch_time or time.time())
        child = _replace(parent)
        child.id = f"{parent.id}/periodic-{ts}"
        child.name = child.id
        child.parent_id = parent.id
        child.periodic = None
        self.server.register_job(child)
        return child
