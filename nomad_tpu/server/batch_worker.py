"""Batched evaluation pipeline: the production integration of the
(evals x nodes x picks) kernel.

The per-eval TPU path pays one device round trip per placement, which is
ruinous when the accelerator sits behind a high-latency link (SURVEY.md
section 7.3).  The BatchWorker instead:

1. drains up to E compatible evals from the broker in one gulp,
2. runs a host-side *simulation pre-pass* per eval — the same
   reconciler the scheduler will run (reference generic_sched.go:332
   computeJobAllocs) — predicting the stops, in-place updates,
   destructive evictions, reschedule penalties and placement count,
3. *prescores* the run through a three-stage pipeline — assemble
   (host numpy staging into a chunk-aligned arena), launch
   (non-blocking `chained_plan_picks_cols` dispatches of
   chunk-wide slices, each chained on the previous chunk's
   device-resident carry), fetch (deferred device_get) — so chunk N
   executes on device while the host replays chunk N-1.  The chunk
   width is adapted per flush from the measured launch EWMAs
   (CHUNK_BUCKETS compiled-shape ladder: wide under backlog, narrow
   when latency-bound), and the chain stays OPEN while it is in
   flight: evals dequeued while chunk N launches or replays are
   gated, simulated against the chain snapshot and assembled into
   chunk N+1 of the *same* chain (continuous micro-batching — see
   docs/ARCHITECTURE.md "Continuous micro-batching";
   NOMAD_TPU_ADMIT=0 restores the flush-boundary gulp loop).  Every
   eval's
   full pick sequence runs with in-kernel plan-delta accumulation
   (pre-placement usage deltas, per-pick destructive evictions,
   per-pick penalty rows, failure coalescing) and the same seeded
   visit orders the sequential path would use; the shared usage
   columns come from a persistent device mirror delta-patched via the
   store's dirty-row log (see docs/ARCHITECTURE.md "Prescore
   pipeline"),
4. runs each eval through the ordinary GenericScheduler so all control
   flow (reconciler, blocked evals, retries, plan bookkeeping, status
   writes) stays in one implementation — but with a `PrescoredStack`
   whose `select` answers from the precomputed rows after exact host
   verification (fit) of each winner; in-place update probes delegate
   to an inner oracle stack,
5. falls back to the normal scheduler for any eval whose shape deviates
   from what was prescored (networks, devices, sticky disk, multi
   task groups, preemption retries, option mismatches, verification
   mismatches), re-prescoring the rest of the run on a fresh snapshot
   whenever a deviation or failed pick makes the chained state suspect.

Because the kernel reproduces the sequential selection exactly
(ops/batch.py), prescored evals produce bit-identical plans; the
fallback guarantees correctness for everything else.
"""
from __future__ import annotations

import logging
import random
import threading
from collections import deque
from dataclasses import dataclass, field
from dataclasses import replace as _dc_replace
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

LOG = logging.getLogger("nomad_tpu.server.batch_worker")

import numpy as np

from ..ops.batch import (
    ChainInputs,
    PreDeltas,
    StepDeltas,
    chained_plan_picks_cols,
    chained_plan_picks_cols_donated,
    patch_rows,
    pow2_bucket as _pow2,
)
from ..ops.constraints import MaskCompiler
from ..sched.feasible import shuffle_permutation
from ..sched.generic_sched import GenericScheduler
from ..sched.rank import BinPackIterator, RankedNode
from ..sched.stack import GenericStack, compute_visit_limit
from ..sched.tpu_stack import _SingleNodeSource
from ..sched.util import ready_nodes_in_dcs
from ..structs import (
    ALLOC_CLIENT_STATUS_FAILED,
    CONSTRAINT_DISTINCT_HOSTS,
    Evaluation,
    Job,
    TaskGroup,
)
from ..decisions import DECISIONS
from ..explain import EXPLAIN
from ..raft import NotLeaderError
from ..raft import chaos as _chaos
from ..trace import TRACE
from .worker import Worker

BATCH_MAX = 64
BATCH_WAIT_S = 0.005
MAX_PENALTY_NODES = 8  # per-pick penalty row slots in StepDeltas
MAX_PRE_ROWS = 512  # pre-placement delta rows before falling back
# eval-axis widths of one pipelined prescore launch: every run is
# sliced into chunks chained through the kernel's carry output, so
# production launches share a SMALL set of eval-axis trace buckets
# (padding waste is < one chunk per run instead of up to
# BATCH_MAX - 1) and chunk N's device time overlaps chunk N-1's host
# replay.  The width is chosen per flush from the measured launch
# EWMAs (_plan_chunk_width): the widest bucket under backlog (fewer
# dispatches), a narrow one when latency-bound (the first replay —
# and the first mid-chain admission point — arrives after ONE chunk's
# device time, not eight evals' worth).  Restricting widths to this
# ladder keeps the number of XLA trace shapes bounded exactly like
# the old fixed width did.
CHUNK_BUCKETS = (2, 4, 8)
# widest chunk bucket, kept under its historical name: the assembly
# arena, warm_shapes and the mesh path still use it as the default
# eval-axis alignment
PIPELINE_CHUNK = CHUNK_BUCKETS[-1]
# continuous micro-batching counters, zero-registered at Server
# construction (tools/check_stage_accounting.py check 10): every
# `admission.*` name the worker emits must appear here, so dashboards
# can tell "admission never engaged" from "admission not exported"
ADMISSION_COUNTERS = (
    "admission.admitted",
    "admission.deferred",
    "admission.chains",
)
# sharded (mesh) hot-path metrics, zero-registered at Server
# construction (tools.nomadlint mesh-metrics): every `mesh.*` name the
# worker emits must appear here, so dashboards can tell "mesh never
# engaged" from "mesh not exported".  mesh.launches counts sharded
# chunk dispatches; the gauges carry the sharded mirror's sync cost
# (host->device bytes uploaded by the LAST mirror sync — O(dirty rows)
# on the warm path, the acceptance gauge for the delta-patch contract),
# the chunk width mesh flushes ran at, and the sharded mirror's
# delta-hit rate
MESH_COUNTERS = ("mesh.launches",)
MESH_GAUGES = (
    "mesh.bytes_per_flush",
    "mesh.chunk_width",
    "mesh.hosts",
    "mesh.mirror_hit_rate",
)
# global storm solver (NOMAD_TPU_STORM=1) metrics, zero-registered at
# Server construction (tools.nomadlint storm-metrics): every `storm.*`
# name the worker emits must appear here, so dashboards can tell
# "storm mode never engaged" from "storm not exported".  Counters:
# solver launches, evals entering the storm path, alloc rows the
# solver assigned, members that fell back to the serial chain, and
# rows whose global assignment diverged from the greedy serial walk.
# Gauges: the last solve's auction rounds-to-converge and the family
# backlog the detector drained.
STORM_COUNTERS = (
    "storm.solves",
    "storm.evals",
    "storm.rows",
    "storm.fallbacks",
    "storm.divergent",
)
STORM_GAUGES = (
    "storm.rounds",
    "storm.backlog",
)
# optimistic parallel replay: below this many prescored evals in a run
# the speculative-wave dispatch overhead beats the win
REPLAY_MIN_WAVE = 2
# upper bound on retained dequeue timestamps: entries normally pop on
# ack/nack, but an eval that dies between dequeue and either would
# otherwise leak its stamp forever
DEQ_TS_MAX = 1024


class _Deviation(Exception):
    """The eval's control flow left the prescored fast path."""


class _SpecAbort(Exception):
    """Speculative replay left the provably-serial-equivalent path
    (e.g. its plan did not verify as a clean full commit against the
    wave snapshot); the eval must replay serially."""


_LRU_MISS = object()


class _LRUCache:
    """Bounded mapping with least-recently-used eviction: get()
    refreshes recency, put() evicts the coldest entry past capacity.
    Replaces the clear-all-on-overflow host-assembly caches, where a
    single one-off job spec used to evict every warm entry; stale-
    generation entries (generations are part of each key) now simply
    age out instead of forcing a flush."""

    __slots__ = ("cap", "_d")

    def __init__(self, cap: int) -> None:
        self.cap = cap
        self._d: dict = {}

    def get(self, key):
        value = self._d.pop(key, _LRU_MISS)
        if value is _LRU_MISS:
            return None
        self._d[key] = value  # re-insert: now most recent
        return value

    def put(self, key, value) -> None:
        self._d.pop(key, None)
        self._d[key] = value
        while len(self._d) > self.cap:
            del self._d[next(iter(self._d))]

    def __len__(self) -> int:
        return len(self._d)


def _count_values(snap, attribute: str, allocs) -> Dict[str, int]:
    """Allocs per attribute value of their node — shared with
    PropertySet so the batch path's spread bookkeeping can never
    desynchronize from the sequential scheduler's."""
    from ..sched.propertyset import count_values_by_property

    return count_values_by_property(snap, attribute, allocs)


@dataclass
class _Sim:
    """Predicted pre-placement outcome of one eval (the simulation
    pre-pass's mirror of computeJobAllocs up to the select calls)."""

    placements: int
    penalties: List[FrozenSet[str]] = field(default_factory=list)
    # pre-placement usage deltas: row -> [cpu, mem, disk]
    pre: Dict[int, List[float]] = field(default_factory=dict)
    # per-pick destructive evictions (aligned with placements)
    evict_rows: List[int] = field(default_factory=list)
    evict_res: List[Tuple[float, float, float]] = field(
        default_factory=list
    )
    evict_coll: List[int] = field(default_factory=list)
    # task-group routing: the ordered distinct groups this eval
    # places, and each pick's slot into that list (the sequential
    # path iterates groups within one eval — generic_sched.go:468)
    tgs: List[TaskGroup] = field(default_factory=list)
    pick_tg: List[int] = field(default_factory=list)
    # anti-affinity base per group slot: [T, C] (None when all zero)
    base_collisions: Optional[np.ndarray] = None
    # distinct_hosts occupancy from job groups placing NOTHING this
    # eval: their live allocs block nodes but have no T-axis slot
    occ_extra: Optional[np.ndarray] = None
    # static host ports asked per group slot (kernel collision mask)
    asked_ports: List[FrozenSet[int]] = field(default_factory=list)
    # host ports freed by this eval's staged stops/evictions — if any
    # intersects an asked port in the run, the chain past that point
    # is gated to the sequential path (the kernel carry is monotone)
    released_ports: FrozenSet[int] = frozenset()
    # device asks per group slot: matched-code-set -> instance count
    # (ops/batch.py DeviceInputs; pooled counting is exact only for
    # identical-or-disjoint sets — overlap gates in _flush_run)
    asked_devices: List[Dict[FrozenSet[int], int]] = field(
        default_factory=list
    )
    # (vendor, type, name) keys of device instances this eval's
    # staged stops/evictions would free
    released_device_keys: FrozenSet[tuple] = frozenset()
    # the shuffled walk order the sequential stack would use for the
    # placement set_nodes — captured from the sim ctx's rng AFTER the
    # reconciler's single-node probes consumed their draws
    order: Optional[np.ndarray] = None
    # replay-time passthrough state (preemption retries): the order
    # actually used by the prescore (only when rng-aligned) + its
    # candidate count
    replay_order: Optional[np.ndarray] = None
    replay_n_cand: int = 0
    # propertyset state per (group, spread attribute): value -> count
    spread_existing: Dict[tuple, Dict[str, int]] = field(
        default_factory=dict
    )
    spread_cleared: Dict[tuple, Dict[str, int]] = field(
        default_factory=dict
    )
    spread_proposed: Dict[tuple, Dict[str, int]] = field(
        default_factory=dict
    )


@dataclass
class _Assembled:
    """One admitted chain's kernel inputs, staged host-side by
    ``_assemble`` (the pipeline's first stage).  Every per-eval array
    carries a leading eval axis of ``E`` rows — ``E_real`` real evals
    padded up to a multiple of ``chunk`` with inert rows
    (wanted=0, n_cand=1) — so the launch stage can slice
    ``chunk``-wide slices that all share one trace bucket per
    width."""

    E_real: int
    E: int
    P: int
    T: int
    stacked: ChainInputs
    n_cands: np.ndarray  # i32[E]
    wanted: np.ndarray  # i32[E]
    spread_fit: bool
    coll0: Optional[np.ndarray]
    affinity: Optional[np.ndarray]
    spread: Optional[object]  # SpreadInputs
    deltas: StepDeltas
    pre: PreDeltas
    port_ask: Optional[np.ndarray]
    port_used0: Optional[np.ndarray]
    dev_ask: Optional[np.ndarray]
    dev_free0: Optional[np.ndarray]
    dev_aff: Optional[np.ndarray]
    dev_aff_on: Optional[np.ndarray]
    occ0: Optional[np.ndarray]
    dh_tg: Optional[np.ndarray]
    # the shared node columns every launch reads: the delta-patched
    # device mirror — plain device arrays on the chunk path, the
    # NamedSharding(P("nodes")) sharded mirror on the mesh path
    dev_cols: Optional[tuple] = None
    use_mesh: bool = False
    # eval-axis width this arena's E was aligned to (one launch =
    # one `chunk`-wide slice); chosen per flush by _plan_chunk_width
    chunk: int = PIPELINE_CHUNK


class _AdmissionQueue:
    """Mid-chain eval intake for the continuous micro-batching
    pipeline: while a chunk chain is in flight, the worker polls the
    broker through one of these (non-blocking) and admits gate-clean
    evals as new chunks of the SAME chain.

    FIFO discipline is absolute — the chain commits its members in
    dequeue order, so an eval that fails an admission gate cannot be
    skipped over: it is parked on ``deferred`` (the worker holds its
    broker lease) and the queue CLOSES, guaranteeing no later dequeue
    jumps the serial order.  The caller processes ``deferred`` as the
    next gulp once the chain completes."""

    __slots__ = ("worker", "deferred", "closed", "admitted_any")

    def __init__(self, worker) -> None:
        self.worker = worker
        self.deferred: List[Tuple[Evaluation, str]] = []
        self.closed = False
        self.admitted_any = False

    def poll(self, limit: int) -> List[Tuple[Evaluation, str]]:
        """Dequeue up to ``limit`` already-queued evals without
        waiting (an empty broker ends the round, never blocks the
        chain)."""
        out: List[Tuple[Evaluation, str]] = []
        if self.closed or limit <= 0:
            return out
        worker = self.worker
        broker = worker.server.broker
        while len(out) < limit:
            try:
                ev, token = broker.dequeue(
                    worker.schedulers, timeout=0.0
                )
            except Exception:  # noqa: BLE001 — intake is best-effort
                break
            if ev is None:
                break
            worker._note_dequeue(ev)
            out.append((ev, token))
        return out

    def defer(self, ev: Evaluation, token: str) -> None:
        self.deferred.append((ev, token))
        self.closed = True


class _DoneFuture:
    """Pre-resolved future for storm-wave members that skip
    speculation (serial-fallback members; every member when parallel
    replay is off): ``_commit_wave``'s drain loop needs only
    ``done()`` and ``result()``."""

    __slots__ = ("_value",)

    def __init__(self, value=None) -> None:
        self._value = value

    def done(self) -> bool:
        return True

    def result(self):
        return self._value


class _SpecPlanner:
    """Capturing Planner facade for speculative replay (phase A of the
    optimistic parallel replay — see docs/ARCHITECTURE.md "Optimistic
    parallel replay").  ``submit_plan`` verifies the plan against the
    shared wave snapshot (reusing ``plan_apply.evaluate_plan``, the
    same per-node check the applier runs) but commits NOTHING; every
    planner side effect — plan submit, eval status writes,
    blocked/follow-up eval creation — is recorded in call order and
    replayed verbatim by the in-order commit phase.  A plan whose
    speculative verification is not a clean full commit aborts the
    speculation: the serial path owns partial commits and their
    refresh/retry control flow."""

    def __init__(self, snap) -> None:
        self.snap = snap
        self.ops: List[tuple] = []
        # nodes the captured plans would mutate — part of the
        # speculation's conflict read set
        self.touched: Set[str] = set()

    def submit_plan(self, plan):
        from .plan_apply import evaluate_plan

        plan.snapshot_index = self.snap.index
        result, full = evaluate_plan(self.snap, plan)
        if not full:
            raise _SpecAbort("speculative verification was partial")
        self.touched.update(plan.node_update)
        self.touched.update(plan.node_allocation)
        self.touched.update(plan.node_preemptions)
        self.ops.append(("submit", plan))
        return result, None

    def update_eval(self, ev) -> None:
        self.ops.append(("update_eval", ev))

    def create_eval(self, ev) -> None:
        self.ops.append(("create_eval", ev))

    def reblock_eval(self, ev) -> None:
        self.ops.append(("reblock_eval", ev))


@dataclass
class _Speculation:
    """One eval's captured speculative replay, awaiting its in-order
    conflict check + commit."""

    ops: List[tuple]
    # two-tier read set (see docs/ARCHITECTURE.md "Optimistic
    # parallel replay").  strict_nodes: nodes hosting the job's
    # allocs at speculation time — the reconciler, tainted scan and
    # in-place update probes read them as real control-flow inputs,
    # so ANY touch past the wave baseline conflicts.  plan_nodes:
    # nodes the captured plans mutate — their reads are the winner
    # verification whose fit the kernel chain already modeled for
    # every earlier chain member, so touches the wave's OWN committed
    # plans account for are expected; only an unexpected (external)
    # touch conflicts.
    strict_nodes: Set[str]
    plan_nodes: Set[str]
    # the _replay_one contract: False = a prescored pick failed, the
    # chained state past this eval is suspect
    clean: bool
    # non-node reads the per-node ledger can't cover, re-checked at
    # commit time: the job version the replay ran against, the
    # scheduler-config table index, and (service evals) the absence
    # of a deployment
    job_fence: tuple = ()
    config_index: int = -1
    check_deployment: bool = False
    # placement explanation built on the pool thread, published only
    # if this speculation commits (a discarded speculation's replay
    # never happened as far as the explain ring is concerned)
    explain: Optional[Dict] = None


class PrescoredStack:
    """Stack whose select() replays a precomputed pick sequence.

    In-place update probes (generic_alloc_update_fn's single-node
    set_nodes + select, reference util.go:849) delegate to an inner
    oracle GenericStack, so the update/destructive decision is exact;
    full-node-set selects answer from the kernel rows after exact
    verification of each winner.

    Multi-task-group evals: the pick sequence carries each pick's
    group name (computePlacements iterates groups within one eval).
    Failure coalescing is per group — after a group's first failed
    pick the scheduler stops selecting for it, so the cursor silently
    consumes that group's remaining picks when another group selects."""

    def __init__(self, ctx, job: Job, pick_tgs: List[str],
                 rows: List[int], table,
                 penalties: List[FrozenSet[str]],
                 inner: GenericStack,
                 evict_rows: Optional[List[int]] = None,
                 pulls: Optional[List[int]] = None,
                 n_cand: int = 0,
                 order=None,
                 batch: bool = False) -> None:
        self.ctx = ctx
        self.job = job
        self.pick_tgs = pick_tgs
        self.rows = rows
        self.table = table
        self.penalties = penalties
        self.inner = inner
        self.evict_rows = evict_rows or []
        self.cursor = 0
        self.probing = False
        self.saw_failed_row = False
        self.failed_tgs: set = set()
        # preemption-retry passthrough state (r5): the kernel's
        # per-pick source-pull counts let the host reconstruct the
        # sequential walk offset at any pick, so a preempt retry can
        # seed the inner oracle EXACTLY where the sequential stack
        # would be and hand the rest of the eval to it
        self.pulls = pulls
        self.n_cand = n_cand
        self.order = order
        self.batch = batch
        self.passthrough = False
        self.entered_passthrough = False
        self._all_nodes: Optional[list] = None

    def set_nodes(self, nodes) -> None:
        # single-node set_nodes comes from inplace-update probing;
        # answer those exactly through the inner oracle stack
        if len(nodes) <= 1:
            self.probing = True
            self.inner.set_nodes(nodes)
        else:
            self.probing = False
            # kept for preemption passthrough: this is the exact list
            # the sequential stack would shuffle
            self._all_nodes = list(nodes)

    def set_job(self, job: Job) -> None:
        if job.id != self.job.id or job.version != self.job.version:
            raise _Deviation("job changed")
        self.inner.set_job(job)

    def _enter_passthrough(self) -> None:
        """Seed the inner oracle with the sequential stack's EXACT
        state at this pick — shuffled node list (the recorded
        permutation, not a fresh rng draw) and rotating walk offset
        (running sum of the kernel's per-pick source pulls) — then
        hand the remainder of the eval to it.  Preemption-mode selects
        and every later pick replay bit-identically through the real
        iterator chain (rank.py evict path), closing the r4
        preemption-retry carve-out for kernel-prescored evals."""
        nodes = self._all_nodes
        if (
            self.pulls is None
            or self.order is None
            or nodes is None
            or len(nodes) != self.n_cand
            or self.n_cand == 0
        ):
            raise _Deviation(
                "preemption retry needs the sequential path"
            )
        shuffled = [nodes[i] for i in self.order]
        # bypass GenericStack.set_nodes: it would draw a fresh
        # shuffle from the replay rng; the sequential order is the
        # recorded one
        self.inner.source.set_nodes(shuffled)
        self.inner.source.offset = int(
            sum(self.pulls[: self.cursor])
        ) % self.n_cand
        self.inner.limit.set_limit(
            compute_visit_limit(len(shuffled), self.batch)
        )
        self.passthrough = True
        self.entered_passthrough = True

    def select(self, tg: TaskGroup, options=None) -> Optional[RankedNode]:
        if self.probing:
            return self.inner.select(tg, options)
        if self.passthrough:
            # everything after the first preemption retry runs on the
            # exact oracle (its walk offset was seeded below); the
            # chain past this eval is already marked suspect
            return self.inner.select(tg, options)
        if options is not None and options.preempt:
            if getattr(self.ctx, "speculative", False):
                # the passthrough's oracle walk reads EVERY candidate
                # node — a read set the per-node conflict ledger can't
                # cover — so a speculative replay hands preemption
                # retries to the serial path
                raise _Deviation(
                    "preemption retry needs the serial replay"
                )
            self._enter_passthrough()
            return self.inner.select(tg, options)
        if options is not None and options.preferred_nodes:
            raise _Deviation("preferred nodes need the sequential path")
        # per-placement metric scope, like the serial chain's select
        # (GenericStack.select -> ctx.reset): each placement's
        # AllocMetric describes that placement, not the whole eval
        self.ctx.reset()
        # skip picks of groups the scheduler has coalesced (their
        # first failure means no further selects for that group)
        while (
            self.cursor < len(self.pick_tgs)
            and self.pick_tgs[self.cursor] in self.failed_tgs
        ):
            self.cursor += 1
        if self.cursor >= len(self.rows):
            raise _Deviation("prescored picks exhausted")
        if tg.name != self.pick_tgs[self.cursor]:
            raise _Deviation("unexpected task group")
        expected = (
            self.penalties[self.cursor]
            if self.cursor < len(self.penalties)
            else frozenset()
        )
        got = frozenset(
            options.penalty_node_ids
        ) if options is not None and options.penalty_node_ids else (
            frozenset()
        )
        if got != expected:
            raise _Deviation("penalty set mismatch")
        row = self.rows[self.cursor]
        pick = self.cursor
        self.cursor += 1
        if self.pulls is not None and pick < len(self.pulls):
            # the chained kernel's per-pick source-pull count is
            # exactly how many nodes the serial StaticIterator would
            # have evaluated for this placement — recorded
            # unconditionally so FailedTGAllocs on /v1/evaluation and
            # the plan API report the same NodesEvaluated the serial
            # path would, with or without the explain layer
            self.ctx.metrics.nodes_evaluated += int(self.pulls[pick])
        if row < 0:
            # prescored failure: the chain's state past this eval is
            # suspect (the caller re-prescores).  Within THIS eval the
            # kernel's per-group dead carry keeps the other groups'
            # remaining picks exact — UNLESS the failed pick staged a
            # destructive eviction, which the sequential path pops
            # back out of the plan (generic_sched.py:402) while the
            # kernel kept its delta applied
            self.saw_failed_row = True
            self.failed_tgs.add(tg.name)
            staged_evict = (
                pick < len(self.evict_rows)
                and self.evict_rows[pick] >= 0
            )
            more_other_tg = any(
                t not in self.failed_tgs
                for t in self.pick_tgs[self.cursor:]
            )
            if staged_evict and more_other_tg:
                raise _Deviation(
                    "failed pick staged an eviction; remaining "
                    "groups' rows are suspect"
                )
            return None
        node_id = self.table.node_ids[row]
        node = self.ctx.state.node_by_id(node_id)
        if node is None:
            raise _Deviation("node vanished")
        ranked = RankedNode(node=node)
        source = _SingleNodeSource(ranked)
        algorithm = (
            self.ctx.state.scheduler_config().effective_scheduler_algorithm()
        )
        binpack = BinPackIterator(
            self.ctx, source, False, self.job.priority, algorithm
        )
        binpack.set_job(self.job)
        binpack.set_task_group(tg)
        option = binpack.next()
        if option is None:
            raise _Deviation("winner failed exact verification")
        return option


class BatchWorker(Worker):
    """Worker that drains and prescores evals in batches."""

    # FanoutBatchWorker (server/fanout.py) overrides this marker.
    # With NOMAD_TPU_FANOUT_MESH=1 only the marked worker may bring
    # up the device mesh — a process hosting both the leader's main
    # workers and a follower fan-out worker must not have two workers
    # racing for one jax.distributed world / pod head port.
    _is_fanout_worker = False

    def __init__(self, server, **kwargs) -> None:
        super().__init__(server, **kwargs)
        # exclusive accelerator lock before any backend init: a second
        # jax process against a tunneled single-chip session wedges it
        # for every future process (no-op on CPU-only backends)
        from ..device_lock import ensure_device_lock

        ensure_device_lock("batch worker")
        # accelerator supervisor (nomad_tpu/device): the launch/fetch
        # stages run under its watchdog guards, and its backend epoch
        # keys every cache that holds device-resident or
        # backend-compiled state.  On a failover (or recovery
        # flip-back) the transition listener flushes those caches so a
        # re-targeted pipeline can never replay stale device buffers.
        self.supervisor = getattr(server, "device_supervisor", None)
        self._backend_epoch = (
            self.supervisor.backend_epoch
            if self.supervisor is not None
            else 0
        )
        # fallback evals are the shapes batching didn't cover: the
        # exact host stack beats per-pick device round trips there
        self.host_fallback = True
        # tunable per deployment: larger launches amortize dispatch
        # (throughput), smaller ones cut per-eval service latency.
        # Clamped to [1, BATCH_MAX]: the prescore eval-axis buckets
        # (and warmed compile shapes) top out at BATCH_MAX, so a
        # larger value would only overflow the stacked inputs and
        # demote every big batch to the sequential path
        import os as _os

        try:
            requested = int(
                _os.environ.get("NOMAD_TPU_BATCH_MAX", BATCH_MAX)
            )
        except ValueError:
            LOG.warning(
                "invalid NOMAD_TPU_BATCH_MAX=%r; using %d",
                _os.environ.get("NOMAD_TPU_BATCH_MAX"),
                BATCH_MAX,
            )
            requested = BATCH_MAX
        self.batch_max = max(1, min(BATCH_MAX, requested))
        self.prescored = 0
        self.fallbacks = 0
        self.errors = 0
        self.cold_shape_fallbacks = 0
        self.mesh_used = 0
        self.preempt_passthroughs = 0
        # optimistic parallel replay (the same optimistic-concurrency
        # shape as the plan applier): prescored evals replay
        # speculatively on a thread pool against the shared wave
        # snapshot, then commit in queue order behind a per-node
        # conflict check — an eval whose read set was mutated by an
        # earlier-committed plan (or an external writer) is discarded
        # and re-replayed serially, so the committed outcome is
        # bit-identical to the serial worker loop.
        # NOMAD_TPU_PARALLEL_REPLAY=0 restores the serial replay loop.
        self.parallel_replay = (
            _os.environ.get("NOMAD_TPU_PARALLEL_REPLAY", "1") != "0"
        )
        # strict mode: ALL read nodes conflict on any touch, own-wave
        # commits included — full bit-identity of alloc score metrics
        # on wave-contended nodes, at the cost of serializing every
        # contended eval (the relaxed default keeps decisions, plans
        # and eval outcomes bit-identical; only contended-node score
        # metrics may reflect the wave snapshot)
        self.replay_strict = (
            _os.environ.get("NOMAD_TPU_REPLAY_STRICT") == "1"
        )
        # node-touch counts of the last serial replay's committed
        # plan (None = unknown writes), merged into the wave's
        # expected-touch ledger so serial fallbacks don't poison the
        # relaxed conflict check for later wave members
        self._last_replay_touches: Optional[Dict[str, int]] = None
        try:
            self.replay_workers: Optional[int] = (
                int(_os.environ.get("NOMAD_TPU_REPLAY_WORKERS", "0"))
                or None
            )
        except ValueError:
            self.replay_workers = None
        self._replay_pool = None  # lazy EvaluatePool
        self.replay_speculative = 0  # speculations committed
        self.replay_conflicts = 0  # speculations discarded on conflict
        self.replay_serial_fallbacks = 0  # wave evals replayed serially
        # dequeue timestamps for the per-eval service-latency samples
        self._deq_ts: Dict[str, float] = {}
        # adaptive batch sizing (VERDICT r3 #2): close the loop from
        # MEASURED launch/replay latency instead of a fixed gulp size.
        # When the backlog shows the worker is keeping up, cap the
        # batch so the last eval's estimated end-to-end time stays
        # within the budget; under saturation queueing dominates and
        # the full batch maximizes throughput.  0 disables.
        try:
            self.latency_budget_ms = float(
                _os.environ.get("NOMAD_TPU_LATENCY_BUDGET_MS", 250.0)
            )
        except ValueError:
            self.latency_budget_ms = 250.0
        # per-chunk launch cost (dispatch + the blocking fetch wait),
        # keyed by chunk WIDTH bucket (CHUNK_BUCKETS) — the adaptive
        # gulp cap and the per-flush chunk-width policy both read it
        self._launch_ewma: Dict[int, float] = {}  # chunk width -> ms
        # first measured warm launch, used as the default estimate for
        # buckets with no samples yet (replacing the old 50.0 ms
        # constant, which misestimated both a laptop CPU backend and a
        # tunneled TPU by an order of magnitude in opposite directions)
        self._launch_ewma_seed: Optional[float] = None
        # separate seed for mesh dispatches (their first warm launch
        # says nothing about single-chip chunks, and vice versa)
        self._mesh_ewma_seed: Optional[float] = None
        self._replay_ewma_ms = 5.0
        # decision-ledger dedup: chunk width / adaptive cap are
        # per-gulp hot paths, so they ledger only when the CHOICE
        # changes — a steady-state 64-wide drain is one record, not
        # ten thousand (which would evict every other site's flight
        # data from the bounded ring)
        self._last_chunk_width = 0
        self._last_adaptive_cap = 0
        # continuous micro-batching (NOMAD_TPU_ADMIT=0 restores the
        # flush-boundary gulp loop): evals dequeued while a chunk
        # chain is in flight are admitted into that chain's next chunk
        # when the admission gates prove they would see exactly the
        # state a fresh gulp would
        self.admit_enabled = (
            _os.environ.get("NOMAD_TPU_ADMIT", "1") != "0"
        )
        # global storm solver (NOMAD_TPU_STORM=1): when the broker
        # holds a backlog of >= storm_min pending evals of ONE job
        # family, the family prefix is drained atomically and solved
        # as a single (pending-allocs x nodes) assignment on the
        # device instead of walking the per-eval chunk chain.  Serial
        # equivalence is explicitly relaxed behind this flag (the win
        # is storm throughput + global placement quality); every
        # member still commits through the _commit_wave conflict
        # fences in broker FIFO order, with unsolvable or conflicted
        # members falling back to the serial chain — zero evals lost.
        self.storm_enabled = (
            _os.environ.get("NOMAD_TPU_STORM") == "1"
        )
        try:
            self.storm_min = max(
                1, int(_os.environ.get("NOMAD_TPU_STORM_MIN", "16"))
            )
        except ValueError:
            self.storm_min = 16
        try:
            self.storm_max = int(
                _os.environ.get("NOMAD_TPU_STORM_MAX", "256")
            )
        except ValueError:
            self.storm_max = 256
        self.storm_max = max(self.storm_min, min(self.storm_max, 1024))
        try:
            # 0 = auto: the solve's padded row bucket (the auction
            # assigns at least one row per round, so the bucket is
            # the convergence bound)
            self.storm_rounds = int(
                _os.environ.get("NOMAD_TPU_STORM_ROUNDS", "0")
            )
        except ValueError:
            self.storm_rounds = 0
        self.storm_solves = 0
        self.storm_evals = 0
        self.storm_rows = 0
        self.storm_fallbacks = 0
        self.storm_divergent = 0
        self.admission_admitted = 0
        self.admission_deferred = 0
        self.admission_chains = 0
        # evals dequeued mid-chain but gated out of it: processed as
        # the next gulp (run() drains this after every batch) so FIFO
        # order with their chain is preserved
        self._deferred: List[Tuple[Evaluation, str]] = []
        # broker leases taken by mid-chain admission this batch:
        # run()'s crash handler nacks these too (they are in neither
        # the original gulp nor _deferred), so a crash between
        # admission and ack can't strand a lease — and with it every
        # later same-job eval — until the broker's nack timeout
        self._admitted_live: List[Tuple[Evaluation, str]] = []
        # abandoned in-flight launches (wedge/failover/fetch error)
        # may still be reading the device usage mirror(s): the next
        # sync of EACH mirror must re-upload instead of donating the
        # buffers (per-mirror flags: a plain re-upload must not
        # re-enable donation on the sharded mirror, whose buffers the
        # abandoned mesh launch may still hold)
        self._mirror_dirty = False
        self._mirror_dirty_sharded = False
        # host-assembly caches keyed by the node table's topology
        # generation (usage churn does NOT invalidate them): candidate
        # row layout per datacenter set, static feasibility /
        # affinity vectors per job signature, and node-level reserved-
        # port columns per port.  Bounded LRUs: a one-off job spec
        # evicts only the coldest entry, never the whole warm set
        self._cand_cache = _LRUCache(64)
        self._mask_cache = _LRUCache(256)
        self._port_col_cache = _LRUCache(256)
        self._dev_codes_cache = _LRUCache(256)
        self._dev_aff_cache = _LRUCache(64)
        # snapshot-delta input cache: device-resident mirror of the
        # node table's totals + usage columns, patched per flush from
        # the store's dirty-row log (store.usage_delta_since) instead
        # of re-shipping all C rows.  {"key": (topo_gen, C),
        # "gen": usage generation synced, "cols": 6 device arrays}
        self._usage_cache: Optional[dict] = None
        # the SHARDED twin (NOMAD_TPU_MESH): the same six columns as
        # NamedSharding(P("nodes")) arrays over the node-axis mesh,
        # delta-patched per shard (ops/batch.patch_rows_sharded) so a
        # warm mesh flush ships O(dirty rows) bytes instead of full
        # node columns.  Both mirrors share the dirty-row log but sync
        # independently (each tracks its own generation)
        self._usage_cache_sharded: Optional[dict] = None
        self._mesh_mirror_hits = 0
        self._mesh_mirror_misses = 0
        # serializes mirror syncs: the prescore-warmup thread
        # (NOMAD_TPU_WARM_ON_START) and the worker thread both call
        # _device_columns, and two interleaved delta syncs could
        # record a generation whose rows one of them never patched
        self._usage_cache_lock = threading.Lock()
        self._input_cache_hits = 0
        self._input_cache_misses = 0
        # pipelined prescore: how many chunk launches may be in flight
        # before the host blocks on the oldest one's fetch.  1 degrades
        # to launch->fetch->replay per chunk (no overlap); 0/negative
        # clamps to 1
        try:
            self.pipeline_depth = max(
                1,
                int(
                    _os.environ.get("NOMAD_TPU_PIPELINE_DEPTH", 2)
                ),
            )
        except ValueError:
            self.pipeline_depth = 2
        self._donate_carries: Optional[bool] = None
        # cold-compile shield: launch signatures known to be compiled.
        # A first-seen shape is compiled on a background thread while
        # the affected evals take the exact sequential path, so an XLA
        # compile (seconds) never stalls the scheduling pipeline.
        self._compiled: set = set()
        self._compiling: set = set()
        self._compile_failed: set = set()
        self._compile_lock = threading.Lock()
        # node-axis device mesh: with NOMAD_TPU_MESH=1 and >1 device
        # the prescore launches shard the node columns so per-device
        # FLOPs scale ~1/devices (parallel/mesh.py
        # sharded_chained_plan)
        self._mesh = None
        # processes contributing devices to the mesh (1 = the PR 8
        # single-host world; >1 = a NOMAD_TPU_DIST multi-host pod,
        # which flips the mirror staging to the per-host protocol and
        # pins compiles inline — see _launch_chunk_mesh)
        self._mesh_hosts = 1
        # pod head service (NOMAD_TPU_POD_PORT, process 0 of a
        # multi-host world): streams this worker's mesh-operation
        # sequence to the other world members so a fan-out follower
        # can head a pod WITHOUT lockstep peers (parallel/pod.py).
        # None in the PR 11 lockstep mode and on single-host meshes.
        self._pod = None
        self._sharded_runners: Dict[tuple, object] = {}
        # opt-in: virtual CPU meshes make every launch slower (the
        # sharding tests cover parity); real multi-chip TPU deployments
        # set NOMAD_TPU_MESH=1
        self._mesh_requested = (
            _os.environ.get("NOMAD_TPU_MESH") == "1"
            and self._mesh_allowed()
        )
        if self._mesh_requested and (
            self.supervisor is None
            or not self.supervisor.failed_over()
        ):
            self._mesh = self._make_mesh()
        # after the caches exist: a transition firing mid-construction
        # must see a fully-initialized worker
        if self.supervisor is not None:
            self.supervisor.subscribe(self._on_device_transition)
        # stage timings (seconds, cumulative) — surfaced through
        # /v1/metrics so a production operator can see where batch time
        # goes and whether the fast path is actually being taken.  The
        # old opaque "prescore" stage is split into its pipeline
        # stages: assemble (host numpy input staging), launch
        # (non-blocking device dispatch) and fetch (time blocked
        # waiting on device results — the part replay overlap hides)
        self.timings = {
            "simulate": 0.0,
            "assemble": 0.0,
            "admit": 0.0,
            "launch": 0.0,
            "fetch": 0.0,
            "mesh_launch": 0.0,
            "mesh_fetch": 0.0,
            "storm_solve": 0.0,
            "storm_decompose": 0.0,
            "replay": 0.0,
            "sequential": 0.0,
        }
        # happens-before sanitizer (NOMAD_TPU_TSAN=1): instruments
        # as family "Worker" — the flowgraph collapses BatchWorker
        # onto its root class, and the SHARED_STATE_ALLOWLIST keys
        # by that family
        from ..tsan import maybe_instrument

        maybe_instrument(self, "Worker")

    def _mesh_allowed(self) -> bool:
        """Whether THIS worker may own the device mesh.

        Default: yes.  With NOMAD_TPU_FANOUT_MESH=1 the mesh is
        reserved for the follower fan-out worker
        (``_is_fanout_worker``): the fan-out deployment runs ONE
        fanout worker per server process heading a multi-process
        ``jax.distributed`` world, and the leader-side main workers
        on the same process must stay meshless or they would race the
        fanout worker for the world's coordinator and the pod head
        port.
        """
        import os as _os

        if _os.environ.get("NOMAD_TPU_FANOUT_MESH") != "1":
            return True
        return bool(getattr(self, "_is_fanout_worker", False))

    def _make_mesh(self):
        """Node-axis device mesh when the hardware offers >1 device;
        None otherwise (and on any failure — the mesh is an
        optimization, never a requirement).  NOMAD_TPU_MESH_DEVICES
        caps the node axis (bench sweeps and deployments that reserve
        chips for other work).

        With the NOMAD_TPU_DIST_* knobs set, the multi-host world is
        joined FIRST (`distributed_init`, idempotent) so
        ``jax.devices()`` counts every host's devices and the node
        axis spans the pod.  A misconfigured world raises out of here
        deliberately — the peer processes would deadlock inside their
        first collective waiting for a member that silently fell back
        to single-host."""
        import os as _os

        from ..parallel.mesh import distributed_init

        distributed_init()
        self._mesh_hosts = 1
        try:
            import jax as _jax

            n = len(_jax.devices())
            try:
                cap = int(
                    _os.environ.get("NOMAD_TPU_MESH_DEVICES", "0")
                )
            except ValueError:
                cap = 0
            if cap > 0:
                n = min(n, cap)
            if n > 1:
                from ..parallel.mesh import host_count, make_mesh

                mesh = make_mesh(n_devices=n, eval_axis=1)
                self._mesh_hosts = host_count(mesh)
                if self._mesh_hosts > 1:
                    self._attach_pod()
                metrics = getattr(self.server, "metrics", None)
                if metrics is not None:
                    # published at bring-up (not first sync): the
                    # bigworld harness reads this gauge to confirm a
                    # follower's pod formed before any eval arrives
                    metrics.set_gauge(
                        "mesh.hosts", float(self._mesh_hosts)
                    )
                return mesh
        except Exception:  # noqa: BLE001 — mesh is an optimization
            self._mesh_hosts = 1
        return None

    def _attach_pod(self) -> None:
        """Pod-head mode: with NOMAD_TPU_POD_PORT set, process 0 of a
        multi-host world serves the mesh-operation stream the other
        members replay (parallel/pod.py).  Idempotent — a failover
        recovery rebuilds the mesh over the SAME world, and the
        already-connected peers keep following the stream (the
        post-flip full resync re-establishes their mirrors).  Failing
        to bring the service up falls through to _make_mesh's
        no-mesh path: degraded to the exact launches, never a pod
        half-joined at a collective."""
        if self._pod is not None:
            return
        import os as _os

        port = _os.environ.get("NOMAD_TPU_POD_PORT")
        if not port:
            return
        import jax as _jax

        if _jax.process_index() != 0:
            return
        from ..ops.contracts import MESH_FANOUT_WIDTHS
        from ..parallel.pod import PodService

        n_global = len(_jax.devices())
        if n_global not in MESH_FANOUT_WIDTHS:
            # pod-ladder gate: an undeclared fan-out width would
            # compile off-contract chained/storm signatures on every
            # follower at once.  Raising drops the whole mesh in
            # _make_mesh (exact launches only — a meshed head
            # without its pod service would deadlock the peers'
            # first collective instead)
            LOG.warning(
                "fan-out pod width %d not in MESH_FANOUT_WIDTHS %s"
                " — mesh declined",
                n_global, MESH_FANOUT_WIDTHS,
            )
            raise RuntimeError("undeclared fan-out pod width")
        self._pod = PodService(
            int(port), n_peers=_jax.process_count() - 1
        )

    # -- accelerator supervisor integration ----------------------------

    def _guard_device(
        self, stage: str, fn, exemplar: Optional[str] = None
    ):
        """Run a pipeline stage under the supervisor's launch
        watchdog.  Without a supervisor (or while failed over to the
        CPU backend, which cannot wedge) the call passes through."""
        sup = self.supervisor
        if sup is None:
            return fn()
        return sup.guard(stage, fn, eval_id=exemplar)

    def _on_device_transition(
        self, old: str, new: str, reason: str
    ) -> None:
        """Backend flip (failover to CPU, or recovery back to the
        device): flush every cache keyed by — or holding buffers of —
        the previous backend, so no launch can read stale device
        state.  The epoch also keys the device mirror and the
        compiled-shape shield, so even a racing in-flight reader
        re-syncs rather than reusing a pre-flip entry."""
        sup = self.supervisor
        epoch = sup.backend_epoch
        if epoch == self._backend_epoch:
            return  # state moved but the pipeline target didn't
        self._backend_epoch = epoch
        # device-resident usage mirror: buffers live on the OLD
        # backend.  Deliberately NOT under _usage_cache_lock: a wedged
        # sacrificial assemble thread may be parked inside
        # _device_columns_locked HOLDING that lock (device_put never
        # returned), and this listener runs on the very thread the
        # watchdog just protected — taking the lock here would
        # re-wedge it.  The bare assignment is atomic, and an
        # in-flight holder can at worst re-publish a dict whose key
        # carries the OLD backend epoch, which the next lookup misses
        # and fully resyncs.
        self._usage_cache = None
        # the sharded mirror's buffers live on the old backend's mesh
        # shards — same epoch-keyed flush
        self._usage_cache_sharded = None
        # ... and REPLACE the lock itself: post-flip _device_columns
        # calls run unguarded (CPU cannot wedge) and must never queue
        # behind that abandoned holder.  Late writers racing the swap
        # publish stale-epoch caches the key check discards.
        self._usage_cache_lock = threading.Lock()
        # host-assembly caches hold no device state, but flushing them
        # keeps the post-flip world observably cold (and is cheap —
        # one rebuild per entry)
        self._cand_cache = _LRUCache(64)
        self._mask_cache = _LRUCache(256)
        self._port_col_cache = _LRUCache(256)
        self._dev_codes_cache = _LRUCache(256)
        self._dev_aff_cache = _LRUCache(64)
        with self._compile_lock:
            # compiled-shape shield: executables were compiled for the
            # old backend (in-flight background compiles finish into
            # the old epoch's key space and are simply never matched)
            self._compiled.clear()
            self._compile_failed.clear()
        # rebind rather than clear(): this listener may run on the
        # supervisor's probe thread while the worker thread iterates
        # these dicts (_export_adaptive_gauges) — clearing mid-iteration
        # raises RuntimeError there, a fresh dict does not
        self._sharded_runners = {}
        self._launch_ewma = {}
        # the seed measurements came from the OLD backend — a TPU's
        # first warm launch says nothing about the CPU fallback's
        self._launch_ewma_seed = None
        self._mesh_ewma_seed = None
        # in-flight launches abandoned by the flip may still read the
        # mirrors; force the next sync of each to re-upload (no
        # donation)
        self._mirror_dirty = True
        self._mirror_dirty_sharded = True
        # donation only helps off-CPU; re-resolve for the new target
        self._donate_carries = None
        if sup.failed_over():
            # sharded mesh path: off while on the CPU fallback.  On a
            # multi-host mesh this is ALSO the peer-death path: a dead
            # process surfaces as a collective error on the healthy
            # hosts, the watchdog trips the supervisor, and every
            # in-flight chain drops through the exact-sequential
            # fallback — zero lost evals, same as a wedged chip
            self._mesh = None
            self._mesh_hosts = 1
        elif self._mesh_requested and self._mesh is None:
            self._mesh = self._make_mesh()
        metrics = getattr(self.server, "metrics", None)
        if metrics is not None:
            metrics.set_gauge(
                "batch_worker.backend_epoch", float(epoch)
            )
            # the pod-width gauge must not report the old world
            # through the exact incident it exists for (a peer-death
            # failover drops the mesh; the sharded sync that normally
            # refreshes it cannot run while failed over)
            metrics.set_gauge(
                "mesh.hosts", float(self._mesh_hosts)
            )
        LOG.warning(
            "batch worker re-targeted (%s -> %s, %s): caches flushed, "
            "backend epoch %d", old, new, reason, epoch,
        )

    def _sharded_runner(self, n_picks: int, spread_fit: bool,
                        with_spread: bool = False,
                        spread_even: bool = False):
        key = (n_picks, spread_fit, with_spread, spread_even)
        runner = self._sharded_runners.get(key)
        if runner is None:
            from ..parallel.mesh import sharded_chained_plan

            # return_carry=True always: every production mesh launch
            # is a chunk of a (possibly length-1) chain, and the
            # sharded usage carry threads chunk -> chunk on-device
            runner = sharded_chained_plan(
                self._mesh, n_picks, spread_fit,
                with_spread=with_spread,
                spread_even=spread_even,
                return_carry=True,
            )
            runner.__name__ = f"sharded_chained_{n_picks}_{spread_fit}"
            self._sharded_runners[key] = runner
        return runner

    def _observe(
        self, stage: str, dt: float,
        exemplar: Optional[str] = None,
    ) -> None:
        self.timings[stage] += dt
        metrics = getattr(self.server, "metrics", None)
        if metrics is not None:
            # exemplar = the eval id (trace id) this sample belongs
            # to, so a slow p99 sample on /v1/metrics links straight
            # to /v1/traces/<id>
            metrics.add_sample(
                f"batch_worker.{stage}", dt * 1000.0,
                exemplar=exemplar,
            )

    def _observe_chunk(
        self, stage: str, run, base: int, c0: int, c1_real: int,
        t0: float, dt: float, **attrs,
    ) -> None:
        """Observe a chunk-wide stage interval and attribute it to
        every member eval's trace: first member as the metrics
        exemplar, and a per-member span carrying its chain position
        plus the membership count (so trace aggregations can divide
        the shared dt back out to match the timings accounting).
        ``base`` is the run index of the chunk's arena's eval 0."""
        chunk_evs = [run[base + e][0] for e in range(c0, c1_real)]
        self._observe(
            stage, dt,
            exemplar=chunk_evs[0].id if chunk_evs else None,
        )
        for pos, c_ev in enumerate(chunk_evs):
            TRACE.add_span(
                c_ev.id, f"batch_worker.{stage}", t0, dt,
                chain_pos=c0 + pos, members=len(chunk_evs), **attrs,
            )

    def _sample_eval_latency(self, ev: Evaluation) -> None:
        """Per-eval service latency (dequeue -> processed), the
        north-star p50/p99 exported via /v1/metrics so an operator
        sees it without running the bench (VERDICT r3 weak #7)."""
        import time as _time

        t0 = self._deq_ts.pop(ev.id, None)
        if t0 is None:
            return
        metrics = getattr(self.server, "metrics", None)
        if metrics is not None:
            metrics.add_sample(
                "batch_worker.eval_latency_ms",
                (_time.monotonic() - t0) * 1000.0,
                exemplar=ev.id,
            )

    def _count(self, name: str) -> None:
        """Bump a pipeline counter both on the worker and in /v1/metrics
        (prescore rate and fallback/error visibility was VERDICT r2
        weak #8: nothing read these in production)."""
        setattr(self, name, getattr(self, name) + 1)
        metrics = getattr(self.server, "metrics", None)
        if metrics is not None:
            metrics.incr(f"batch_worker.{name}")

    def _count_replay(self, kind: str) -> None:
        """Optimistic-replay counters, exported under the `replay.`
        namespace on /v1/metrics (speculative | conflicts |
        serial_fallbacks)."""
        attr = f"replay_{kind}"
        setattr(self, attr, getattr(self, attr) + 1)
        metrics = getattr(self.server, "metrics", None)
        if metrics is not None:
            metrics.incr(f"replay.{kind}")

    def _count_admission(self, kind: str) -> None:
        """Continuous micro-batching counters, exported under the
        `admission.` namespace on /v1/metrics (admitted | deferred |
        chains; the family is zero-registered at Server construction
        from ADMISSION_COUNTERS)."""
        attr = f"admission_{kind}"
        setattr(self, attr, getattr(self, attr) + 1)
        metrics = getattr(self.server, "metrics", None)
        if metrics is not None:
            metrics.incr(f"admission.{kind}")

    def _count_storm(self, kind: str, n: int = 1) -> None:
        """Global-storm-solver counters, exported under the `storm.`
        namespace on /v1/metrics (solves | evals | rows | fallbacks |
        divergent; the family is zero-registered at Server
        construction from STORM_COUNTERS)."""
        attr = f"storm_{kind}"
        setattr(self, attr, getattr(self, attr) + n)
        metrics = getattr(self.server, "metrics", None)
        if metrics is not None:
            metrics.incr(f"storm.{kind}", float(n))

    def _record_decision(self, site: str, action: str, **kw) -> None:
        """Ledger hook (nomad_tpu/decisions.py): every adaptive
        decision in this worker funnels through here so the inputs
        snapshot always carries the two epochs a post-hoc reader
        needs to interpret it — the leadership generation the
        decision ran under and the backend epoch its cost EWMAs were
        measured against."""
        inputs = dict(kw.pop("inputs", None) or {})
        inputs.setdefault("leader_gen", self._leader_gen())
        inputs.setdefault("backend_epoch", self._backend_epoch)
        DECISIONS.record(
            site,
            action,
            inputs=inputs,
            metrics=getattr(self.server, "metrics", None),
            **kw,
        )

    def _count_policy(self, kind: str) -> None:
        """Policy-weighted-scoring counters, exported under the
        `policy.` namespace on /v1/metrics (the family is
        zero-registered at Server construction from
        sched/policy.py POLICY_COUNTERS)."""
        metrics = getattr(self.server, "metrics", None)
        if metrics is not None:
            metrics.incr(f"policy.{kind}")

    def _export_adaptive_gauges(self) -> None:
        """The adaptive-cap inputs as /v1/metrics gauges, so an
        operator can see WHY `_adaptive_cap` picked a gulp size (the
        launch EWMA per trace bucket and the per-eval replay EWMA are
        the whole decision)."""
        metrics = getattr(self.server, "metrics", None)
        if metrics is None:
            return
        metrics.set_gauge(
            "batch_worker.replay_ewma_ms", self._replay_ewma_ms
        )
        for bucket, ms in self._launch_ewma.items():
            # mesh buckets are ("mesh", width) tuples -> .m<width>;
            # the storm solver's dedicated bucket -> .storm
            if isinstance(bucket, tuple):
                suffix = f"m{bucket[1]}"
            elif bucket == "storm":
                suffix = "storm"
            else:
                suffix = f"e{bucket}"
            metrics.set_gauge(
                f"batch_worker.launch_ewma_ms.{suffix}", ms
            )

    def _replay_pool_instance(self):
        """Lazy speculative-replay pool (the plan applier's
        EvaluatePool shape, sized cores/2 unless
        NOMAD_TPU_REPLAY_WORKERS overrides); its width is the
        `batch_worker.replay_parallelism` gauge.

        Re-created when the previous pool was shut down: leadership
        can be re-established on the same server (revoke -> establish
        on re-election), and the new generation's waves must not
        submit into the dead pool — this exact shape stranded every
        wave (and three-struck its evals into the failed queue) in
        the chaos smoke before the check existed."""
        if self._replay_pool is None or self._replay_pool.closed:
            from .plan_apply import EvaluatePool

            self._replay_pool = EvaluatePool(
                self.replay_workers,
                thread_name_prefix="replay-spec",
            )
            metrics = getattr(self.server, "metrics", None)
            if metrics is not None:
                metrics.set_gauge(
                    "batch_worker.replay_parallelism",
                    self._replay_pool.workers,
                )
        return self._replay_pool

    def stop(self) -> None:
        super().stop()
        if self._replay_pool is not None:
            self._replay_pool.shutdown()

    def dispose(self) -> None:
        """Final disposal (process shutdown / fleet discard), as
        opposed to ``stop()``, which both leadership cycles and
        fan-out teardown treat as a PAUSE: the pod head service (and
        with it the peers' device mirrors) must survive stop/start
        cycles — a re-established fleet catches the peers up in
        O(dirty rows) deltas instead of rebuilding the world — and a
        pod head cannot be re-bound while the old service still owns
        the port."""
        self.stop()
        if self._pod is not None:
            self._pod.close()
            self._pod = None

    # ------------------------------------------------------------------

    def _chunk_buckets(self) -> tuple:
        """The compiled-shape chunk-width ladder, clamped to the
        operator's batch ceiling (a NOMAD_TPU_BATCH_MAX below the
        widest bucket must never mint launches wider than a gulp can
        be)."""
        buckets = tuple(
            w for w in CHUNK_BUCKETS if w <= self.batch_max
        )
        return buckets or (self.batch_max,)

    @staticmethod
    def _ewma_key(width: int, mesh: bool, storm: bool = False):
        """Launch-EWMA bucket key: mesh dispatches get their OWN
        buckets — a sharded all-gather-bearing launch costs nothing
        like a single-chip chunk of the same width, and smearing its
        cost into the chunk buckets used to poison the adaptive
        width/cap policy for both paths.  Storm solves likewise get a
        single dedicated bucket (exported as
        ``launch_ewma_ms.storm``): a whole-backlog assignment solve
        is neither a chunk launch nor a mesh flush, and feeding its
        wall time into the chunk buckets would make
        ``_plan_chunk_width``/``_adaptive_cap`` plan chunk flushes
        from solver costs (and vice versa let the solver inherit a
        chunk-launch watchdog budget — the supervisor budgets key by
        stage string, and the storm solve runs under its own
        ``storm_solve`` stage)."""
        if storm:
            return "storm"
        return ("mesh", width) if mesh else width

    def _launch_cost_ms(
        self, width: int, mesh: bool = False, storm: bool = False
    ) -> float:
        """Estimated cost of one ``width``-wide chunk launch (dispatch
        + blocking fetch): the measured EWMA for that bucket, the
        first warm launch observed on this backend for buckets with no
        samples yet, or 50 ms before anything has been measured.
        Mesh launches read (and seed) only mesh buckets; storm
        solves read only theirs."""
        if storm:
            return self._launch_ewma.get("storm", 50.0)
        seed = self._mesh_ewma_seed if mesh else self._launch_ewma_seed
        default = seed if seed is not None else 50.0
        return self._launch_ewma.get(
            self._ewma_key(width, mesh), default
        )

    def _note_launch_cost(
        self, width: int, ms: float, mesh: bool = False,
        storm: bool = False,
    ) -> None:
        """Feed one chunk's measured device-path cost into the
        adaptive sizing loop (and seed the default estimate from the
        first warm measurement).  A sample an order of magnitude past
        the latency budget is a synchronous cold XLA compile billed to
        the launch (NOMAD_TPU_SYNC_COMPILE, or a first donated-variant
        execution), not a launch cost — seeding or averaging it in
        would collapse the cap/width policy to the smallest bucket
        for hundreds of flushes, so it is dropped."""
        ceiling = 20.0 * max(self.latency_budget_ms, 50.0)
        if ms > ceiling:
            return
        if storm:
            # the storm bucket seeds itself and never touches the
            # chunk/mesh seeds: a backlog-wide solve's first warm
            # wall time says nothing about a chunk dispatch
            pass
        elif mesh:
            if self._mesh_ewma_seed is None:
                self._mesh_ewma_seed = ms
        elif self._launch_ewma_seed is None:
            self._launch_ewma_seed = ms
        key = self._ewma_key(width, mesh, storm)
        prev = self._launch_ewma.get(key)
        self._launch_ewma[key] = (
            ms if prev is None else 0.8 * prev + 0.2 * ms
        )

    def _plan_chunk_width(
        self, n_evals: int, backlog: int, mesh: bool = False
    ) -> int:
        """Chunk width for a flush of ``n_evals`` given the backlog.

        Saturated (or latency budget off): the widest bucket — fewer
        dispatches, queueing dominates latency anyway.  Keeping up:
        the smallest bucket covering the flush in one launch (a 1-2
        eval interactive flush must not pay an 8-wide kernel), and for
        bigger flushes the widest bucket UNLESS its measured launch
        cost alone would eat over half the latency budget — then one
        bucket narrower, so the first replay (and the first mid-chain
        admission point) lands after a fraction of the budget instead
        of all of it."""
        buckets = self._chunk_buckets()
        widest = buckets[-1]
        if self.latency_budget_ms <= 0 or backlog >= self.batch_max:
            return widest
        for w in buckets:
            if n_evals <= w:
                return w
        if len(buckets) > 1 and self._launch_cost_ms(
            widest, mesh=mesh
        ) > (self.latency_budget_ms / 2.0):
            return buckets[-2]
        return widest

    def _chunk_width(self, n_evals: int, mesh: bool = False) -> int:
        """Per-flush chunk width (reads the live backlog), exported as
        the ``batch_worker.chunk_width`` gauge.  ``mesh`` flushes plan
        from the mesh launch-cost buckets."""
        try:
            backlog = self.server.broker.ready_count(self.schedulers)
        except Exception:  # noqa: BLE001 — sizing is best-effort
            backlog = self.batch_max
        width = self._plan_chunk_width(n_evals, backlog, mesh=mesh)
        metrics = getattr(self.server, "metrics", None)
        if metrics is not None:
            metrics.set_gauge("batch_worker.chunk_width", width)
        if DECISIONS.enabled and width != self._last_chunk_width:
            self._last_chunk_width = width
            buckets = self._chunk_buckets()
            self._record_decision(
                "chunk_width",
                f"width={width}",
                inputs={
                    "n_evals": n_evals,
                    "backlog": backlog,
                    "budget_ms": self.latency_budget_ms,
                    "launch_cost_ms": round(
                        self._launch_cost_ms(width, mesh=mesh), 3
                    ),
                    "mesh": mesh,
                },
                alternatives=[f"width={w}" for w in buckets],
            )
        return width

    def _adaptive_cap(self) -> int:
        """Batch size for this gulp, from measured latency + backlog.

        Keeping up (backlog < a full batch): pick the LARGEST
        candidate whose estimated last-eval latency — chunk launches
        at that gulp size (the live chunk-width ladder's cost EWMAs)
        plus per-eval replay EWMA x evals ahead — fits the budget; the
        smallest candidate when none does.  Saturated: the full batch
        (queueing dominates latency anyway, amortizing the launch
        maximizes drain rate).  Candidates are the chunk-size buckets
        themselves plus the operator ceiling, so the cap can drop all
        the way to a 2-eval gulp when even one narrow launch barely
        fits the budget."""
        if self.latency_budget_ms <= 0:
            return self.batch_max
        try:
            backlog = self.server.broker.ready_count(self.schedulers)
        except Exception:  # noqa: BLE001 — sizing is best-effort
            return self.batch_max
        if backlog >= self.batch_max:
            return self.batch_max
        # gulp-size candidates, derived from the live chunk-width
        # ladder and never above the operator's configured ceiling
        candidates = sorted(
            set(self._chunk_buckets()) | {self.batch_max}
        )
        cap = candidates[0]
        for c in candidates:
            width = self._plan_chunk_width(c, backlog)
            launches = -(-c // width)
            est = launches * self._launch_cost_ms(width) + min(
                c, backlog + 1
            ) * self._replay_ewma_ms
            if est <= self.latency_budget_ms:
                cap = c
        metrics = getattr(self.server, "metrics", None)
        if metrics is not None:
            metrics.set_gauge("batch_worker.adaptive_cap", cap)
        if DECISIONS.enabled and cap != self._last_adaptive_cap:
            self._last_adaptive_cap = cap
            self._record_decision(
                "adaptive_cap",
                f"cap={cap}",
                inputs={
                    "backlog": backlog,
                    "budget_ms": self.latency_budget_ms,
                    "replay_ewma_ms": round(self._replay_ewma_ms, 3),
                },
                alternatives=[f"cap={c}" for c in candidates],
            )
        return cap

    def _note_dequeue(self, ev: Evaluation) -> None:
        """Stamp an eval's dequeue time for the service-latency
        sample, shedding oldest-first past DEQ_TS_MAX — entries
        normally pop on ack (_sample_eval_latency) or nack
        (_nack_quietly), but an eval that crashes between dequeue and
        either must not leak its stamp forever."""
        import time as _time

        while len(self._deq_ts) >= DEQ_TS_MAX:
            self._deq_ts.pop(next(iter(self._deq_ts)))
        self._deq_ts[ev.id] = _time.monotonic()
        # explain/trace audit: every record of this delivery names the
        # leadership generation it ran under, so a post-failover
        # operator can tell which leader's pipeline produced it
        TRACE.annotate(ev.id, leader_gen=self._leader_gen())

    # -- leadership fence ----------------------------------------------

    def _leader_gen(self) -> int:
        """The server's current leadership generation (0 for bare
        test harnesses that never call establish_leadership)."""
        return getattr(self.server, "_leadership_gen", 0)

    def _count_leadership(self, kind: str) -> None:
        """Leadership-failover counters, exported under the
        `leadership.` namespace on /v1/metrics (the family is
        zero-registered at Server construction from
        LEADERSHIP_COUNTERS)."""
        metrics = getattr(self.server, "metrics", None)
        if metrics is not None:
            metrics.incr(f"leadership.{kind}")

    def _check_leadership(self, gen: int) -> None:
        """The hot path's leadership fence — the exact analogue of the
        ``chain_epoch != self._backend_epoch`` backend fence: a wave,
        chunk chain or storm solve captured ``gen`` when it started,
        and may only commit while the server still holds THAT
        leadership.  Raises NotLeaderError (handled by run(): every
        outstanding lease is nacked for redelivery, nothing commits)
        when leadership was revoked or re-established at a newer
        generation mid-flight."""
        srv = self.server
        if (
            getattr(srv, "_leader_established", True)
            and getattr(srv, "_leadership_gen", gen) == gen
        ):
            return
        self._count_leadership("stale_wave_fenced")
        raise NotLeaderError(None)

    def run(self) -> None:
        import time as _time

        # evals dequeued mid-chain by the admission queue but gated
        # out of the chain: they hold broker leases and must be
        # processed NEXT, before any fresh dequeue, to keep FIFO order
        leftover: List[Tuple[Evaluation, str]] = []
        while not self._stop.is_set() and self._current_generation():
            batch = leftover
            leftover = []
            if not batch:
                if self._paused.is_set():
                    # honor Worker.set_pause (leaders park half their
                    # workers; benches stage backlogs behind it) —
                    # the base run() checked it, this override never
                    # did, making pause a silent no-op for the whole
                    # batch pipeline.  Checked only between gulps: a
                    # leftover batch still holds broker leases and
                    # must finish first.
                    self._stop.wait(0.05)
                    continue
                ev, token = self.server.broker.dequeue(
                    self.schedulers, timeout=0.1
                )
                if ev is None:
                    continue
                self._note_dequeue(ev)
                # storm detection at the gulp boundary: a backlog of
                # pending evals sharing this eval's job family above
                # the trigger threshold is drained atomically and
                # solved as ONE global assignment instead of feeding
                # the per-eval chunk chain
                if self.storm_enabled:
                    storm = self._maybe_drain_storm(ev, token)
                    if storm is not None:
                        try:
                            leftover = self._process_storm(storm)
                        except NotLeaderError:
                            # leadership revoked mid-storm: the solve
                            # result was discarded before decompose,
                            # nothing committed — nack every member
                            # lease for redelivery under the next
                            # leadership.  Expected on failover, so no
                            # error accounting.
                            self._count_leadership("chain_aborts")
                            self._abandon_leases(storm)
                            leftover = []
                        except Exception:  # noqa: BLE001
                            self._count("errors")
                            LOG.exception(
                                "storm processing crashed"
                            )
                            # the members' waterfalls must explain
                            # the detour: they were coalesced into a
                            # storm that never committed, and will
                            # reappear via lease redelivery
                            for s_ev, _tok in storm:
                                TRACE.event(
                                    s_ev.id, "storm.fallback",
                                    reason="storm_crash",
                                )
                            self._abandon_leases(storm)
                            leftover = []
                        continue
                batch = [(ev, token)]
                cap = self._adaptive_cap()
                # ONE fill deadline for the whole gulp: the old
                # per-dequeue timeout waited up to cap x BATCH_WAIT_S
                # on an empty queue, holding a lone interactive eval
                # hostage to batch-fill timeouts.  Anything that
                # arrives after the deadline is picked up mid-chain by
                # the admission queue instead.
                deadline = _time.monotonic() + BATCH_WAIT_S
                while len(batch) < cap:
                    wait = deadline - _time.monotonic()
                    if wait <= 0:
                        break
                    ev, token = self.server.broker.dequeue(
                        self.schedulers, timeout=wait
                    )
                    if ev is None:
                        break
                    self._note_dequeue(ev)
                    batch.append((ev, token))
                # chaos seam: deterministic revoke-during-gulp races
                # (no-op unless a test armed the hook)
                _chaos.fire("gulp_filled")
            for pos, (b_ev, _tok) in enumerate(batch):
                TRACE.event(
                    b_ev.id, "batch_worker.gulp",
                    size=len(batch), pos=pos,
                )
            try:
                leftover = self._process_batch(batch)
            except NotLeaderError:
                # leadership revoked with this gulp in flight: the
                # chain was dropped via its abandon path and no wave
                # member past the fence committed — nack every lease
                # (original gulp, deferred AND admitted) for
                # redelivery under the next leadership
                self._count_leadership("chain_aborts")
                self._abandon_leases(batch)
                leftover = []
            except Exception:  # noqa: BLE001
                # a crash here would silently kill the worker thread and
                # strand every queued eval — log, nack, keep running
                self._count("errors")
                LOG.exception("batch processing crashed")
                self._abandon_leases(batch)
                leftover = []

    def _abandon_leases(
        self, held: List[Tuple[Evaluation, str]]
    ) -> None:
        """Nack every broker lease this worker still holds after an
        aborted gulp/storm: the evals handed in, plus everything the
        admission queue dequeued mid-chain — parked (deferred) or
        already admitted into the dropped chain (_nack_quietly
        tolerates leases the flush managed to ack/nack, and leases a
        leadership revoke already flushed wholesale)."""
        for ev, token in held:
            self._nack_quietly(ev, token)
        deferred, self._deferred = self._deferred, []
        admitted, self._admitted_live = self._admitted_live, []
        for ev, token in deferred + admitted:
            self._nack_quietly(ev, token)

    # ------------------------------------------------------------------

    def _process_batch(
        self, batch: List[Tuple[Evaluation, str]]
    ) -> List[Tuple[Evaluation, str]]:
        """Process the drained evals in queue order, prescoring each
        contiguous run of batchable evals in one chained kernel launch
        so the outcome is exactly what the serial worker loop would
        produce.  Returns the evals the admission queue dequeued
        mid-chain but gated out — the caller must process them as the
        next gulp (before dequeuing anything newer)."""
        run: List[Tuple[Evaluation, str, Job]] = []
        for ev, token in batch:
            job = self.store.job_by_id(ev.namespace, ev.job_id)
            if self._batchable(ev, job):
                run.append((ev, token, job))
                continue
            self._flush_run(run)
            run = []
            self._process_sequential(ev, token)
        # only the batch's FINAL flush may admit mid-chain arrivals: a
        # mid-batch flush has evals of this gulp still queued behind
        # it, and an admitted (newer) eval would commit ahead of them
        self._flush_run(run, admit=True)
        self._export_adaptive_gauges()
        # normal completion: every admitted eval was acked, nacked or
        # deferred inside the flush — the crash ledger is void
        self._admitted_live = []
        deferred, self._deferred = self._deferred, []
        return deferred

    def _flush_run(self, run, admit: bool = False) -> None:
        import time as _time

        from ..sched.policy import resolve as _policy_resolve

        idx = 0
        while idx < len(run):
            snap = self.store.snapshot()
            # global conflict fence for the optimistic replay wave:
            # the ready-node-set generation at wave start (the
            # per-node baseline is captured with the wave below)
            wave_readiness = self.store.readiness_generation()
            # leadership fence: the generation this chain runs under —
            # a revoke (or a newer establish) mid-chain aborts the
            # chain through the same drop path a backend flip uses,
            # and _commit_wave re-checks it before every member commit
            wave_gen = self._leader_gen()
            # simulate the longest prefix we can model in the kernel
            t0 = _time.monotonic()
            sims: List[_Sim] = []
            j = idx
            while j < len(run):
                ev, _token, job = run[j]
                if job is not None and _policy_resolve(job) is not None:
                    # the chunk chain's carry does not model policy
                    # terms; a weighted eval ends the prefix and runs
                    # the single-eval vectorized select (sequential
                    # path -> tpu_stack fuses PolicyTerms).  Storms
                    # stay eligible — build_storm_problem stages
                    # policy rows into the solve itself.
                    if j == idx:
                        self._count_policy("evals")
                    break
                try:
                    with TRACE.span(ev.id, "batch_worker.simulate"):
                        sim = self._simulate(snap, ev, job)
                except Exception:  # noqa: BLE001
                    # a broken simulation falls back to the exact path,
                    # but silently eating it would demote the fast path
                    # to 0% prescore with no signal — count and log
                    self._count("errors")
                    LOG.warning(
                        "simulate failed for eval %s", ev.id,
                        exc_info=True,
                    )
                    sim = None
                if sim is None:
                    break
                sims.append(sim)
                j += 1
            sim_exemplar = run[idx][0].id
            self._observe("simulate", _time.monotonic() - t0, exemplar=sim_exemplar)
            # port/device chain gates: the kernel's occupancy carries
            # are monotone (placements occupy/consume; releases are
            # not modeled) and device pooling is exact only for
            # identical-or-disjoint ask signatures.  An eval whose
            # staged releases hit a port/device asked at-or-after it,
            # or whose device signatures overlap earlier ones without
            # matching, ends the chain — committed state rebuilds the
            # carries exactly for the next chain.
            cut = len(sims)
            table_ = snap.node_table
            any_dev = any(
                cs for s in sims for d in s.asked_devices for cs in d
            )
            key_codes: Dict[tuple, set] = {}
            if any_dev:
                # one scan of the sig interner per flush (not per
                # eval): (vendor, type, name) -> codes
                for code, sig in table_._device_sig_meta.items():
                    key_codes.setdefault(
                        (sig[0], sig[1], sig[2]), set()
                    ).add(code)
            suffix_asks: set = set()
            suffix_dev_codes: set = set()
            for i2 in range(len(sims) - 1, -1, -1):
                s2 = sims[i2]
                own = (
                    set().union(*s2.asked_ports)
                    if s2.asked_ports
                    else set()
                )
                own_dev_sets = {
                    cs
                    for d in s2.asked_devices
                    for cs in d
                }
                own_dev_codes = (
                    set().union(*own_dev_sets)
                    if own_dev_sets
                    else set()
                )
                rel = s2.released_ports
                if rel and rel & own:
                    cut = i2  # its own picks see the stale mask
                elif rel and rel & suffix_asks:
                    cut = i2 + 1  # keep it; later evals re-chain
                if s2.released_device_keys and (
                    own_dev_codes or suffix_dev_codes
                ):
                    rel_codes = set()
                    for key in s2.released_device_keys:
                        rel_codes |= key_codes.get(key, set())
                    if rel_codes & own_dev_codes:
                        cut = min(cut, i2)
                    elif rel_codes & suffix_dev_codes:
                        cut = min(cut, i2 + 1)
                suffix_asks |= own
                suffix_dev_codes |= own_dev_codes
            # forward device gates: pooled free-count accounting is
            # exact only when (a) distinct signatures in one chain are
            # pairwise identical-or-disjoint, (b) every asked code's
            # (vendor, type, name) key is unambiguous (one code — an
            # attr-changed re-registration mints a second code whose
            # key-granularity reservations can't be attributed), and
            # (c) no node carries TWO groups of one signature (the
            # sequential allocator must satisfy a request from a
            # SINGLE group — device.py — so a pooled per-node count
            # would over-admit)
            seen_sets: set = set()
            for i2 in range(min(cut, len(sims))):
                eval_sets = {
                    cs
                    for d in sims[i2].asked_devices
                    for cs in d
                }
                bad = any(
                    cs & other
                    for cs in eval_sets
                    for other in (seen_sets | eval_sets)
                    if other != cs
                )
                if not bad:
                    for cs in eval_sets - seen_sets:
                        keys = {
                            table_.device_sig_key(c) for c in cs
                        }
                        if any(
                            len(key_codes.get(k, ())) > 1
                            for k in keys
                        ):
                            bad = True
                            break
                        for _row, groups in (
                            table_.device_groups.items()
                        ):
                            if (
                                sum(
                                    1
                                    for code, _n in groups
                                    if code in cs
                                )
                                > 1
                            ):
                                bad = True
                                break
                        if bad:
                            break
                if bad:
                    cut = min(cut, i2)
                    break
                seen_sets |= eval_sets
            if cut < len(sims):
                sims = sims[:cut]
                j = idx + cut
            if not sims:
                self._process_sequential(run[idx][0], run[idx][1])
                idx += 1
                continue
            # ---- prescore pipeline: assemble -> launch -> fetch ----
            t0 = _time.monotonic()
            # the backend this chain's inputs are staged for: a
            # supervisor flip mid-chain (probe-driven failover, or a
            # recovery) strands the staged dev_cols/handles on the old
            # backend — they must be dropped, never executed
            chain_epoch = self._backend_epoch
            # adaptive micro-batch width for this flush, from the
            # measured launch EWMAs + live backlog.  On a mesh worker
            # the width plans from the mesh cost buckets — most
            # flushes there take the sharded path, and a mispredicted
            # width for the ones that don't is a heuristic miss, not a
            # correctness issue
            chunk_w = self._chunk_width(
                len(sims), mesh=self._mesh is not None
            )
            asm = None
            try:
                asm = self._guard_device(
                    "assemble",
                    lambda: self._assemble(
                        snap, run[idx:j], sims, chunk=chunk_w
                    ),
                    exemplar=run[idx][0].id,
                )
            except Exception:  # noqa: BLE001
                self._count("errors")
                LOG.warning(
                    "prescore assembly failed for %d evals",
                    len(sims), exc_info=True,
                )
            asm_dt = _time.monotonic() - t0
            self._observe(
                "assemble", asm_dt, exemplar=run[idx][0].id
            )
            # run-wide stage, attributed to every member eval: the
            # `members` attr lets aggregations divide the shared dt
            # back out so trace-derived stage sums match the
            # batch_worker.timings accounting
            for m_ev, _t, _jb in run[idx:j]:
                TRACE.add_span(
                    m_ev.id, "batch_worker.assemble", t0, asm_dt,
                    members=len(sims), ok=asm is not None,
                )
            k = idx
            rescore = False
            # optimistic parallel replay: big-enough runs replay
            # speculatively on the pool as each chunk's rows land
            # (overlapping later fetches), then commit in queue order
            # behind the conflict check (_commit_wave)
            wave = None
            spec_pool = None
            wave_base: Dict[str, int] = {}
            # in-order commit state threaded across the incremental
            # wave drains (job ledger + expected-touch accounting)
            wave_state = {"job_ledger": set(), "expect": {}}
            chain_base: Optional[Dict[str, int]] = None
            if (
                asm is not None
                and self.parallel_replay
                and asm.E_real >= REPLAY_MIN_WAVE
            ):
                wave = deque()
                spec_pool = self._replay_pool_instance()
                # touch-count baseline, captured before any
                # speculation reads (launches haven't fetched yet)
                wave_base = self.store.node_touch_counts()
                chain_base = wave_base
            if asm is not None:
                # chunked double-buffered launches: chunk N executes
                # on device while the host replays chunk N-1's picks,
                # and chunk N+1 chains on N's device-resident carry
                # without a host round trip.  Splitting the eval scan
                # at chunk boundaries is bit-identical to one launch.
                # Each descriptor is (arena, slice start/end, run
                # index of the arena's eval 0) — admitted chunks bring
                # their own arena, chained on the live carry.  Mesh
                # arenas (asm.use_mesh) run the SAME pipeline: the
                # launch dispatches the node-sharded chained runner
                # and the sharded usage carry threads chunk -> chunk
                # on-device (mesh_launch/mesh_fetch stages).
                chunks = [
                    (asm, s, s + asm.chunk, idx)
                    for s in range(0, asm.E, asm.chunk)
                ]
                if asm.use_mesh:
                    metrics = getattr(self.server, "metrics", None)
                    if metrics is not None:
                        metrics.set_gauge(
                            "mesh.chunk_width", asm.chunk
                        )
                # continuous micro-batching: while this chain is in
                # flight, evals the broker receives are admitted as
                # new chunks of the SAME chain — but only when the
                # chain covers the whole remaining gulp (nothing
                # queued behind it to leapfrog), no eval was already
                # deferred this batch, and the chain carries no
                # port/device occupancy (an admitted arena cannot
                # splice into those slot axes)
                admission = None
                chain_jobs: Set[tuple] = set()
                if (
                    admit
                    and self.admit_enabled
                    and j == len(run)
                    and not self._deferred
                    and asm.port_ask is None
                    and asm.dev_ask is None
                ):
                    admission = _AdmissionQueue(self)
                    chain_jobs = {
                        (r_ev.namespace, r_ev.job_id)
                        for r_ev, _t, _jb in run[idx:j]
                    }
                    if chain_base is None:
                        # touch-count baseline for the admission
                        # strict-node gate (the wave captured it
                        # already when parallel replay is on)
                        chain_base = self.store.node_touch_counts()
                pending = deque()
                carry = None
                ci = 0
                stalled = False  # cold shape or launch/fetch failure
                while (ci < len(chunks) or pending) and not rescore:
                    try:
                        self._check_leadership(wave_gen)
                    except NotLeaderError:
                        # leadership left mid-chain: drop the
                        # in-flight launches via the same abandon path
                        # a backend flip uses (the buffers may still
                        # be read by abandoned launches), then
                        # re-raise — run() nacks every lease; NOTHING
                        # of this chain commits, sequential fallback
                        # included
                        LOG.info(
                            "leadership revoked mid-chain; dropping "
                            "%d in-flight chunk(s)", len(pending),
                        )
                        pending.clear()
                        self._mark_mirror_dirty()
                        raise
                    if chain_epoch != self._backend_epoch:
                        # a probe-driven failover (or recovery) flipped
                        # the backend mid-chain: the pending handles
                        # and asm buffers target the OLD backend, and
                        # with the guard now in pass-through a fetch
                        # against a wedged device would block forever.
                        # Drop the in-flight work; the sequential path
                        # covers the rest of the run.
                        LOG.warning(
                            "backend flipped mid-chain; dropping %d "
                            "in-flight chunk(s)", len(pending),
                        )
                        pending.clear()
                        # the dropped launches may still be reading
                        # the usage mirrors on the old backend: the
                        # next sync of each must re-upload, never
                        # donate
                        self._mark_mirror_dirty()
                        stalled = True
                        break
                    while (
                        not stalled
                        and ci < len(chunks)
                        and len(pending) < self.pipeline_depth
                    ):
                        casm, c0, c1, base = chunks[ci]
                        # mesh chunks time/trace/guard under their own
                        # stage names: a sharded dispatch has its own
                        # cost profile AND its own watchdog budget
                        # (the supervisor budgets per stage key)
                        launch_stage = (
                            "mesh_launch" if casm.use_mesh
                            else "launch"
                        )
                        t0 = _time.monotonic()
                        handle = None
                        try:
                            handle = self._guard_device(
                                launch_stage,
                                lambda: self._launch_chunk(
                                    casm, c0, c1, carry,
                                    # first slice of each arena: the
                                    # cold-compile shield keys on the
                                    # launch signature, which is
                                    # identical for that arena's
                                    # later slices
                                    check_ready=c0 == 0,
                                ),
                                exemplar=run[base + c0][0].id,
                            )
                            if handle is None and not (
                                casm.use_mesh and self._mesh is None
                            ):
                                # a mesh arena whose mesh vanished
                                # (failover between assemble and
                                # launch) is not a cold shape — the
                                # failover's own counters tell that
                                # story
                                self._count("cold_shape_fallbacks")
                        except Exception:  # noqa: BLE001
                            self._count("errors")
                            LOG.warning(
                                "prescore launch failed",
                                exc_info=True,
                            )
                        dt = _time.monotonic() - t0
                        self._observe_chunk(
                            launch_stage, run, base, c0,
                            min(c1, casm.E_real), t0, dt,
                            chunk=ci, ok=handle is not None,
                        )
                        if handle is None:
                            stalled = True
                            break
                        carry = handle[2]
                        pending.append((chunks[ci], handle, dt))
                        ci += 1
                        # chaos seam: deterministic revoke-mid-launch
                        # races (no-op unless a test armed the hook)
                        _chaos.fire("chunk_launched")
                    if (
                        admission is not None
                        and not stalled
                        and not rescore
                    ):
                        # poll while the oldest chunk executes on
                        # device; an admitted group becomes the
                        # chain's next chunk(s) and the launch loop
                        # above dispatches it next iteration
                        new_chunks, j = self._admit_into_chain(
                            admission, snap, run, sims, idx, j,
                            chain_jobs, chain_base, wave_readiness,
                            chain_epoch, asm, chunk_w,
                        )
                        if new_chunks:
                            chunks.extend(new_chunks)
                            continue
                    if not pending:
                        break
                    (casm, c0, c1, base), handle, launch_dt = (
                        pending.popleft()
                    )
                    fetch_stage = (
                        "mesh_fetch" if casm.use_mesh else "fetch"
                    )
                    t0 = _time.monotonic()
                    try:
                        rows_arr, pulls_arr = self._guard_device(
                            fetch_stage,
                            lambda: self._fetch(handle),
                            exemplar=run[base + c0][0].id,
                        )
                    except Exception:  # noqa: BLE001
                        self._count("errors")
                        LOG.warning(
                            "prescore fetch failed", exc_info=True
                        )
                        # later chunks chain on this chunk's carry, so
                        # they share its failure: drop them and let the
                        # exact path cover the rest of the run
                        pending.clear()
                        self._mark_mirror_dirty()
                        stalled = True
                        self._observe(
                            fetch_stage, _time.monotonic() - t0
                        )
                        continue
                    dt = _time.monotonic() - t0
                    self._observe_chunk(
                        fetch_stage, run, base, c0,
                        min(c1, casm.E_real), t0, dt,
                    )
                    # feed the adaptive sizing loop: this chunk's
                    # blocking device-path cost (dispatch + the fetch
                    # wait replay overlap didn't hide), keyed by its
                    # width bucket — mesh dispatches into their own
                    # buckets
                    self._note_launch_cost(
                        c1 - c0, (launch_dt + dt) * 1000.0,
                        mesh=casm.use_mesh,
                    )
                    for e in range(c0, min(c1, casm.E_real)):
                        if rescore:
                            break
                        ev, token, job = run[base + e]
                        sim = sims[base + e - idx]
                        rows = [
                            int(r)
                            for r in rows_arr[
                                e - c0, : sim.placements
                            ]
                        ]
                        pulls = [
                            int(p)
                            for p in pulls_arr[
                                e - c0, : sim.placements
                            ]
                        ]
                        if wave is not None:
                            wave.append((
                                ev, token, job, sim, rows, pulls,
                                spec_pool.submit(
                                    self._speculate_one, snap,
                                    wave_readiness, ev, job, sim,
                                    rows, pulls,
                                ),
                            ))
                            continue
                        ok = self._replay_one(
                            ev, token, job, sim, rows, pulls
                        )
                        k += 1
                        if not ok:
                            rescore = True
                    if wave is not None and wave and not rescore:
                        # continuous commit: drain the READY prefix of
                        # the wave in order, so these evals ack now —
                        # not when the (possibly admission-extended)
                        # chain finally ends.  Blocking only happens
                        # in the final drain below.
                        try:
                            k, rescore = self._commit_wave(
                                wave, k, wave_base, wave_readiness,
                                state=wave_state, drain_all=False,
                                leader_gen=wave_gen,
                            )
                        except NotLeaderError:
                            # the fence tripped with launches still in
                            # flight: drop them through the same
                            # abandon path as the loop-top checks (the
                            # abandoned launches may still read the
                            # usage mirrors — the next sync must
                            # re-upload, never donate)
                            pending.clear()
                            self._mark_mirror_dirty()
                            raise
                if pending:
                    # a rescore exit abandoned in-flight launches that
                    # may still read the usage mirrors: the next sync
                    # must re-upload instead of donating the buffers
                    self._mark_mirror_dirty()
                if admission is not None and admission.deferred:
                    # gated-out arrivals: the worker holds their
                    # leases; run() processes them as the next gulp
                    self._deferred.extend(admission.deferred)
            if wave and not rescore:
                # final drain: block on whatever speculations are
                # still running (a rescore above discards the rest —
                # the outer loop re-prescores them on fresh state)
                k, rescore = self._commit_wave(
                    wave, k, wave_base, wave_readiness,
                    state=wave_state, drain_all=True,
                    leader_gen=wave_gen,
                )
            if not rescore:
                # evals no fetched chunk covered (assembly failure,
                # cold shape, launch/fetch error) take the exact
                # sequential path, preserving queue order
                while k < j:
                    ev, token, _job = run[k]
                    self._process_sequential(ev, token)
                    k += 1
            idx = k

    # -- continuous micro-batching (mid-chain admission) ---------------

    def _admission_gates(
        self, snap, ev: Evaluation, job: Optional[Job],
        chain_jobs: Set[tuple], chain_base: Dict[str, int],
        wave_readiness: int, chain_epoch: int,
    ) -> Optional[str]:
        """Serial-equivalence gates for admitting ``ev`` into an
        in-flight chain.  Returns a defer reason, or None when the
        eval would see EXACTLY the state a fresh gulp would: its
        simulation runs against the chain snapshot, so every
        reconciler input it reads there must be provably identical to
        what a fresh snapshot would show — the usage columns evolve
        inside the kernel carry (which models every earlier chain
        member's deltas exactly), and everything the carry does NOT
        model is fenced here, mirroring the optimistic replay wave's
        conflict vocabulary.

        Note what does NOT need a fence: job versions and deployment
        state.  ``StateSnapshot`` is a live delegating view (mutation
        is serialized behind the plan applier), so the admitted
        eval's simulation reads the CURRENT job/deployment — exactly
        what a fresh gulp's simulation would — and drift between
        simulation and replay is caught by the replay's ``set_job``
        deviation, the same way it is for gulped evals."""
        if self._backend_epoch != chain_epoch:
            return "backend_flip"
        if not self._batchable(ev, job):
            return "unbatchable"
        if (ev.namespace, ev.job_id) in chain_jobs:
            # a chain member of the same job is ahead of this eval:
            # its commit changes allocs_by_job, the reconciler's
            # primary input (the broker serializes same-job evals,
            # but an ack mid-chain releases the next one)
            return "job_in_chain"
        if self.store.readiness_generation() != wave_readiness:
            # the ready-node set moved since the chain started: one
            # candidate world per chain is an assumption of the
            # serial-equivalence argument (and of the wave's
            # commit-time readiness fence)
            return "readiness"
        count = self.store.node_touch_count
        for alloc in snap.allocs_by_job(ev.namespace, ev.job_id):
            if count(alloc.node_id) != chain_base.get(
                alloc.node_id, 0
            ):
                # a node hosting this job's allocs was written since
                # the chain baseline (by a chain commit or an external
                # writer): the reconciler/tainted-scan/in-place probes
                # read it as a control-flow input — and in wave mode
                # the commit-time strict-node fence would discard the
                # speculation anyway; defer instead of churning
                return "strict_node"
        return None

    def _admit_into_chain(
        self, admission: _AdmissionQueue, snap, run, sims,
        idx: int, j: int, chain_jobs: Set[tuple],
        chain_base: Dict[str, int], wave_readiness: int,
        chain_epoch: int, asm0: _Assembled, chunk_w: int,
    ) -> Tuple[list, int]:
        """One admission round: poll the broker for evals that arrived
        while the chain is in flight, gate them, simulate the admitted
        prefix against the chain snapshot and assemble it into new
        chunk descriptor(s) chained on the live carry.  Appends
        admitted members to ``run``/``sims`` (keeping the replay
        loop's indexing contract) and returns (new descriptors,
        updated j).  A gate failure defers the eval AND closes the
        queue — FIFO with the chain is absolute."""
        import time as _time

        budget = self.batch_max - (j - idx)
        polled = admission.poll(min(budget, chunk_w))
        if not polled:
            return [], j
        t0 = _time.monotonic()
        admitted: List[Tuple[Evaluation, str, Job]] = []
        adm_sims: List[_Sim] = []
        for ev, token in polled:
            if admission.closed:
                # an earlier poll member was deferred: everything
                # after it defers too (no leapfrogging)
                admission.deferred.append((ev, token))
                self._count_admission("deferred")
                TRACE.event(
                    ev.id, "batch_worker.admit_deferred",
                    reason="queue_closed",
                )
                self._record_decision(
                    "admission_defer", "defer",
                    inputs={"chain_epoch": chain_epoch},
                    outcome="queue_closed", trace_id=ev.id,
                )
                continue
            job = self.store.job_by_id(ev.namespace, ev.job_id)
            reason = self._admission_gates(
                snap, ev, job, chain_jobs, chain_base,
                wave_readiness, chain_epoch,
            )
            sim = None
            if reason is None:
                try:
                    sim = self._simulate(snap, ev, job)
                except Exception:  # noqa: BLE001
                    self._count("errors")
                    LOG.warning(
                        "admission simulate failed for eval %s",
                        ev.id, exc_info=True,
                    )
                if sim is None:
                    reason = "simulate"
                elif sim.asked_ports and any(sim.asked_ports):
                    # the chain's kernel carries no port-slot axis
                    # (admission is disabled on chains that have one)
                    reason = "ports"
                elif any(d for d in sim.asked_devices):
                    reason = "devices"
            if reason is not None:
                admission.defer(ev, token)
                self._count_admission("deferred")
                TRACE.event(
                    ev.id, "batch_worker.admit_deferred",
                    reason=reason,
                )
                self._record_decision(
                    "admission_defer", "defer",
                    inputs={
                        "chain_epoch": chain_epoch,
                        "wave_readiness": wave_readiness,
                        "chunk_w": chunk_w,
                    },
                    outcome=reason, trace_id=ev.id,
                )
                continue
            admitted.append((ev, token, job))
            adm_sims.append(sim)
        if not admitted:
            return [], j
        asm2 = None
        try:
            # same snapshot, same chunk width, same backend path
            # (sharded or not), SAME device-column mirror tuple as
            # the chain head (re-syncing the mirror mid-chain would
            # patch buffers in-flight launches read)
            asm2 = self._assemble(
                snap, admitted, adm_sims, chunk=chunk_w,
                shared_cols=asm0.dev_cols, chain=True,
                mesh=asm0.use_mesh,
            )
        except Exception:  # noqa: BLE001
            self._count("errors")
            LOG.warning(
                "admission assembly failed for %d evals",
                len(admitted), exc_info=True,
            )
        if asm2 is None or (
            asm2.port_ask is not None or asm2.dev_ask is not None
        ) or asm2.use_mesh != asm0.use_mesh:
            # unreachable port/dev arenas are gated per-sim above;
            # defensive — defer the whole admitted group, INSERTED
            # AHEAD of any evals this round already gate-deferred:
            # the admitted group was dequeued first, and the deferred
            # list is replayed as the next gulp in list order, so
            # appending would leapfrog the serial order
            admission.closed = True
            admission.deferred[0:0] = [
                (ev, token) for ev, token, _job in admitted
            ]
            for ev, _token, _job in admitted:
                self._count_admission("deferred")
                TRACE.event(
                    ev.id, "batch_worker.admit_deferred",
                    reason="assembly",
                )
                self._record_decision(
                    "admission_defer", "defer_group",
                    inputs={
                        "chain_epoch": chain_epoch,
                        "group": len(admitted),
                    },
                    outcome="assembly", trace_id=ev.id,
                )
            return [], j
        if not admission.admitted_any:
            # first successful admission into THIS chain
            admission.admitted_any = True
            self._count_admission("chains")
        base = len(run)  # == j: the chain covers the whole gulp
        for (ev, token, job), sim in zip(admitted, adm_sims):
            run.append((ev, token, job))
            sims.append(sim)
            chain_jobs.add((ev.namespace, ev.job_id))
            self._admitted_live.append((ev, token))
        dt = _time.monotonic() - t0
        self._observe("admit", dt, exemplar=admitted[0][0].id)
        for pos, (ev, _token, _job) in enumerate(admitted):
            TRACE.add_span(
                ev.id, "batch_worker.admit", t0, dt,
                chain_pos=base - idx + pos,
                members=len(admitted),
            )
            self._count_admission("admitted")
        descriptors = [
            (asm2, s, s + asm2.chunk, base)
            for s in range(0, asm2.E, asm2.chunk)
        ]
        return descriptors, base + len(admitted)

    # -- global storm solver (NOMAD_TPU_STORM=1) ------------------------

    def _maybe_drain_storm(self, ev, token):
        """Detect a storm at the gulp boundary: when the broker's
        ready prefix continues ``ev``'s job family for at least
        ``storm_min`` members total, drain that prefix atomically
        (never leapfrogging unrelated evals) and return the FIFO
        member list.  None = no storm; nothing was dequeued."""
        from .eval_broker import job_family

        family = job_family(ev)
        if not family[1]:
            return None
        try:
            drained = self.server.broker.drain_family(
                self.schedulers,
                family,
                max_n=self.storm_max - 1,
                min_n=max(0, self.storm_min - 1),
            )
        except Exception:  # noqa: BLE001 — detection is best-effort
            LOG.warning("storm drain failed", exc_info=True)
            return None
        if len(drained) + 1 < self.storm_min:
            return None
        for d_ev, _tok in drained:
            self._note_dequeue(d_ev)
        members = [(ev, token)] + drained
        self._record_decision(
            "storm_trigger", "drain_family",
            inputs={
                "family": f"{family[0]}/{family[1]}",
                "drained": len(members),
                "storm_min": self.storm_min,
                "storm_max": self.storm_max,
            },
            alternatives=["serial_gulp"],
            trace_id=ev.id,
        )
        # settle beats: a storm ARRIVES as a wave (drain loop,
        # restore scan, dispatch burst), so keep absorbing the
        # family prefix while it is still growing — one empty
        # BATCH_WAIT_S beat ends the hunt.  Unrelated evals still
        # fence the walk (drain_family never leapfrogs), so FIFO
        # fairness is untouched, and a complete backlog costs one
        # 5 ms beat — noise next to the solve it feeds.
        import time as _time

        waited = False
        beats = 0
        absorbed = 0
        while len(members) < self.storm_max:
            try:
                more = self.server.broker.drain_family(
                    self.schedulers,
                    family,
                    max_n=self.storm_max - len(members),
                )
            except Exception:  # noqa: BLE001 — growth is optional;
                # the members already leased MUST still be processed
                # (an escape here would kill the worker thread with
                # up to storm_max leases outstanding)
                LOG.warning(
                    "storm settle drain failed", exc_info=True
                )
                break
            if more:
                for d_ev, _tok in more:
                    self._note_dequeue(d_ev)
                members.extend(more)
                absorbed += len(more)
                waited = False
                continue
            if waited:
                break
            _time.sleep(BATCH_WAIT_S)
            waited = True
            beats += 1
        self._record_decision(
            "storm_settle",
            "solve" if len(members) < self.storm_max else "solve_full",
            inputs={
                "members": len(members),
                "beats": beats,
                "absorbed": absorbed,
                "storm_max": self.storm_max,
            },
            alternatives=["keep_waiting"],
            trace_id=ev.id,
        )
        metrics = getattr(self.server, "metrics", None)
        if metrics is not None:
            metrics.set_gauge("storm.backlog", float(len(members)))
        for pos, (s_ev, _tok) in enumerate(members):
            TRACE.event(
                s_ev.id, "batch_worker.storm_gulp",
                size=len(members), pos=pos,
                family=f"{family[0]}/{family[1]}",
            )
        return members

    def _process_storm(
        self, members: List[Tuple[Evaluation, str]]
    ) -> List[Tuple[Evaluation, str]]:
        """Coalesce one family storm into a single global
        (pending-allocs x candidate-nodes) assignment solve, then
        decompose the converged assignment into per-eval prescored
        plans that commit in broker FIFO order through the existing
        ``_commit_wave`` conflict fences.  Any member the solver
        cannot cover — ineligible shape, unassignable row, solve
        failure, or a commit-time conflict cascade — re-enters the
        normal batch path, so zero evals are ever lost and
        correctness never depends on the solver.  Returns leftover
        evals under the ``_process_batch`` contract."""
        import time as _time

        from ..explain import EXPLAIN
        from ..sched.storm import StormMember, build_storm_problem, decompose

        self._count_storm("evals", len(members))
        snap = self.store.snapshot()
        wave_readiness = self.store.readiness_generation()
        wave_base = self.store.node_touch_counts()
        chain_epoch = self._backend_epoch
        # leadership fence: the generation this storm solves under —
        # checked after the solve (the result is discarded BEFORE
        # decompose on a flip) and again before every member commit
        wave_gen = self._leader_gen()

        # simulation pre-pass, FIFO order (the same host mirror of
        # computeJobAllocs the chunk chain runs)
        t0 = _time.monotonic()
        storm_members: List[StormMember] = []
        for ev, token in members:
            job = self.store.job_by_id(ev.namespace, ev.job_id)
            member = StormMember(
                ev=ev, token=token, job=job, leader_gen=wave_gen
            )
            if not self._batchable(ev, job):
                member.reason = "unbatchable"
            else:
                try:
                    with TRACE.span(ev.id, "batch_worker.simulate"):
                        member.sim = self._simulate(snap, ev, job)
                except Exception:  # noqa: BLE001
                    self._count("errors")
                    LOG.warning(
                        "storm simulate failed for eval %s", ev.id,
                        exc_info=True,
                    )
                if member.sim is None:
                    member.reason = "simulate"
            storm_members.append(member)
        dt_sim = _time.monotonic() - t0
        self._observe("simulate", dt_sim, exemplar=members[0][0].id)

        # stage + solve: one device call for the whole backlog
        problem = None
        try:
            problem = build_storm_problem(self, snap, storm_members)
        except Exception:  # noqa: BLE001
            self._count("errors")
            LOG.warning("storm staging failed", exc_info=True)
        out = None
        if problem is not None and problem.n_rows > 0:
            t1 = _time.monotonic()
            try:
                out = self._guard_device(
                    "storm_solve",
                    lambda: self._storm_solve(problem, snap),
                    exemplar=members[0][0].id,
                )
            except Exception:  # noqa: BLE001
                self._count("errors")
                LOG.warning("storm solve failed", exc_info=True)
                # the abandoned solve may still read the usage
                # mirror: the next sync must re-upload, not donate
                self._mark_mirror_dirty()
            dt = _time.monotonic() - t1
            solver_members = [
                m for m in storm_members if m.reason is None
            ]
            self._observe(
                "storm_solve", dt, exemplar=members[0][0].id
            )
            for pos, m in enumerate(solver_members):
                TRACE.add_span(
                    m.ev.id, "batch_worker.storm_solve", t1, dt,
                    chain_pos=pos, members=len(solver_members),
                    rows=problem.n_rows, ok=out is not None,
                )
            # solver wall time feeds its OWN EWMA bucket
            # (launch_ewma_ms.storm) — never the chunk-width buckets
            # the adaptive gulp policy plans flushes from
            self._note_launch_cost(0, dt * 1000.0, storm=True)
            if chain_epoch != self._backend_epoch:
                # a failover flipped the backend mid-solve: the
                # assignment came from (or hung on) the old target
                out = None
                self._mark_mirror_dirty()
        # chaos seam: deterministic revoke-mid-solve races (no-op
        # unless a test armed the hook)
        _chaos.fire("storm_solved")
        # leadership fence: a revoke mid-solve discards the solve
        # result BEFORE decompose — nothing downstream (decompose,
        # speculation, commit) ever sees a deposed leadership's
        # assignment.  run()'s NotLeaderError handler nacks every
        # member lease for redelivery.
        self._check_leadership(wave_gen)
        if problem is not None:
            t2 = _time.monotonic()
            solved_rows = decompose(problem, out)
            dt2 = _time.monotonic() - t2
            self._observe(
                "storm_decompose", dt2, exemplar=members[0][0].id
            )
            if out is not None:
                rounds = int(out[5])
                self._count_storm("solves")
                self._count_storm("rows", solved_rows)
                divergent = sum(
                    m.divergent_rows
                    for m in storm_members
                    if m.rows is not None
                )
                if divergent:
                    self._count_storm("divergent", divergent)
                metrics = getattr(self.server, "metrics", None)
                if metrics is not None:
                    metrics.set_gauge("storm.rounds", float(rounds))
                for m in storm_members:
                    if m.rows is not None:
                        TRACE.add_span(
                            m.ev.id,
                            "batch_worker.storm_decompose",
                            t2, dt2, rows=len(m.rows),
                            round=m.solver_round,
                            divergent=m.divergent_rows,
                        )

        # in-order commit through the existing conflict fences:
        # solved members speculate on the replay pool (or replay
        # their solver rows serially when parallel replay is off);
        # fallback members ride the same wave with rows=None so FIFO
        # order with their solved siblings is preserved
        spec_pool = (
            self._replay_pool_instance()
            if self.parallel_replay
            else None
        )
        wave = deque()
        for m in storm_members:
            if m.rows is not None:
                fut = (
                    spec_pool.submit(
                        self._speculate_one, snap, wave_readiness,
                        m.ev, m.job, m.sim, m.rows, m.pulls,
                    )
                    if spec_pool is not None
                    else _DoneFuture(None)
                )
                wave.append((
                    m.ev, m.token, m.job, m.sim, m.rows, m.pulls,
                    fut,
                ))
            else:
                self._count_storm("fallbacks")
                TRACE.event(
                    m.ev.id, "storm.fallback",
                    reason=m.reason or "solver",
                )
                wave.append((
                    m.ev, m.token, m.job, m.sim, None, None,
                    _DoneFuture(None),
                ))
        wave_state = {"job_ledger": set(), "expect": {}}
        _k, _rescore = self._commit_wave(
            wave, 0, wave_base, wave_readiness,
            state=wave_state, drain_all=True, leader_gen=wave_gen,
        )
        leftover: List[Tuple[Evaluation, str]] = []
        if wave:
            # a mid-wave rescore abandoned the remaining members'
            # speculations; their leases are still held — re-feed
            # them through the normal batch path (chunk chain or
            # sequential), never dropping one.  Solver-placed
            # members in the remainder are DEMOTED (rows cleared)
            # so the explain/trace audit below never tags their
            # eventual chunk-chain placements as solver output, and
            # the fallback counter counts each member once (gated
            # members were already counted at wave build).
            remaining = [
                (r_ev, r_token)
                for (r_ev, r_token, *_rest) in wave
            ]
            remaining_ids = {r_ev.id for r_ev, _rt in remaining}
            demoted = 0
            for m in storm_members:
                if m.ev.id in remaining_ids and m.rows is not None:
                    m.rows = None
                    m.pulls = None
                    demoted += 1
                    TRACE.event(
                        m.ev.id, "storm.fallback",
                        reason="rescore",
                    )
            if demoted:
                self._count_storm("fallbacks", demoted)
            leftover = self._process_batch(remaining)
        # explain-ring audit trail: every committed member whose
        # placements came from the solver carries the solver round,
        # aggregate assignment score and greedy-walk divergence, so
        # `eval explain` shows WHY the global solve differed from
        # the serial walk
        for m in storm_members:
            if m.rows is None:
                continue
            EXPLAIN.annotate(
                m.ev.id,
                Storm={
                    "Round": m.solver_round,
                    "AssignmentScore": round(
                        m.assignment_score, 6
                    ),
                    "DivergentRows": m.divergent_rows,
                    "Rows": len(m.rows),
                    "LeaderGen": m.leader_gen,
                },
            )
            TRACE.annotate(
                m.ev.id, outcome_detail="storm",
                storm_round=m.solver_round,
            )
        self._export_adaptive_gauges()
        return leftover

    def _storm_solve(self, problem, snap):
        """Dispatch one storm assignment solve against the
        device-resident usage mirror and realize the outputs.  The
        jitted solve (ops/solve.py) runs the score matrix build and
        the auction ``while_loop`` entirely on device; shapes are
        pow2-bucketed by the problem builder so traces stay cached
        across storms.  ``snap`` is the SAME snapshot the problem
        was staged against — the solve's arena row indices are only
        meaningful against that table.

        On a mesh worker the solve runs NODE-SHARDED over the same
        mesh (and the same sharded usage mirror) as the chunk chain:
        each device scores and auctions its own node shard, and the
        assignment is bit-identical to the single-device solve — on a
        multi-host mesh this is the path that solves pod-wide storms
        no single chip's HBM could hold."""
        import jax

        from ..ops.solve import storm_assignment

        table = snap.node_table
        max_rounds = problem.max_rounds
        if self.storm_rounds > 0:
            max_rounds = min(max_rounds, self.storm_rounds)
        mesh = self._mesh
        if (
            mesh is not None
            and table.capacity % mesh.devices.size == 0
        ):
            from ..ops.solve import storm_assignment_sharded
            from ..sched.storm import stage_for_mesh

            cols = self._device_columns(table, sharded=True)
            fn = storm_assignment_sharded(
                mesh,
                spread_fit=problem.spread_fit,
                max_rounds=max_rounds,
                weighted=problem.inputs.policy_tput_term is not None,
            )
            if self._pod is not None:
                # pod head: the storm inputs are plain host numpy —
                # peers stage them against the mesh themselves and
                # solve over their own mirror shards (synced by the
                # _device_columns call above, which streamed first)
                self._pod.send(
                    "storm",
                    tuple(problem.inputs),
                    problem.spread_fit,
                    max_rounds,
                )
            inp = stage_for_mesh(problem.inputs, mesh)
            out = fn(inp, cols)
            # replicated outputs: every process holds the full
            # answer — no cross-host fetch
            host_out = tuple(np.asarray(x) for x in out)
            if self._pod is not None and self._pod.check:
                from ..parallel.pod import result_digest

                self._pod.check_results(result_digest(*host_out))
            return host_out
        cols = self._device_columns(table)
        out = storm_assignment(
            problem.inputs, cols,
            spread_fit=problem.spread_fit,
            max_rounds=max_rounds,
        )
        return tuple(np.asarray(x) for x in jax.device_get(out))

    def _replay_one(
        self, ev, token, job, sim: _Sim,
        rows: List[int], pulls: Optional[List[int]],
    ) -> bool:
        """Replay one prescored eval; returns False when the chained
        state past it is suspect (failed pick, deviation, or replay
        error) and the caller must re-prescore the remainder."""
        import time as _time

        # None = unknown writes until a clean prescored replay records
        # its committed plan's touches (the wave commit loop reads it)
        self._last_replay_touches = None
        if rows is None:
            # storm wave member the solver could not cover: the full
            # sequential path owns it.  True (not the chain's
            # "suspect" False): storm rows are computed from the
            # baseline + the solver's capacity model, not a
            # sequential carry, so a fallback commit does not
            # invalidate later members' rows — their own conflict
            # fences see this commit's writes as unexpected touches
            # and serialize exactly the members it actually affected.
            self._process_sequential(ev, token)
            return True
        t0 = _time.monotonic()
        try:
            clean = self._process_prescored(
                ev, token, job, rows, sim, pulls=pulls
            )
            replay_dt = _time.monotonic() - t0
            self._observe("replay", replay_dt, exemplar=ev.id)
            TRACE.add_span(
                ev.id, "batch_worker.replay", t0, replay_dt,
                mode="serial", clean=clean,
            )
            self._replay_ewma_ms = (
                0.8 * self._replay_ewma_ms
                + 0.2 * replay_dt * 1000.0
            )
            self._count("prescored")
            self._sample_eval_latency(ev)
            EXPLAIN.annotate(ev.id, LeaderGen=self._leader_gen())
            # a failed prescored pick means the chained state past
            # this eval is suspect — re-prescore
            return clean
        except _Deviation as dev:
            self._count("fallbacks")
            TRACE.event(
                ev.id, "batch_worker.fallback",
                reason="deviation", detail=str(dev),
            )
            self._process_sequential(ev, token)
            return False
        except Exception:  # noqa: BLE001
            self._count("errors")
            LOG.warning(
                "prescored replay failed for eval %s", ev.id,
                exc_info=True,
            )
            TRACE.event(
                ev.id, "batch_worker.fallback", reason="error"
            )
            self._nack_quietly(ev, token)
            return False

    # -- optimistic parallel replay ------------------------------------

    def _speculate_one(
        self, snap, wave_readiness: int, ev, job, sim: _Sim,
        rows: List[int], pulls: Optional[List[int]],
    ) -> Optional[_Speculation]:
        """Phase A (pool thread): replay one prescored eval against
        the shared wave snapshot with every side effect captured
        instead of applied.  Returns None when the eval must replay
        serially — unsupported shape (active deployment, CSI
        volumes), a deviation, or any error."""
        try:
            # span runs on the pool thread, so the trace records WHICH
            # replay-spec thread carried this eval (straggler
            # attribution across the wave)
            with TRACE.span(
                ev.id, "replay.speculate", speculative=True
            ):
                return self._speculate_inner(
                    snap, wave_readiness, ev, job, sim, rows, pulls
                )
        except (_Deviation, _SpecAbort) as exc:
            TRACE.event(
                ev.id, "replay.serial_required",
                reason="deviation", detail=str(exc),
            )
            return None
        except Exception:  # noqa: BLE001 — the serial path recovers
            LOG.debug(
                "speculative replay failed for eval %s", ev.id,
                exc_info=True,
            )
            TRACE.event(
                ev.id, "replay.serial_required", reason="error"
            )
            return None

    def _speculate_inner(
        self, snap, wave_readiness: int, ev, job, sim: _Sim,
        rows: List[int], pulls: Optional[List[int]],
    ) -> Optional[_Speculation]:
        batch = ev.type == "batch"
        if not batch and snap.latest_deployment_by_job(
            ev.namespace, ev.job_id
        ) is not None:
            # deployment state is written by the watcher thread —
            # a read the per-node conflict ledger can't cover
            TRACE.event(
                ev.id, "replay.serial_required", reason="deployment"
            )
            return None
        for tg in job.task_groups:
            for req in tg.volumes.values():
                if req.type == "csi":
                    # claim races linearize at the applier; the
                    # serial path owns them
                    TRACE.event(
                        ev.id, "replay.serial_required", reason="csi"
                    )
                    return None
        if self.store.readiness_generation() != wave_readiness:
            TRACE.event(
                ev.id, "replay.serial_required", reason="readiness"
            )
            return None
        # strict read set: nodes hosting the job's allocs — the
        # reconciler, tainted-node scan and in-place update probes
        # read them as real control-flow inputs, so any touch
        # (even an own-wave commit) invalidates the speculation
        strict_nodes = {
            a.node_id
            for a in snap.allocs_by_job(ev.namespace, ev.job_id)
        }
        # non-node fences, captured BEFORE the replay reads them:
        # a job/config/deployment write between here and the
        # commit check makes the commit check disagree and
        # conflict; one between here and the replay's own read
        # makes set_job deviate.  Either way the serial path wins.
        job_now = snap.job_by_id(ev.namespace, ev.job_id)
        job_fence = (
            getattr(job_now, "version", -1),
            getattr(job_now, "modify_index", -1),
        )
        config_index = self.store.table_index("scheduler_config")
        # the broker's eval object must not see speculative writes
        spec_ev = _dc_replace(ev)
        spec_ev.snapshot_index = snap.index
        planner = _SpecPlanner(snap)
        scheduler, made = self._prescored_scheduler(
            snap, planner, spec_ev, job, rows, sim, pulls,
            speculative=True,
        )
        scheduler.process(spec_ev)
        return _Speculation(
            explain=EXPLAIN.build_record(spec_ev, scheduler),
            ops=planner.ops,
            strict_nodes=strict_nodes,
            # relaxed read set: the plan-touched nodes — their
            # reads (winner verification, plan evaluation) check
            # fit the kernel chain already modeled for every
            # earlier chain member, so own-wave touches there are
            # expected, not conflicts
            plan_nodes=set(planner.touched),
            clean=not (made and made[0].saw_failed_row),
            job_fence=job_fence,
            config_index=config_index,
            check_deployment=not batch,
        )

    @staticmethod
    def _merge_touches(
        expect: Dict[str, int], touches: Dict[str, int]
    ) -> None:
        for node_id, count in touches.items():
            expect[node_id] = expect.get(node_id, 0) + count

    @staticmethod
    def _plan_touches(node_update, node_allocation,
                      node_preemptions) -> Dict[str, int]:
        """node_id -> how many alloc writes committing these plan
        collections performs (each alloc upsert bumps its node's
        touch count once — store._upsert_allocs_locked)."""
        touches: Dict[str, int] = {}
        for coll in (node_update, node_allocation, node_preemptions):
            for node_id, allocs in coll.items():
                touches[node_id] = touches.get(node_id, 0) + len(
                    allocs
                )
        return touches

    def _commit_wave(
        self, wave, k: int, wave_base: Dict[str, int],
        wave_readiness: int, state: Optional[dict] = None,
        drain_all: bool = True, leader_gen: Optional[int] = None,
    ) -> Tuple[int, bool]:
        """Phase B: walk the wave in queue order, committing each
        eval's speculation when its read set survived every
        earlier-committed plan (and external writers), and
        re-replaying it serially otherwise.  ``wave_base`` is the
        per-node touch-count baseline captured before any speculation
        read; ``wave_expect`` accumulates the touches the wave's own
        commits perform, so kernel-modeled self-conflicts don't
        demote the whole wave.  Returns (next unhandled run index,
        rescore); rescore=True means a replay marked the chained
        state suspect — exactly the serial loop's contract, so the
        caller re-prescores the remainder and the discarded
        speculations past it are never applied.

        ``wave`` is a deque consumed from the front.  With
        ``drain_all=False`` the walk stops at the first member whose
        speculation is still running — the continuous micro-batching
        loop drains the READY prefix after every chunk fetch, so an
        eval's ack lands one chunk after its rows do instead of at
        the end of the (possibly admission-extended) chain.
        ``state`` carries the in-order commit's job ledger and
        expected-touch accounting across those incremental drains."""
        import time as _time

        if state is None:
            state = {"job_ledger": set(), "expect": {}}
        job_ledger: Set[tuple] = state["job_ledger"]
        wave_expect: Dict[str, int] = state["expect"]
        rescore = False
        while wave:
            # chaos seam: deterministic revoke between speculation and
            # commit (no-op unless a test armed the hook)
            _chaos.fire("pre_commit_wave")
            if leader_gen is not None:
                # the leadership fence, checked before EVERY member
                # commit exactly where the backend epoch would be: a
                # deposed leader's speculations are discarded, their
                # leases nacked by run()'s NotLeaderError handler,
                # and the remaining wave members' leases with them
                self._check_leadership(leader_gen)
            fut = wave[0][6]
            if not drain_all and not fut.done():
                break
            ev, token, job, sim, rows, pulls, fut = wave.popleft()
            t0 = _time.monotonic()
            try:
                spec = fut.result()
            except Exception:  # noqa: BLE001 — speculation-only work
                spec = None
            # the in-order commit's serialization wait: time this eval
            # spent parked behind earlier wave members (plus any
            # remainder of its own speculation)
            wait_dt = _time.monotonic() - t0
            TRACE.add_span(
                ev.id, "replay.commit_wait", t0, wait_dt,
                speculated=spec is not None,
            )
            ok: Optional[bool] = None
            committed = False
            if spec is not None:
                t_c = _time.monotonic()
                try:
                    ok = self._commit_speculation(
                        spec, ev, token, wave_base, wave_expect,
                        wave_readiness, job_ledger,
                        leader_gen=leader_gen,
                    )
                    committed = ok is not None
                except NotLeaderError:
                    # the plan applier (or the replicated FSM fence)
                    # rejected the commit: leadership is gone — nack
                    # this lease and abort the whole wave; run()'s
                    # handler nacks the rest
                    self._nack_quietly(ev, token)
                    raise
                except Exception:  # noqa: BLE001
                    self._count("errors")
                    LOG.warning(
                        "speculative commit failed for eval %s",
                        ev.id, exc_info=True,
                    )
                    self._nack_quietly(ev, token)
                    job_ledger.add((ev.namespace, ev.job_id))
                    ok = False  # chain past this eval is suspect
                if committed:
                    TRACE.add_span(
                        ev.id, "replay.commit", t_c,
                        _time.monotonic() - t_c, clean=bool(ok),
                    )
            if committed:
                dt = _time.monotonic() - t0
                self._observe("replay", dt, exemplar=ev.id)
                self._replay_ewma_ms = (
                    0.8 * self._replay_ewma_ms + 0.2 * dt * 1000.0
                )
            if ok is None:
                # not speculated, or the speculation lost its race:
                # replay serially against the updated state (the
                # serial loop's own snapshot/fallback semantics)
                if spec is not None:
                    self._count_replay("conflicts")
                self._count_replay("serial_fallbacks")
                TRACE.event(
                    ev.id, "replay.serial_fallback",
                    reason=(
                        "conflict" if spec is not None
                        else "unspeculated"
                    ),
                )
                job_ledger.add((ev.namespace, ev.job_id))
                ok = self._replay_one(ev, token, job, sim, rows, pulls)
                # whitelist the serial commit's touches for later
                # relaxed checks; None (unknown writes: deviation or
                # error paths) leaves them unexpected, so overlapping
                # later evals conflict — conservative
                if self._last_replay_touches is not None:
                    self._merge_touches(
                        wave_expect, self._last_replay_touches
                    )
            k += 1
            if not ok:
                rescore = True
                break
        return k, rescore

    def _commit_speculation(
        self, spec: _Speculation, ev, token,
        wave_base: Dict[str, int], wave_expect: Dict[str, int],
        wave_readiness: int, job_ledger: Set[tuple],
        leader_gen: Optional[int] = None,
    ) -> Optional[bool]:
        """Commit one speculative replay: conflict check, then replay
        the captured transcript verbatim through the real planner
        surface.  Returns the `_replay_one`-style ok flag, or None
        when the speculation conflicts and must be discarded."""
        key = (ev.namespace, ev.job_id)
        if key in job_ledger:
            # an earlier wave member of the SAME job committed: its
            # allocs/evals are reads this reconciler pass depended on
            TRACE.event(
                ev.id, "replay.conflict", fence="job_ledger"
            )
            return None
        if self.store.readiness_generation() != wave_readiness:
            # the ready-node set moved: candidate scans (and the
            # nodes_available placement metrics) are stale
            TRACE.event(
                ev.id, "replay.conflict", fence="readiness"
            )
            return None
        # per-node conflict check against the touch-count ledger:
        # strict nodes accept NO touch past the baseline; plan nodes
        # accept exactly the touches this wave's own commits account
        # for (kernel-modeled), so only external writes conflict
        count = self.store.node_touch_count
        for node_id in spec.strict_nodes:
            if count(node_id) != wave_base.get(node_id, 0):
                TRACE.event(
                    ev.id, "replay.conflict",
                    fence="strict_node", node=node_id,
                )
                return None
        for node_id in spec.plan_nodes:
            expected = wave_base.get(node_id, 0) + (
                0
                if self.replay_strict
                else wave_expect.get(node_id, 0)
            )
            if count(node_id) != expected:
                TRACE.event(
                    ev.id, "replay.conflict",
                    fence="plan_node", node=node_id,
                )
                return None
        # non-node fences (reads the per-node ledger can't cover)
        job_now = self.store.job_by_id(ev.namespace, ev.job_id)
        if (
            getattr(job_now, "version", -1),
            getattr(job_now, "modify_index", -1),
        ) != spec.job_fence:
            TRACE.event(
                ev.id, "replay.conflict", fence="job_version"
            )
            return None
        if (
            self.store.table_index("scheduler_config")
            != spec.config_index
        ):
            TRACE.event(
                ev.id, "replay.conflict", fence="scheduler_config"
            )
            return None
        if spec.check_deployment and (
            self.store.latest_deployment_by_job(
                ev.namespace, ev.job_id
            )
            is not None
        ):
            TRACE.event(
                ev.id, "replay.conflict", fence="deployment"
            )
            return None
        if leader_gen is not None:
            # last host-side leadership fence before any captured op
            # is applied (the replicated FSM fence backstops the
            # check-to-apply window on a cluster server)
            self._check_leadership(leader_gen)
        commit_index = self.store.latest_index()
        # the serial loop stamps each replay's fresh snapshot index on
        # the eval's status writes; the commit point is that replay's
        # moment in the serial order
        ev.snapshot_index = commit_index
        # plan submits apply FIRST (a transcript holds at most one —
        # process() runs a single pass in speculation): if the applier
        # partially commits despite the conflict check (external race
        # between check and apply), NO other captured op has been
        # applied yet, so the sequential recovery below re-runs the
        # eval without duplicating blocked/follow-up evals.  Eval
        # writes that preceded the submit in capture order land after
        # it instead — safe, because BlockedEvals.block's
        # missed-unblock check requeues a late-registered blocked
        # eval past any capacity change our own commit triggered.
        ordered = sorted(
            spec.ops, key=lambda op: 0 if op[0] == "submit" else 1
        )
        for op, payload in ordered:
            if op == "submit":
                if leader_gen is not None:
                    # stamp the WAVE's captured generation, not the
                    # submit-time one: a straggler thread committing
                    # after this server was re-elected must carry the
                    # deposed generation so the replicated FSM fence
                    # rejects it (propose-time stamping would launder
                    # the stale plan under the new term)
                    payload.leader_gen = leader_gen
                result, refreshed = self.submit_plan(payload)
                if refreshed is not None or not result.is_full_commit(
                    payload
                ):
                    # the conflict guard missed a race (external
                    # writer between check and apply): the plan
                    # partially committed, so the captured transcript
                    # past this point is invalid.  Recover like the
                    # serial partial-commit path — the real scheduler
                    # on refreshed state sees the committed subset and
                    # finishes the eval — and mark the chain suspect.
                    LOG.warning(
                        "speculative commit for eval %s was partial;"
                        " recovering via the sequential path", ev.id,
                    )
                    self._count_replay("serial_fallbacks")
                    TRACE.event(
                        ev.id, "replay.serial_fallback",
                        reason="partial_commit",
                    )
                    job_ledger.add(key)
                    self._process_sequential(ev, token)
                    return False
                # a full commit wrote exactly the plan's collections:
                # record those touches as expected for later relaxed
                # conflict checks in this wave
                self._merge_touches(
                    wave_expect,
                    self._plan_touches(
                        payload.node_update,
                        payload.node_allocation,
                        payload.node_preemptions,
                    ),
                )
            else:
                if getattr(payload, "id", None) == ev.id:
                    payload.snapshot_index = commit_index
                if op == "update_eval":
                    self.update_eval(payload)
                elif op == "create_eval":
                    self.create_eval(payload)
                else:
                    self.reblock_eval(payload)
        job_ledger.add(key)
        self.evals_processed += 1
        TRACE.annotate(ev.id, outcome="speculative")
        EXPLAIN.publish(
            spec.explain, getattr(self.server, "metrics", None)
        )
        if leader_gen is not None:
            # the published explanation names the leadership
            # generation whose wave committed it (failover forensics:
            # "which leader placed this?")
            EXPLAIN.annotate(ev.id, LeaderGen=leader_gen)
        self.server.broker.ack(ev.id, token)
        self._count("prescored")
        self._count_replay("speculative")
        self._sample_eval_latency(ev)
        return spec.clean

    def _process_sequential(self, ev, token) -> None:
        import time as _time

        # set before processing: process_eval acks (finishing the
        # trace) inside, and the annotated outcome must be there first
        TRACE.annotate(ev.id, outcome="sequential")
        t0 = _time.monotonic()
        try:
            self.process_eval(ev, token)
        except Exception:  # noqa: BLE001
            self._nack_quietly(ev, token)
        dt = _time.monotonic() - t0
        self._observe("sequential", dt, exemplar=ev.id)
        TRACE.add_span(ev.id, "batch_worker.sequential", t0, dt)
        self._sample_eval_latency(ev)
        # failover forensics: every explain record names the
        # leadership generation whose pipeline produced it
        EXPLAIN.annotate(ev.id, LeaderGen=self._leader_gen())

    def _nack_quietly(self, ev, token) -> None:
        self._deq_ts.pop(ev.id, None)
        try:
            self.server.broker.nack(ev.id, token)
        except ValueError:
            pass

    # ------------------------------------------------------------------

    def _batchable(self, ev: Evaluation, job: Optional[Job]) -> bool:
        if job is None or job.stopped():
            return False
        if ev.type not in ("service", "batch"):
            return False
        # multi-task-group jobs run in-kernel in full (r5): per-pick
        # group routing (TGInputs), distinct_hosts in both scopes
        # (occ_extra + dh_tg), and GROUP-scoped spread slots routed by
        # SpreadInputs.group
        for tg in job.task_groups:
            # both spread modes run in-kernel: percent targets via the
            # desired/used carry, even mode (no targets) via min/max
            # over the observed use map (ops/batch.py even_full)
            # host-mode DYNAMIC-port asks are batchable: binpack never
            # skips a node for a dynamic-only ask (the per-node range
            # is thousands of ports), so the sequential walk window is
            # port-independent and the kernel's port-blind scoring
            # stays bit-identical; the winner's exact BinPack
            # verification (PrescoredStack.select) still assigns the
            # real ports.
            # Reserved/static ports run in-kernel as a walk-slot-
            # neutral collision mask (ops/batch.py PortInputs): a
            # port-collided node is skipped by binpack WITHOUT
            # consuming a limit slot (rank.py continue) — identical
            # to infeasibility in the walk arithmetic.  Exceptions
            # that stay sequential: static asks INSIDE the dynamic
            # range (an in-chain dynamic assignment could collide
            # invisibly, and a non-winner divergence would shift the
            # walk window past what winner verification can catch)
            # and port releases intersecting asked ports (gated in
            # _flush_run).  Non-host modes gate on NetworkChecker
            # feasibility the kernel doesn't model.
            from ..structs.network import MIN_DYNAMIC_PORT

            for nw in list(tg.networks) + [
                n for t in tg.tasks for n in t.resources.networks
            ]:
                if (nw.mode or "host") != "host":
                    return False
                for p in nw.reserved_ports:
                    if p.value >= MIN_DYNAMIC_PORT:
                        return False
            # device asks run in-kernel: capacity-count masks over a
            # chained free-instance carry (ops/batch.py DeviceInputs);
            # overlapping ask signatures and instance releases gate
            # per-batch in _flush_run.  Device AFFINITIES run
            # in-kernel too (r5): under the chain gates each node has
            # at most ONE group matching an ask, so the allocator's
            # match fraction (rank.go:460) is a STATIC per-node score
            # column (_device_affinity_column)
            for t in tg.tasks:
                for req in t.resources.devices:
                    # count<=0 is rejected by the sequential
                    # allocator on every node (device.py invalid
                    # request) — the kernel would treat it as
                    # trivially satisfiable and deviate every time
                    if req.count <= 0:
                        return False
            # distinct_hosts IS batchable for single-TG jobs: the
            # kernel's collision carry equals the proposed-allocs-
            # per-node count, so the mask is exact
            if tg.ephemeral_disk.sticky:
                return False
        return True

    # ------------------------------------------------------------------

    def _simulate(self, snap, ev: Evaluation,
                  job: Job) -> Optional[_Sim]:
        """Host-side mirror of computeJobAllocs up to (not including)
        the select calls (reference generic_sched.go:332): runs the
        real reconciler on the prescore snapshot and extracts the plan
        mutations the kernel must model.  Returns None when the eval's
        shape cannot be prescored."""
        from ..sched.context import EvalContext
        from ..sched.reconcile import AllocReconciler
        from ..sched.util import (
            generic_alloc_update_fn,
            tainted_nodes,
            update_non_terminal_allocs_to_lost,
        )

        batch = ev.type == "batch"
        plan = ev.make_plan(job)
        deployment = None
        if not batch:
            deployment = snap.latest_deployment_by_job(
                ev.namespace, ev.job_id
            )
        ctx = EvalContext(snap, plan, seed=self.seed)
        stack = GenericStack(batch, ctx)
        stack.set_job(job)

        allocs = snap.allocs_by_job(ev.namespace, ev.job_id)
        tainted = tainted_nodes(snap, allocs)
        update_non_terminal_allocs_to_lost(plan, tainted, allocs)

        reconciler = AllocReconciler(
            generic_alloc_update_fn(ctx, stack, ev.id),
            batch,
            ev.job_id,
            job,
            deployment,
            allocs,
            tainted,
            ev.id,
        )
        results = reconciler.compute()
        for stop in results.stop:
            plan.append_stopped_alloc(
                stop.alloc, stop.status_description, stop.client_status
            )

        sim = _Sim(placements=0)
        table = snap.node_table

        # spread propertyset bookkeeping, GROUP-scoped like the
        # sequential SpreadIterator (propertyset.py:151 filters each
        # pset to one task group; job-level stanzas get one pset PER
        # group).  State is keyed (group, attribute); single-group
        # jobs collapse to the historical shape.
        for g in job.task_groups:
            g_spreads = list(g.spreads) + list(job.spreads)
            if not g_spreads:
                continue
            # existing = the job's live allocs of THIS group per
            # attribute value; cleared = staged stops (terminal ones
            # included, matching _filter(stopping,
            # filter_terminal=False)); proposed = in-place/attribute
            # updates entering plan.node_allocation before any select
            # (generic_sched.py:287-294)
            live = [
                a
                for a in allocs
                if not a.terminal_status()
                and a.task_group == g.name
            ]
            stopping = [
                a
                for stops in plan.node_update.values()
                for a in stops
                if a.task_group == g.name
            ]
            staged = [
                a
                for a in list(results.inplace_update)
                + list(results.attribute_updates.values())
                if a.task_group == g.name
                and not a.terminal_status()
            ]
            for sp in g_spreads:
                key = (g.name, sp.attribute)
                sim.spread_existing[key] = _count_values(
                    snap, sp.attribute, live
                )
                sim.spread_cleared[key] = _count_values(
                    snap, sp.attribute, stopping
                )
                sim.spread_proposed[key] = _count_values(
                    snap, sp.attribute, staged
                )
            # even-mode guard: the oracle's min/max loop reproduces the
            # reference's zero-reset idiom (spread.py:162 "if min_count
            # == 0 or v < min_count"), whose result depends on map
            # iteration order once a use-map value sits at count 0.
            # That only happens when cleared zeroes a present value —
            # so evals whose even stanzas start with a zeroed value, or
            # that stage destructive evictions (cleared can grow
            # mid-chain), take the exact sequential path.
            from ..sched.spread import compute_spread_info as _csi

            infos, _w = _csi(g_spreads, g.count)
            has_even = any(
                not infos[sp.attribute]["desired_counts"]
                for sp in g_spreads
            )
            if has_even:
                if results.destructive_update:
                    return None
                for sp in g_spreads:
                    if infos[sp.attribute]["desired_counts"]:
                        continue
                    key = (g.name, sp.attribute)
                    ex = sim.spread_existing[key]
                    pr = sim.spread_proposed[key]
                    cl = sim.spread_cleared[key]
                    for value in set(ex) | set(pr):
                        raw = ex.get(value, 0) + pr.get(value, 0)
                        if raw > 0 and raw - cl.get(value, 0) <= 0:
                            return None

        def add_pre(node_id: str, c: float, m: float, d: float) -> None:
            row = table.row_of.get(node_id)
            if row is None:
                return
            acc = sim.pre.setdefault(row, [0.0, 0.0, 0.0])
            acc[0] += c
            acc[1] += m
            acc[2] += d

        evicted_ids = set()
        for node_id, stops in plan.node_update.items():
            for a in stops:
                if a.id in evicted_ids:
                    continue
                evicted_ids.add(a.id)
                orig = snap.alloc_by_id(a.id)
                if orig is None or orig.terminal_status():
                    continue  # not counted in usage columns
                r = orig.comparable_resources()
                add_pre(node_id, -r.cpu, -r.memory_mb, -r.disk_mb)

        for update in list(results.inplace_update) + list(
            results.attribute_updates.values()
        ):
            orig = snap.alloc_by_id(update.id)
            if orig is None or orig.terminal_status():
                continue
            old = orig.comparable_resources()
            new = update.comparable_resources()
            add_pre(
                update.node_id,
                new.cpu - old.cpu,
                new.memory_mb - old.memory_mb,
                new.disk_mb - old.disk_mb,
            )

        if len(sim.pre) > MAX_PRE_ROWS:
            return None

        placements = list(results.destructive_update) + list(
            results.place
        )
        # ordered distinct groups this eval places (pick k routes to
        # group slot pick_tg[k] in the kernel)
        tg_slot: Dict[str, int] = {}
        for missing in placements:
            name = missing.task_group.name
            if name not in tg_slot:
                tg_slot[name] = len(sim.tgs)
                sim.tgs.append(missing.task_group)
            sim.pick_tg.append(tg_slot[name])

        # anti-affinity base: proposed same-job+group allocs per node
        # at pre-placement time (rank.go:474 collision count), one row
        # per group slot
        coll = np.zeros(
            (max(1, len(sim.tgs)), table.capacity), dtype=np.int32
        )
        occ_extra = np.zeros(table.capacity, dtype=np.int32)
        for a in allocs:
            if a.terminal_status() or a.id in evicted_ids:
                continue
            if a.job_id != job.id:
                continue
            slot = tg_slot.get(a.task_group)
            row = table.row_of.get(a.node_id)
            if row is None:
                continue
            if slot is not None:
                coll[slot, row] += 1
            else:
                # a group placing nothing this eval: its allocs still
                # occupy the node for distinct_hosts (the sequential
                # DistinctHostsIterator counts ALL proposed job
                # allocs, feasible.go:470)
                occ_extra[row] += 1
        sim.base_collisions = coll
        # ship the extra occupancy ONLY when a job-level
        # distinct_hosts will read it: ordinary multi-TG scale-ups
        # must not mint a new launch-shape variant (cold compile ->
        # whole-batch sequential fallback) for an input the kernel
        # would ignore
        job_level_dh = any(
            c.operand == CONSTRAINT_DISTINCT_HOSTS
            for c in job.constraints
        )
        sim.occ_extra = (
            occ_extra
            if job_level_dh and occ_extra.any()
            else None
        )

        for missing in placements:
            p_tg = missing.task_group
            prev = missing.previous_alloc
            if prev is not None and p_tg.ephemeral_disk.sticky:
                return None  # preferred-node path

            stop_prev, _desc = missing.stop_previous_alloc()
            e_row, e_res, e_coll = -1, (0.0, 0.0, 0.0), 0
            if stop_prev and prev is not None and (
                prev.id not in evicted_ids
            ):
                evicted_ids.add(prev.id)
                orig = snap.alloc_by_id(prev.id)
                if orig is not None and not orig.terminal_status():
                    row = table.row_of.get(prev.node_id)
                    if row is not None:
                        r = orig.comparable_resources()
                        e_row = row
                        e_res = (
                            -float(r.cpu),
                            -float(r.memory_mb),
                            -float(r.disk_mb),
                        )
                        if (
                            prev.job_id == job.id
                            and prev.task_group == p_tg.name
                        ):
                            e_coll = -1
            sim.evict_rows.append(e_row)
            sim.evict_res.append(e_res)
            sim.evict_coll.append(e_coll)

            pen = set()
            if prev is not None:
                if prev.client_status == ALLOC_CLIENT_STATUS_FAILED:
                    pen.add(prev.node_id)
                if prev.reschedule_tracker is not None:
                    for event in prev.reschedule_tracker.events:
                        pen.add(event.prev_node_id)
            if len(pen) > MAX_PENALTY_NODES:
                return None
            sim.penalties.append(frozenset(pen))

        if len(placements) > 64:
            return None  # over the largest supported pick bucket
        sim.placements = len(placements)

        # static-port bookkeeping for the kernel's collision mask:
        # asked ports per group slot, and ports this eval's staged
        # stops/evictions would free (gated in _flush_run — the
        # kernel's occupancy carry is monotone)
        for g in sim.tgs:
            ports = set()
            # mirror the binpack ask EXACTLY: only tg.networks[0] and
            # each task's networks[0] are ever assigned (rank.py
            # group/task network paths); extra declared networks are
            # ignored by the sequential scheduler and must not
            # over-constrain the kernel mask
            asks = []
            if g.networks:
                asks.append(g.networks[0])
            for t in g.tasks:
                if t.resources.networks:
                    asks.append(t.resources.networks[0])
            for nw in asks:
                for p in nw.reserved_ports:
                    if p.value:
                        ports.add(p.value)
            sim.asked_ports.append(frozenset(ports))
            # device asks: matched-code sets per request (constraint
            # filtering included), counts pooled per set
            dev_asks: Dict[FrozenSet[int], int] = {}
            reqs = [
                req for t in g.tasks for req in t.resources.devices
            ]
            if reqs:
                for req in reqs:
                    codes = self._device_request_codes(table, req)
                    dev_asks[codes] = dev_asks.get(codes, 0) + int(
                        req.count
                    )
            sim.asked_devices.append(dev_asks)
        released = set()
        released_dev = set()
        for aid in evicted_ids:
            orig = snap.alloc_by_id(aid)
            if (
                orig is None
                or orig.terminal_status()
                or orig.allocated_resources is None
            ):
                continue
            for p in orig.allocated_resources.shared.ports:
                if p.value:
                    released.add(p.value)
            for tr in orig.allocated_resources.tasks.values():
                for net in tr.networks:
                    for p in net.reserved_ports:
                        if p.value:
                            released.add(p.value)
                for dv in tr.devices:
                    released_dev.add(
                        (dv.vendor, dv.type, dv.name)
                    )
        sim.released_ports = frozenset(released)
        sim.released_device_keys = frozenset(released_dev)
        # the stateful ctx rng has now consumed exactly the draws the
        # sequential path would have (one per in-place probe's
        # set_nodes); the next draw is the placement shuffle
        nodes, _by_dc = ready_nodes_in_dcs(snap, job.datacenters)
        sim.order = shuffle_permutation(ctx.rng, len(nodes))
        return sim

    # ------------------------------------------------------------------

    def _inert_inputs(self, table, P: int = 16,
                      T: int = 1) -> ChainInputs:
        """A single inert eval in the stacked layout (E axis absent):
        wanted=0 makes every pick step a no-op, so the chained carry
        passes through unchanged.  Used by warm_shapes; production
        padding rows are built directly in _prescore."""
        C = table.capacity
        return ChainInputs(
            feasible=np.zeros((T, C), dtype=bool),
            perm=np.arange(C, dtype=np.int32),
            ask_cpu=np.zeros(P),
            ask_mem=np.zeros(P),
            ask_disk=np.zeros(P),
            desired_count=np.ones(P, np.int32),
            limit=np.ones(P, np.int32),
            distinct_hosts=np.bool_(False),
            tg_idx=np.zeros(P, np.int32),
        )

    def warm_shapes(
        self, e_buckets=None, p_buckets=(16,),
        t_buckets=(1, 2),
    ) -> None:
        """Pre-compile the chained kernel for the common launch shapes
        so the first production batches don't pay the jit compile (the
        bench and server startup call this outside any timed region).
        The default eval-axis buckets are the live chunk-width
        ladder (``_chunk_buckets``, the CHUNK_BUCKETS constants
        clamped to the operator's batch ceiling) — EVERY production
        launch is a chunk of one of those widths since the pipelined
        prescore went adaptive — warmed with return_carry=True
        exactly as _launch_chunk dispatches it.  T buckets cover the single-group
        shape and the first multi-task-group bucket (T=2 — jobs with 2
        groups; 3-4-group jobs pad to T=4 and compile on first
        sighting)."""
        import jax

        table = self.store.node_table
        C = table.capacity
        # the SAME device-resident columns production launches read:
        # warming with the host numpy arrays would register float64
        # signatures that never match the device mirror's canonical
        # dtype when x64 is off (production TPU runs f32)
        dev_cols = self._device_columns(table)
        if e_buckets is None:
            e_buckets = self._chunk_buckets()
        for e in e_buckets:
            for p in p_buckets:
                for t in t_buckets:
                    inert = self._inert_inputs(
                        table, P=int(p), T=int(t)
                    )
                    stacked = ChainInputs(
                        *[
                            np.stack([getattr(inert, f)] * e)
                            for f in ChainInputs._fields
                        ]
                    )
                    for extras in (
                        {},
                        # steady-state variant: anti-affinity bases
                        # and affinity vectors present
                        {
                            "coll0": np.zeros((e, t, C), np.int32),
                            "affinity": np.zeros((e, t, C)),
                        },
                    ):
                        args = dev_cols + (
                            stacked,
                            np.full(e, 1, np.int32),
                            int(p),
                        )
                        kwargs = dict(
                            spread_fit=False,
                            wanted=np.zeros(e, np.int32),
                            coll0=None,
                            affinity=None,
                            spread=None,
                            deltas=self._zero_deltas(e, p),
                            pre=self._zero_pre(e),
                            return_carry=True,
                        )
                        kwargs.update(extras)
                        out = chained_plan_picks_cols(
                            *args, **kwargs
                        )
                        jax.block_until_ready(out)
                        with self._compile_lock:
                            # must match _launch_ready's lookup key
                            # (fn-name prefix + backend epoch
                            # included), or warmed shapes are never
                            # recognized
                            self._compiled.add(
                                (
                                    "chained_plan_picks_cols",
                                    self._backend_epoch,
                                )
                                + self._launch_signature(
                                    args, kwargs
                                )
                            )

    @staticmethod
    def _zero_deltas(E: int, P: int) -> StepDeltas:
        return StepDeltas(
            evict_rows=np.full((E, P), -1, np.int32),
            evict_cpu=np.zeros((E, P)),
            evict_mem=np.zeros((E, P)),
            evict_disk=np.zeros((E, P)),
            evict_coll=np.zeros((E, P), np.int32),
            penalty_rows=np.full(
                (E, P, MAX_PENALTY_NODES), -1, np.int32
            ),
        )

    @staticmethod
    def _zero_pre(E: int, R: int = 1) -> PreDeltas:
        return PreDeltas(
            rows=np.zeros((E, R), np.int32),
            cpu=np.zeros((E, R)),
            mem=np.zeros((E, R)),
            disk=np.zeros((E, R)),
        )

    # -- host-assembly caches ------------------------------------------

    def _candidates(self, snap, datacenters) -> tuple:
        """(nodes, rows, rest) for a datacenter set, cached per node-
        topology generation — usage-only changes (every plan commit)
        keep the cache warm."""
        table = snap.node_table
        gen = table.topo_generation
        key = (gen, tuple(datacenters))
        hit = self._cand_cache.get(key)
        if hit is not None:
            return hit
        nodes, _by_dc = ready_nodes_in_dcs(snap, datacenters)
        rows = np.asarray(
            [table.row_of[n.id] for n in nodes], dtype=np.int32
        )
        present = np.zeros(table.capacity, dtype=bool)
        present[rows] = True
        rest = np.nonzero(~present)[0].astype(np.int32)
        out = (nodes, rows, rest)
        self._cand_cache.put(key, out)
        return out

    def _stage_walk_order(self, snap, job, sim):
        """The per-eval walk-order staging shared by the chunk
        assembler (`_assemble`) and the storm problem builder
        (`sched/storm.build_storm_problem`): candidate layout, the
        recorded serial shuffle when rng-aligned (seed-keyed
        fallback otherwise), the arena-order perm, and the replay
        passthrough mirror.  ONE definition on purpose — the storm
        path's degenerate-parity contract depends on byte-identical
        staging, and a copy here would drift silently.
        Returns ``(rows, rest, n_cand, order, perm)``."""
        nodes, rows, rest = self._candidates(
            snap, job.datacenters
        )
        n_cand = len(nodes)
        rng_aligned = (
            sim.order is not None and len(sim.order) == n_cand
        )
        if rng_aligned:
            order = sim.order
        else:
            order = shuffle_permutation(
                random.Random(self.seed), n_cand
            )
        perm = np.concatenate([rows[order], rest])
        # passthrough needs the rng-aligned order (the one the
        # sequential shuffle would produce); a fallback shuffle
        # keeps prescoring valid but gates preempt retries
        sim.replay_order = order if rng_aligned else None
        sim.replay_n_cand = n_cand
        return rows, rest, n_cand, order, perm

    @staticmethod
    def _job_signature(job: Job, tg: TaskGroup) -> tuple:
        cons = tuple(
            (c.ltarget, c.operand, c.rtarget)
            for c in list(job.constraints)
            + list(tg.constraints)
            + [c for t in tg.tasks for c in t.constraints]
        )
        affs = tuple(
            (a.ltarget, a.operand, a.rtarget, a.weight)
            for a in list(job.affinities)
            + list(tg.affinities)
            + [a for t in tg.tasks for a in t.affinities]
        )
        drivers = tuple(sorted({t.driver for t in tg.tasks}))
        return (cons, affs, drivers, tuple(job.datacenters))

    def _static_vectors(
        self, snap, job: Job, tg: TaskGroup, rows: np.ndarray
    ) -> tuple:
        """(feasible bool[C], affinity f[C]) for a job spec, cached per
        (topology generation, job signature)."""
        table = snap.node_table
        gen = table.topo_generation
        key = (gen,) + self._job_signature(job, tg)
        hit = self._mask_cache.get(key)
        if hit is not None:
            return hit
        # bounded LRU: one (bool[C], f64[C]) pair per distinct job
        # spec, capped so thousands of one-off specs on a long-lived
        # stable topology can't accumulate hundreds of MB
        compiler = MaskCompiler(table)
        feasible = np.zeros(table.capacity, dtype=bool)
        feasible[rows] = True
        feasible &= table.active & table.eligible
        for constraint in list(job.constraints) + list(
            tg.constraints
        ) + [c for t in tg.tasks for c in t.constraints]:
            m = compiler.constraint_mask(constraint)
            if m is not None:
                feasible &= m
        for task in tg.tasks:
            col = table.column(f"driver.{task.driver}")
            feasible = feasible & (col.codes != -1)
        affinities = (
            list(job.affinities)
            + list(tg.affinities)
            + [a for t in tg.tasks for a in t.affinities]
        )
        total, sum_w = compiler.affinity_score_vector(affinities)
        aff_vec = (
            total / sum_w if sum_w else np.zeros(table.capacity)
        )
        out = (feasible, aff_vec)
        self._mask_cache.put(key, out)
        return out

    def _device_affinity_column(
        self, table, compiler, tg
    ) -> Tuple[Optional[np.ndarray], bool]:
        """Static per-node device-affinity score for a task group's
        device asks (reference rank.go:443-461: per req the allocator
        returns the chosen group's matched affinity weights; the node
        score appends sum(matched)/sum(|weights|)).

        Exactness rests on the _flush_run chain gates: admitted
        batches guarantee each node carries at most ONE group matching
        any ask signature, so the "best group" choice is degenerate
        and the score is independent of instance consumption — nodes
        whose unique group runs out of instances become infeasible via
        the DeviceInputs mask, never mis-scored."""
        reqs = [
            req
            for t in tg.tasks
            for req in t.resources.devices
            if req.affinities
        ]
        if not reqs:
            return None, False
        # static per (device inventory, group ask): cached like the
        # sibling _dev_codes_cache — the hot _prescore loop must not
        # re-walk device_groups x affinities per eval per flush
        ask_sig = tuple(
            (
                req.name,
                tuple(
                    (c.ltarget, c.operand, c.rtarget)
                    for c in req.constraints
                ),
                tuple(
                    (a.ltarget, a.operand, a.rtarget, a.weight)
                    for a in req.affinities
                ),
            )
            for req in reqs
        )
        cache_key = (table.topo_generation, ask_sig)
        hit = self._dev_aff_cache.get(cache_key)
        if hit is not None:
            return hit
        from ..sched.device import matched_affinity_weight
        from ..structs import NodeDeviceResource

        total_w = 0.0
        col = np.zeros(table.capacity)
        for req in reqs:
            total_w += sum(
                abs(float(a.weight)) for a in req.affinities
            )
            codes = self._device_request_codes(table, req)
            if not codes:
                continue
            matched: Dict[int, float] = {}
            for code in codes:
                sig = table._device_sig_meta[code]
                group = NodeDeviceResource(
                    vendor=sig[0], type=sig[1], name=sig[2],
                    attributes=dict(sig[3]),
                )
                _tw, s = matched_affinity_weight(
                    group, req.affinities,
                    compiler.regex_cache, compiler.version_cache,
                )
                matched[code] = s
            for row, groups in table.device_groups.items():
                for code, _cnt in groups:
                    if code in codes:
                        col[row] += matched[code]
                        break
        out = (
            (col / total_w, True) if total_w else (None, False)
        )
        self._dev_aff_cache.put(cache_key, out)
        return out

    def _device_request_codes(self, table, req) -> FrozenSet[int]:
        """Matched device-sig codes for a request (name + constraint
        filtering), cached by the sig interner's length — it is
        append-only, so a grown interner only ever ADDS candidate
        codes (avoids an O(sigs) scan per request per eval)."""
        cons_sig = tuple(
            (c.ltarget, c.operand, c.rtarget)
            for c in req.constraints
        )
        key = (len(table.device_sigs), req.name, cons_sig)
        hit = self._dev_codes_cache.get(key)
        if hit is not None:
            return hit
        compiler = MaskCompiler(table)
        codes = frozenset(
            code
            for code in range(len(table.device_sigs))
            if table.device_sig_matches(code, req.name)
            and compiler._device_sig_meets_constraints(code, req)
        )
        self._dev_codes_cache.put(key, codes)
        return codes

    def _node_reserved_port_column(self, snap, port: int) -> np.ndarray:
        """bool[C]: nodes whose OWN reservations hold `port` (node
        networks' reserved_ports + reserved_resources.reserved_ports —
        the node half of NetworkIndex.set_node).  Cached per topology
        generation; alloc churn never touches node reservations."""
        table = snap.node_table
        gen = table.topo_generation
        key = (gen, port)
        hit = self._port_col_cache.get(key)
        if hit is not None:
            return hit
        col = np.zeros(table.capacity, dtype=bool)
        for node_id, row in table.row_of.items():
            node = snap.node_by_id(node_id)
            if node is None:
                continue
            if port in node.reserved_resources.reserved_ports:
                col[row] = True
                continue
            # NetworkIndex reserves each net's ports under that net's
            # OWN ip, but assign_ports only consults the DEFAULT ip
            # (node_ips[0] — the first network's) — a secondary
            # network's reservation never collides in the sequential
            # path, so it must not collide here either
            nets = node.node_resources.networks
            default_ip = (
                (nets[0].ip or "0.0.0.0") if nets else "0.0.0.0"
            )
            for net in nets:
                if (net.ip or "0.0.0.0") != default_ip:
                    continue
                if any(p.value == port for p in net.reserved_ports):
                    col[row] = True
                    break
        self._port_col_cache.put(key, col)
        return col

    # -- snapshot-delta input cache ------------------------------------

    def _mark_mirror_dirty(self) -> None:
        """Abandoned in-flight launches may still be reading EITHER
        device mirror: the next sync of each must re-upload instead of
        donating the buffers out from under them."""
        self._mirror_dirty = True
        self._mirror_dirty_sharded = True

    def _device_columns(self, table, sharded: bool = False) -> tuple:
        """The six shared node columns (cpu/mem/disk totals + used) as
        device-resident arrays — the persistent padded arena the
        pipelined prescore launches read instead of re-shipping all C
        rows per flush.  Totals re-upload only on topology changes;
        usage columns are delta-patched from the store's dirty-row log
        (store.usage_delta_since): between consecutive flushes only the
        rows the interleaved plan commits touched are scattered in.
        Patching uses absolute SET of the current host values (never
        accumulated deltas), so the device mirror is bit-identical to a
        fresh upload.  Hit rate is exported as the
        ``batch_worker.input_cache_hit_rate`` gauge.

        ``sharded=True`` returns the SHARDED twin: the same columns as
        ``NamedSharding(P("nodes"))`` arrays over the node-axis mesh,
        patched per shard (ops/batch.patch_rows_sharded) so a warm
        mesh flush ships O(dirty rows) bytes host->device — the bytes
        actually staged are exported as the ``mesh.bytes_per_flush``
        gauge and the delta-hit rate as ``mesh.mirror_hit_rate``."""
        import jax

        with self._usage_cache_lock:
            # nomadlint: disable=blocking-while-locked -- the mirror sync MUST serialize (two interleaved delta syncs corrupt generation tracking), so device_put runs under the lock by design; the wedge story is owned by the supervisor: a parked holder is abandoned, _on_device_transition REPLACES the lock (see lock-discipline ALLOWLIST) and stale-epoch publishes are discarded by the cache key
            return self._device_columns_locked(table, jax, sharded)

    def _device_columns_locked(
        self, table, jax, sharded: bool = False
    ) -> tuple:
        if sharded and self._mesh is None:
            raise RuntimeError(
                "sharded usage mirror requested without a mesh"
            )
        # table.epoch: a snapshot restore swaps in a FRESH NodeTable
        # whose restarted generations could collide with the cached
        # key and leave pre-restore usage on device permanently.
        # _backend_epoch: a supervisor failover/recovery re-targets
        # the backend — a mirror uploaded to the old one must never
        # satisfy a post-flip launch.  The sharded mirror additionally
        # keys on the mesh width (a rebuilt mesh re-lays the shards).
        key = (
            self._backend_epoch,
            table.epoch,
            table.topo_generation,
            table.capacity,
        )
        if sharded:
            key = key + ("sharded", self._mesh.devices.size)
            from jax.sharding import PartitionSpec as _P

            from ..parallel.mesh import (
                local_device_positions,
                mesh_put,
            )

            # multi-host: each process stages ONLY its own shards
            # (mesh_put -> make_array_from_callback); fully
            # addressable meshes keep the PR 8 device_put byte-for-
            # byte.  Every byte figure below is PER HOST: this
            # process's host->device staging, the pod's per-host
            # cross-host flush cost
            multihost = self._mesh_hosts > 1
            n_dev = self._mesh.devices.size
            local_pos = (
                local_device_positions(self._mesh)
                if multihost
                else list(range(n_dev))
            )
            n_local = len(local_pos)

            def put(col):
                return mesh_put(self._mesh, col, _P("nodes"))

        else:
            # explicit placement while failed over (the CPU backend);
            # None = jax's default device
            target = (
                self.supervisor.jax_device()
                if self.supervisor is not None
                else None
            )

            def put(col):
                return (
                    jax.device_put(col, target)
                    if target is not None
                    else jax.device_put(col)
                )

        cache_attr = (
            "_usage_cache_sharded" if sharded else "_usage_cache"
        )
        dirty_attr = (
            "_mirror_dirty_sharded" if sharded else "_mirror_dirty"
        )
        cache = getattr(self, cache_attr)
        hit = False
        bytes_up = 0
        if cache is None or cache["key"] != key:
            # topology changed (join/leave/re-fingerprint/arena
            # growth): rows may have been reassigned — full resync
            gen, _rows = self.store.usage_delta_since(-1)
            host_cols = (
                table.cpu_total,
                table.mem_total,
                table.disk_total,
                table.cpu_used,
                table.mem_used,
                table.disk_used,
            )
            if sharded and multihost and self._pod is not None:
                # pod head: peers rebuild their mirror shards from
                # the same host columns before any launch can read
                # them (FIFO: this precedes every later chain/storm)
                self._pod.send("mirror_full", host_cols)
            cols = tuple(put(col) for col in host_cols)
            bytes_up = sum(col.nbytes for col in host_cols)
            if sharded and multihost:
                # cold resync on a pod: each host uploads only its
                # own 1/hosts slice of every column
                bytes_up = bytes_up * n_local // n_dev
            cache = {"key": key, "gen": gen, "cols": cols}
            setattr(self, cache_attr, cache)
            # full re-upload: the cache now holds fresh buffers no
            # abandoned launch has ever seen
            setattr(self, dirty_attr, False)
        else:
            gen, rows = self.store.usage_delta_since(cache["gen"])
            cols = cache["cols"]
            if len(rows) > max(64, table.capacity // 8):
                # wide churn: one bulk upload beats many scatters
                host_used = (
                    table.cpu_used,
                    table.mem_used,
                    table.disk_used,
                )
                if sharded and multihost and self._pod is not None:
                    self._pod.send("mirror_bulk", host_used)
                cols = cols[:3] + tuple(
                    put(col) for col in host_used
                )
                bytes_up = sum(col.nbytes for col in host_used)
                if sharded and multihost:
                    bytes_up = bytes_up * n_local // n_dev
                setattr(self, dirty_attr, False)
            elif rows:
                idx = np.asarray(sorted(rows), dtype=np.int32)
                # hot-path donation (off-CPU): the stale column and
                # the idx/vals staging buffers are consumed in place,
                # so a steady-state delta sync allocates nothing net
                # on device — UNLESS an abandoned in-flight launch or
                # a background shield compile may still be reading
                # the live column (then the copying patch keeps the
                # old buffer intact for them)
                with self._compile_lock:
                    compiling = bool(self._compiling)
                donate = (
                    self._donation_enabled()
                    and not getattr(self, dirty_attr)
                    and not compiling
                )
                idx_dev = per_dev = idx_p = None
                if sharded and multihost:
                    # per-host flush protocol: every process builds
                    # the SAME [D, w] shard-local staging from the
                    # shared dirty log, then ships ONLY its own
                    # devices' rows (mesh_put) — a warm cross-host
                    # flush costs each host O(its dirty rows) bytes,
                    # never a replicated buffer over the network
                    from ..ops.batch import (
                        hostlocal_staging,
                        patch_rows_hostlocal,
                    )

                    if self._pod is not None:
                        # pod head: ship the sorted dirty rows plus
                        # their three value columns ONCE — O(dirty
                        # rows) bytes on the wire; each peer gathers
                        # its own shards' rows out of them and runs
                        # this same flush protocol locally
                        self._pod.send(
                            "mirror_delta", idx,
                            tuple(
                                src[idx]
                                for src in (
                                    table.cpu_used,
                                    table.mem_used,
                                    table.disk_used,
                                )
                            ),
                            table.capacity,
                        )
                    patch = patch_rows_hostlocal(
                        self._mesh, donate=donate
                    )
                    idx_stack, per_dev, width = hostlocal_staging(
                        self._mesh, idx, table.capacity
                    )
                    idx_dev = mesh_put(
                        self._mesh, idx_stack, _P("nodes")
                    )
                    # the index staging ships once for all three
                    # value columns
                    bytes_up += n_local * width * 4
                else:
                    # replicated staging: pad the row axis to a pow2
                    # bucket so the scatter keeps one trace per
                    # bucket; padding indexes C (out of bounds ->
                    # dropped, never wrapped)
                    width = _pow2(len(idx), floor=8)
                    idx_p = np.full(width, table.capacity, np.int32)
                    idx_p[: len(idx)] = idx
                    if sharded:
                        from ..ops.batch import patch_rows_sharded

                        patch = patch_rows_sharded(
                            self._mesh, donate=donate
                        )
                    elif donate:
                        from ..ops.batch import patch_rows_donated

                        patch = patch_rows_donated()
                    else:
                        patch = patch_rows
                patched = []
                try:
                    for col, src in zip(
                        cols[3:],
                        (
                            table.cpu_used,
                            table.mem_used,
                            table.disk_used,
                        ),
                    ):
                        if idx_dev is not None:
                            # multi-host: per-device value staging in
                            # the shard-local layout of idx_stack —
                            # only THIS host's rows are gathered
                            # (mesh_put ships nothing else; remote
                            # rows would be (H-1)/H wasted work on
                            # the hot flush path)
                            vals_stack = np.zeros(
                                (n_dev, width), dtype=src.dtype
                            )
                            for d in local_pos:
                                sel = per_dev[d]
                                vals_stack[d, : len(sel)] = src[sel]
                            bytes_up += (
                                n_local * width * src.dtype.itemsize
                            )
                            vals_dev = mesh_put(
                                self._mesh, vals_stack, _P("nodes")
                            )
                            # nomadlint: disable=donation-safety -- re-verified for the multi-host mirror (this PR): patch_rows_hostlocal(donate=True) donates a column of cache["cols"], replaced by the patched outputs below before any later read; same per-mirror dirty-flag + no-background-compile gating, same drop-the-mirror except path
                            patched.append(patch(col, idx_dev, vals_dev))
                            continue
                        vals = np.zeros(width, dtype=src.dtype)
                        vals[: len(idx)] = src[idx]
                        bytes_up += idx_p.nbytes + vals.nbytes
                        # nomadlint: disable=donation-safety -- re-verified for BOTH mirror variants (PR 8 audit): plain patch_rows_donated AND the sharded patch_rows_sharded(donate=True) donate a column of cache["cols"], which is replaced by the patched outputs below before any later read; donation is gated on the PER-MIRROR dirty flag + no background compiles, and the except path drops the whole mirror so a partially-donated sync can never be re-read
                        patched.append(patch(col, idx_p, vals))
                except Exception:
                    # a partially-donated sync leaves already-deleted
                    # buffers behind cache["cols"]; retrying the delta
                    # against them would fail on every future flush —
                    # drop the whole mirror so the next sync does a
                    # full re-upload from host state
                    setattr(self, cache_attr, None)
                    raise
                cols = cols[:3] + tuple(patched)
                # the patch produced fresh (or in-place-donated)
                # buffers only this worker references: the next sync
                # may donate again
                setattr(self, dirty_attr, False)
                hit = True
            else:
                hit = True  # nothing changed since the last sync
            cache["cols"] = cols
            cache["gen"] = gen
        metrics = getattr(self.server, "metrics", None)
        if sharded:
            if hit:
                self._mesh_mirror_hits += 1
            else:
                self._mesh_mirror_misses += 1
            if metrics is not None:
                # the acceptance gauge for the sharded-mirror
                # contract: a warm flush's upload is O(dirty rows)
                # staging buffers, not O(nodes) columns
                metrics.set_gauge(
                    "mesh.bytes_per_flush", float(bytes_up)
                )
                total = (
                    self._mesh_mirror_hits
                    + self._mesh_mirror_misses
                )
                metrics.set_gauge(
                    "mesh.mirror_hit_rate",
                    self._mesh_mirror_hits / total if total else 0.0,
                )
                # pod visibility: how many processes the node axis
                # spans (1 = single-host PR 8 mesh)
                metrics.set_gauge(
                    "mesh.hosts", float(self._mesh_hosts)
                )
        else:
            if hit:
                self._input_cache_hits += 1
            else:
                self._input_cache_misses += 1
            if metrics is not None:
                total = (
                    self._input_cache_hits
                    + self._input_cache_misses
                )
                metrics.set_gauge(
                    "batch_worker.input_cache_hit_rate",
                    self._input_cache_hits / total
                    if total
                    else 0.0,
                )
                metrics.set_gauge(
                    "batch_worker.mirror_sync_bytes",
                    float(bytes_up),
                )
        return cache["cols"]

    # ------------------------------------------------------------------

    def _assemble(
        self, snap, prescorable, sims: List[_Sim],
        chunk: int = PIPELINE_CHUNK,
        shared_cols: Optional[tuple] = None,
        chain: bool = False,
        mesh: Optional[bool] = None,
    ) -> _Assembled:
        """Stage 1 of the prescore pipeline: pure host-side numpy input
        staging for one admitted chain (no device work).  The result is
        launched chunk-by-chunk by ``_launch_chunk`` and fetched
        lazily, so device execution overlaps the host's replay of
        earlier chunks.

        ``chunk`` aligns the eval axis (one launch = one chunk-wide
        slice).  ``chain=True`` marks a mid-chain admission arena:
        it must reuse the chain head's device mirror via
        ``shared_cols`` (re-syncing the mirror mid-chain would patch
        buffers the in-flight launches are reading) and stay on the
        head's backend path — ``mesh`` pins that: None lets the arena
        pick the sharded path whenever its shapes qualify, False
        forces the single-chip chunk kernel, True allows the sharded
        path only (the caller defers the arena when the shapes don't
        qualify and ``use_mesh`` comes back False)."""
        table = snap.node_table
        C = table.capacity
        compiler = MaskCompiler(table)

        # per-eval assembly in group-routed form: feasibility/affinity/
        # collision bases per group slot [T, C], asks/limits per pick
        per_eval: List[dict] = []
        n_cands: List[int] = []
        # per eval: list of (codes, desired, used0, weight_frac) or None
        spread_per_eval: List[Optional[list]] = []
        max_picks = 1
        max_tgs = 1
        for (ev, _token, job), sim in zip(prescorable, sims):
            rows, rest, n_cand, order, perm = (
                self._stage_walk_order(snap, job, sim)
            )
            tgs = sim.tgs or [job.task_groups[0]]
            tg = tgs[0]
            max_tgs = max(max_tgs, len(tgs))
            feas_t = []
            aff_t = []
            has_aff_t = []
            dev_aff_t = []
            dev_aff_on_t = []
            for g in tgs:
                feasible_g, aff_vec_g = self._static_vectors(
                    snap, job, g, rows
                )
                feas_t.append(feasible_g)
                aff_t.append(aff_vec_g)
                daff_col, daff_on = self._device_affinity_column(
                    table, compiler, g
                )
                dev_aff_t.append(daff_col)
                dev_aff_on_t.append(daff_on)
                has_aff_t.append(
                    bool(
                        list(job.affinities)
                        or list(g.affinities)
                        or any(t.affinities for t in g.tasks)
                    )
                )
            has_aff_any = any(has_aff_t)

            # percent-target spreads -> in-kernel carry inputs.  The
            # info map is attribute-keyed (shared compute_spread_info,
            # spread.go:232): when job- and group-level stanzas share
            # an attribute, every pset scores with the overwrite
            # winner's desired/weight — exactly like SpreadIterator.
            # kernel stanzas per (group slot, pset), group-scoped
            # like the sequential SpreadIterator: each placing group
            # gets its OWN slots for the job-level stanzas plus its
            # group-level ones, with per-group desired counts
            # (percent x THAT group's count) and per-group weight
            # normalization (spread.py _compute_spread_info)
            eval_spreads = None
            for g_i, g in enumerate(tgs):
                g_spreads = list(g.spreads) + list(job.spreads)
                if not g_spreads:
                    continue
                from ..sched.spread import compute_spread_info

                info, spread_sum_w = compute_spread_info(
                    g_spreads, g.count
                )
                spread_sum_w = spread_sum_w or 1
                if eval_spreads is None:
                    eval_spreads = []
                # job-level first, then group-level (spread.py
                # set_task_group ordering)
                for sp in list(job.spreads) + list(g.spreads):
                    attr_info = info[sp.attribute]
                    # mode follows the MERGED per-attribute info like
                    # the sequential SpreadIterator ("if not
                    # desired_counts"): duplicate attributes with
                    # mixed target presence score in the overwrite
                    # winner's mode on BOTH paths
                    even = not attr_info["desired_counts"]
                    key = (g.name, sp.attribute)
                    codes, desired, used0, prop0, cleared0 = (
                        compiler.spread_kernel_inputs(
                            sp.attribute,
                            None
                            if even
                            else attr_info["desired_counts"],
                            sim.spread_existing.get(key, {}),
                            sim.spread_cleared.get(key, {}),
                            sim.spread_proposed.get(key, {}),
                        )
                    )
                    eval_spreads.append(
                        (codes, desired, used0, prop0, cleared0,
                         # even boosts are UNWEIGHTED (spread.py adds
                         # even_spread_score_boost without the weight
                         # fraction)
                         0.0
                         if even
                         else float(attr_info["weight"])
                         / float(spread_sum_w),
                         even,
                         g_i)
                    )
            spread_per_eval.append(eval_spreads)

            # distinct_hosts scopes (feasible.py _satisfies): JOB-
            # level blocks on any job alloc; GROUP-level only on the
            # picking group's own.  Single-group jobs merge (group ==
            # job there, and it keeps the historical trace shape);
            # multi-group jobs split into the job-wide scalar and a
            # per-group dh_tg vector
            job_dh = any(
                c.operand == CONSTRAINT_DISTINCT_HOSTS
                for c in job.constraints
            )
            tg_dh = [
                any(
                    c.operand == CONSTRAINT_DISTINCT_HOSTS
                    for c in g.constraints
                )
                for g in tgs
            ]
            if len(tgs) == 1:
                distinct_hosts = job_dh or tg_dh[0]
                dh_tg_vec = None
            else:
                distinct_hosts = job_dh
                # job-wide blocking subsumes group-level
                dh_tg_vec = (
                    np.asarray(tg_dh, dtype=bool)
                    if any(tg_dh) and not job_dh
                    else None
                )
            base_limit = compute_visit_limit(
                n_cand, ev.type == "batch"
            )
            # per-group visit limits: affinities (or spreads) lift the
            # walk cap for that group's selects (stack.py limit rules)
            # per-group limit lift (stack.py select: affinities or
            # spreads disable the log2 visit cap); job-level spreads
            # lift EVERY group's limit, group-level only their own
            limits_t = [
                2**31 - 1
                if has_aff_t[s_i]
                or list(job.spreads)
                or list(tgs[s_i].spreads)
                else base_limit
                for s_i in range(len(tgs))
            ]

            max_picks = max(max_picks, sim.placements)
            n_cands.append(n_cand)
            pick_tg = sim.pick_tg or [0] * sim.placements
            per_eval.append(
                dict(
                    feasible=np.stack(feas_t),  # [T, C]
                    affinity=(
                        np.stack(aff_t) if has_aff_any else None
                    ),
                    dev_aff=(
                        np.stack(
                            [
                                c
                                if c is not None
                                else np.zeros(C)
                                for c in dev_aff_t
                            ]
                        )
                        if any(dev_aff_on_t)
                        else None
                    ),
                    dev_aff_on=list(dev_aff_on_t),
                    occ0=sim.occ_extra,
                    dh_tg=dh_tg_vec,
                    coll0=(
                        sim.base_collisions
                        if sim.base_collisions is not None
                        and sim.base_collisions.any()
                        else None
                    ),
                    perm=perm,
                    pick_tg=pick_tg,
                    ask_cpu=[
                        float(
                            sum(
                                t.resources.cpu
                                for t in tgs[s].tasks
                            )
                        )
                        for s in pick_tg
                    ],
                    ask_mem=[
                        float(
                            sum(
                                t.resources.memory_mb
                                for t in tgs[s].tasks
                            )
                        )
                        for s in pick_tg
                    ],
                    ask_disk=[
                        float(tgs[s].ephemeral_disk.size_mb)
                        for s in pick_tg
                    ],
                    desired_count=[
                        int(tgs[s].count) for s in pick_tg
                    ],
                    limit=[int(limits_t[s]) for s in pick_tg],
                    distinct_hosts=bool(distinct_hosts),
                )
            )

        # bucket dynamic shapes so jit traces stay cached across
        # batches: the pick, eval and group axes pad to fixed buckets,
        # and deltas/pre ship always (zero-filled when absent).  coll0/
        # affinity/spread remain optional trace variants — warm_shapes
        # pre-compiles the coll0+affinity one; spread batches bucket
        # their (S, V1) axes to powers of two below to bound variants
        E_real = len(per_eval)
        # the eval axis pads to the next multiple of the flush's chunk
        # width: every launch is a chunk-wide slice of this arena, so
        # the device sees ONE compiled program per (width, pick)
        # bucket regardless of run length (padding waste < one chunk
        # per run)
        E = -(-E_real // chunk) * chunk
        P = 16 if max_picks <= 16 else _pow2(max_picks)
        T = _pow2(max_tgs)
        K = MAX_PENALTY_NODES
        if E > E_real:
            n_cands.extend([1] * (E - E_real))
            spread_per_eval.extend([None] * (E - E_real))

        # stack into the kernel layout, padding the T and P axes
        def _pad_picks(vals, fill, dtype):
            out = np.full((E, P), fill, dtype)
            for k, e in enumerate(per_eval):
                v = vals(e)
                out[k, : len(v)] = v
            return out

        feasible_s = np.zeros((E, T, C), dtype=bool)
        for k, e in enumerate(per_eval):
            feasible_s[k, : e["feasible"].shape[0]] = e["feasible"]
        perm_s = np.tile(
            np.arange(C, dtype=np.int32), (E, 1)
        )
        for k, e in enumerate(per_eval):
            perm_s[k] = e["perm"]
        stacked = ChainInputs(
            feasible=feasible_s,
            perm=perm_s,
            ask_cpu=_pad_picks(lambda e: e["ask_cpu"], 0.0, float),
            ask_mem=_pad_picks(lambda e: e["ask_mem"], 0.0, float),
            ask_disk=_pad_picks(lambda e: e["ask_disk"], 0.0, float),
            desired_count=_pad_picks(
                lambda e: e["desired_count"], 1, np.int32
            ),
            limit=_pad_picks(lambda e: e["limit"], 1, np.int32),
            distinct_hosts=np.array(
                [e["distinct_hosts"] for e in per_eval]
                + [False] * (E - E_real),
                dtype=bool,
            ),
            tg_idx=_pad_picks(lambda e: e["pick_tg"], 0, np.int32),
        )
        coll0 = None
        if any(e["coll0"] is not None for e in per_eval):
            coll0 = np.zeros((E, T, C), np.int32)
            for k, e in enumerate(per_eval):
                if e["coll0"] is not None:
                    coll0[k, : e["coll0"].shape[0]] = e["coll0"]
        affinity = None
        if any(e["affinity"] is not None for e in per_eval):
            affinity = np.zeros((E, T, C))
            for k, e in enumerate(per_eval):
                if e["affinity"] is not None:
                    affinity[k, : e["affinity"].shape[0]] = (
                        e["affinity"]
                    )
        occ0 = None
        if any(e["occ0"] is not None for e in per_eval):
            occ0 = np.zeros((E, C), np.int32)
            for k, e in enumerate(per_eval):
                if e["occ0"] is not None:
                    occ0[k] = e["occ0"]
        dh_tg = None
        if any(e["dh_tg"] is not None for e in per_eval):
            dh_tg = np.zeros((E, T), dtype=bool)
            for k, e in enumerate(per_eval):
                if e["dh_tg"] is not None:
                    dh_tg[k, : len(e["dh_tg"])] = e["dh_tg"]
        dev_aff = None
        dev_aff_on = None
        if any(e["dev_aff"] is not None for e in per_eval):
            dev_aff = np.zeros((E, T, C))
            dev_aff_on = np.zeros((E, T), dtype=bool)
            for k, e in enumerate(per_eval):
                if e["dev_aff"] is not None:
                    dev_aff[k, : e["dev_aff"].shape[0]] = e["dev_aff"]
                dev_aff_on[k, : len(e["dev_aff_on"])] = e[
                    "dev_aff_on"
                ]

        # static-port collision inputs: slot axis Q enumerates the
        # distinct asked ports across the batch; occupancy at the
        # snapshot comes from the store's live-port index plus node-
        # level reservations (ops/batch.py PortInputs)
        all_ports = sorted(
            {p for s in sims for fs in s.asked_ports for p in fs}
        )
        port_ask_arr = None
        port_used0 = None
        if all_ports:
            Q = _pow2(len(all_ports), floor=2)
            slot = {p: q for q, p in enumerate(all_ports)}
            port_ask_arr = np.zeros((E, T, Q), dtype=bool)
            for k, s in enumerate(sims):
                for t_i, fs in enumerate(s.asked_ports):
                    for p in fs:
                        port_ask_arr[k, t_i, slot[p]] = True
            port_used0 = np.zeros((Q, C), dtype=bool)
            for p, q in slot.items():
                for node_id, cnt in snap.live_port_nodes(
                    p
                ).items():
                    if cnt > 0:
                        row = table.row_of.get(node_id)
                        if row is not None:
                            port_used0[q, row] = True
                port_used0[q] |= self._node_reserved_port_column(
                    snap, p
                )

        # device-capacity inputs: slot axis D enumerates the batch's
        # distinct matched-code sets (identical-or-disjoint per the
        # _flush_run gate); free counts = group totals minus live
        # reservations (ops/batch.py DeviceInputs)
        all_dev_sets = sorted(
            {
                cs
                for s in sims
                for d in s.asked_devices
                for cs in d
            },
            key=sorted,
        )
        dev_ask_arr = None
        dev_free0 = None
        if all_dev_sets:
            D = _pow2(len(all_dev_sets), floor=1)
            dslot = {cs: di for di, cs in enumerate(all_dev_sets)}
            dev_ask_arr = np.zeros((E, T, D), np.int32)
            for k, s in enumerate(sims):
                for t_i, asks in enumerate(s.asked_devices):
                    for cs, count in asks.items():
                        dev_ask_arr[k, t_i, dslot[cs]] = count
            dev_free0 = np.zeros((D, C), np.int32)
            for cs, di in dslot.items():
                has_cs = np.zeros(C, dtype=bool)
                for row, groups in table.device_groups.items():
                    for code, count in groups:
                        if code in cs:
                            dev_free0[di, row] += count
                            has_cs[row] = True
                # live reservations from the unified table index —
                # subtracted ONLY on rows that actually carry a cs
                # group (a key-granularity reservation on a node
                # whose group code is outside the set must not drive
                # the pool negative and poison unrelated picks)
                keys = {
                    table.device_sig_key(code) for code in cs
                }
                for (row, key), count in (
                    table.device_used.items()
                ):
                    if key in keys and has_cs[row]:
                        dev_free0[di, row] -= count

        deltas = self._zero_deltas(E, P)
        for k, sim in enumerate(sims):
            for p, row in enumerate(sim.evict_rows):
                deltas.evict_rows[k, p] = row
                (
                    deltas.evict_cpu[k, p],
                    deltas.evict_mem[k, p],
                    deltas.evict_disk[k, p],
                ) = sim.evict_res[p]
                deltas.evict_coll[k, p] = sim.evict_coll[p]
            for p, pen in enumerate(sim.penalties):
                for i, nid in enumerate(sorted(pen)):
                    deltas.penalty_rows[k, p, i] = table.row_of.get(
                        nid, -1
                    )

        R = _pow2(max((len(s.pre) for s in sims), default=1), floor=1)
        pre = self._zero_pre(E, R)
        for k, sim in enumerate(sims):
            for i, (row, acc) in enumerate(sorted(sim.pre.items())):
                pre.rows[k, i] = row
                pre.cpu[k, i], pre.mem[k, i], pre.disk[k, i] = acc

        spread_stack = None
        if any(s for s in spread_per_eval):
            from ..ops.batch import SpreadInputs

            S = _pow2(max(len(s or ()) for s in spread_per_eval))
            V1 = _pow2(
                max(
                    (
                        len(d)
                        for s in spread_per_eval
                        for (_c, d, _u, _p, _cl, _w, _e, _g) in (
                            s or ()
                        )
                    ),
                    default=1,
                ),
                floor=2,
            )
            s_codes = np.zeros((E, S, C), np.int32)
            s_desired = np.zeros((E, S, V1))
            s_used0 = np.zeros((E, S, V1))
            s_prop0 = np.zeros((E, S, V1))
            s_cleared0 = np.zeros((E, S, V1))
            s_weight = np.zeros((E, S))
            s_active = np.zeros((E, S), dtype=bool)
            s_even = np.zeros((E, S), dtype=bool)
            s_group = np.zeros((E, S), np.int32)
            multi_group_spread = False
            for k, s in enumerate(spread_per_eval):
                for j, (
                    c, d, u, p0, cl, w, ev_mode, g_i
                ) in enumerate(s or ()):
                    # this eval's penalty slot moves to the shared
                    # V1-1 slot under padding
                    pen = len(d) - 1
                    s_codes[k, j] = np.where(c == pen, V1 - 1, c)
                    s_desired[k, j, : pen] = d[:-1]
                    s_used0[k, j, : pen] = u[:-1]
                    s_prop0[k, j, : pen] = p0[:-1]
                    s_cleared0[k, j, : pen] = cl[:-1]
                    s_weight[k, j] = w
                    s_active[k, j] = True
                    s_even[k, j] = ev_mode
                    s_group[k, j] = g_i
                    if g_i:
                        multi_group_spread = True
            spread_stack = SpreadInputs(
                codes=s_codes,
                desired=s_desired,
                used0=s_used0,
                proposed0=s_prop0,
                cleared0=s_cleared0,
                weight=s_weight,
                active=s_active,
                # None keeps percent-only workloads on the cheaper
                # kernel path (the even math never traces)
                even=s_even if s_even.any() else None,
                # group routing only traces when a multi-group
                # spread eval is actually in the batch
                group=s_group if multi_group_spread else None,
            )
        spread_fit = (
            snap.scheduler_config().effective_scheduler_algorithm()
            == "spread"
        )
        wanted = np.zeros(E, np.int32)
        wanted[:E_real] = [s.placements for s in sims]
        # the sharded runner covers the single-group scalar layout
        # (T=1, no port/device slot axes, no per-group vectors); the
        # node axis must tile evenly over the mesh.  Mid-chain
        # admission arenas qualify exactly like chain heads — an
        # admitted chunk splices into a sharded chain identically
        mesh_capable = (
            self._mesh is not None
            and T == 1
            and port_ask_arr is None
            and dev_ask_arr is None
            and dev_aff is None
            and occ0 is None
            and dh_tg is None
            and C % self._mesh.devices.size == 0
        )
        use_mesh = mesh_capable if mesh is None else (
            bool(mesh) and mesh_capable
        )
        return _Assembled(
            E_real=E_real,
            E=E,
            P=int(P),
            T=int(T),
            stacked=stacked,
            n_cands=np.asarray(n_cands, np.int32),
            wanted=wanted,
            spread_fit=spread_fit,
            coll0=coll0,
            affinity=affinity,
            spread=spread_stack,
            deltas=deltas,
            pre=pre,
            port_ask=port_ask_arr,
            port_used0=port_used0,
            dev_ask=dev_ask_arr,
            dev_free0=dev_free0,
            dev_aff=dev_aff,
            dev_aff_on=dev_aff_on,
            occ0=occ0,
            dh_tg=dh_tg,
            # the persistent delta-patched device mirror every launch
            # reads — the SHARDED mirror for mesh arenas (a mid-chain
            # admission arena reuses the chain head's mirror tuple
            # instead of re-syncing: a re-sync would patch buffers
            # the in-flight launches are reading)
            dev_cols=(
                shared_cols
                if shared_cols is not None
                else self._device_columns(table, sharded=use_mesh)
            ),
            use_mesh=use_mesh,
            chunk=chunk,
        )

    # -- launch + fetch (pipeline stages 2 and 3) ----------------------

    @staticmethod
    def _chunk_slice(x, c0: int, c1: int):
        """Slice the leading eval axis of an optional array or
        NamedTuple-of-arrays input (fields may be None, e.g.
        SpreadInputs.even)."""
        if x is None:
            return None
        if isinstance(x, np.ndarray):
            return x[c0:c1]
        return type(x)(
            *[None if f is None else f[c0:c1] for f in x]
        )

    def _donation_enabled(self) -> bool:
        """Donating the carry buffers only helps (and is only honored)
        off-CPU; resolved lazily so backend init stays off the module
        import path.  While the supervisor has failed the pipeline
        over, launches run on the CPU backend regardless of what
        jax.default_backend() says — donation stays off."""
        if (
            self.supervisor is not None
            and self.supervisor.failed_over()
        ):
            return False
        if self._donate_carries is None:
            import jax

            self._donate_carries = jax.default_backend() != "cpu"
        return self._donate_carries

    def _launch_chunk(
        self, asm: _Assembled, c0: int, c1: int, carry,
        check_ready: bool,
    ):
        """Stage 2: dispatch one chunk-wide slice of the run,
        chained on ``carry`` (the previous chunk's device carry-out;
        None = chain start, which reads the persistent device usage
        mirror and the host-built occupancy arenas).  NON-blocking —
        the return value holds device futures; ``_fetch`` realizes
        them.  Returns None while the launch shape compiles in the
        background (cold-compile shield).  Mesh arenas dispatch the
        node-sharded chained runner instead; the handle layout is
        identical, so the pipeline/fetch machinery never cares."""
        if asm.use_mesh:
            return self._launch_chunk_mesh(
                asm, c0, c1, carry, check_ready
            )
        sl = self._chunk_slice
        cols = asm.dev_cols
        if carry is None:
            used = cols[3:6]
            ports = asm.port_used0
            devs = asm.dev_free0
        else:
            used, ports, devs = carry
        args = (
            cols[0],
            cols[1],
            cols[2],
            used[0],
            used[1],
            used[2],
            sl(asm.stacked, c0, c1),
            asm.n_cands[c0:c1],
            asm.P,
        )
        kwargs = dict(
            spread_fit=asm.spread_fit,
            wanted=asm.wanted[c0:c1],
            coll0=sl(asm.coll0, c0, c1),
            affinity=sl(asm.affinity, c0, c1),
            spread=sl(asm.spread, c0, c1),
            deltas=sl(asm.deltas, c0, c1),
            pre=sl(asm.pre, c0, c1),
            port_ask=sl(asm.port_ask, c0, c1),
            port_used0=ports,
            dev_ask=sl(asm.dev_ask, c0, c1),
            dev_free0=devs,
            dev_aff=sl(asm.dev_aff, c0, c1),
            dev_aff_on=sl(asm.dev_aff_on, c0, c1),
            occ0=sl(asm.occ0, c0, c1),
            dh_tg=sl(asm.dh_tg, c0, c1),
            return_carry=True,
        )
        if check_ready and not self._launch_ready(args, kwargs):
            # first sighting of this launch shape: an XLA compile takes
            # seconds and must not stall the scheduling pipeline —
            # compile in the background, schedule these evals exactly
            return None
        fn = chained_plan_picks_cols
        if carry is not None and self._donation_enabled():
            # mid-chain chunks may donate their carry-in (it is the
            # previous launch's output, never read again); fall back to
            # the plain executable until the donating one is compiled.
            # clone_args: the shield "compiles" by executing, and a
            # donating background execution on the LIVE args would
            # consume the very carry the plain launch below is using
            donated = chained_plan_picks_cols_donated()
            if self._launch_ready(
                args, kwargs, fn=donated, clone_args=True
            ):
                fn = donated
        rows_j, pulls_j, carry_out = fn(*args, **kwargs)
        return rows_j, pulls_j, carry_out

    def _fetch(self, handle) -> Tuple[np.ndarray, np.ndarray]:
        """Stage 3: realize a chunk's device futures — the only point
        the host blocks on the device.  Off-CPU the staging buffers
        are released eagerly after the host copy: with the carry and
        mirror-patch donation this closes the loop on steady-state
        device allocation (a deep pipeline would otherwise hold every
        in-flight chunk's rows/pulls until GC).  On the CPU backend
        ``np.asarray`` may alias the buffer, so the handles are left
        to the GC there."""
        rows_j, pulls_j, _carry = handle
        out = (np.asarray(rows_j), np.asarray(pulls_j))
        if self._donation_enabled():
            for arr in (rows_j, pulls_j):
                try:
                    arr.delete()
                except Exception:  # noqa: BLE001 — eager-free only
                    pass
        return out

    def _launch_chunk_mesh(
        self, asm: _Assembled, c0: int, c1: int, carry,
        check_ready: bool,
    ):
        """Stage 2, sharded (NOMAD_TPU_MESH): dispatch one chunk-wide
        slice through the node-sharded chained runner
        (parallel/mesh.py sharded_chained_plan).  The chain start
        reads the persistent SHARDED usage mirror; later chunks chain
        on the previous launch's sharded carry — the usage columns
        thread chunk -> chunk on-device, never gathered to the host.
        Single-group arenas only (asm.use_mesh gates the layout): the
        T=1 slices reproduce the runner's per-eval scalar layout
        exactly.  Spread batches route through the with_spread
        variant — the (S, V+1) spread state rides replicated and only
        the winner/evictee slot one-hots reduce over shards.  Returns
        None while the shape compiles in the background, or when a
        failover disabled the mesh after this arena was assembled
        (launching on the old backend's shards could block on a
        wedged device; the exact path covers these evals)."""
        if self._mesh is None:
            return None
        cols = asm.dev_cols
        used = cols[3:6] if carry is None else carry[0]
        st = asm.stacked
        E = c1 - c0
        C = st.perm.shape[1]
        spread_arg = self._chunk_slice(asm.spread, c0, c1)
        runner = self._sharded_runner(
            asm.P, asm.spread_fit,
            with_spread=spread_arg is not None,
            spread_even=(
                spread_arg is not None
                and spread_arg.even is not None
            ),
        )
        args = cols[:3] + tuple(used) + (
            st.feasible[c0:c1, 0],
            st.perm[c0:c1],
            st.ask_cpu[c0:c1, 0],
            st.ask_mem[c0:c1, 0],
            st.ask_disk[c0:c1, 0],
            st.desired_count[c0:c1, 0],
            st.limit[c0:c1, 0],
            asm.wanted[c0:c1],
            asm.n_cands[c0:c1],
            st.distinct_hosts[c0:c1],
            asm.coll0[c0:c1, 0]
            if asm.coll0 is not None
            else np.zeros((E, C), np.int32),
            asm.affinity[c0:c1, 0]
            if asm.affinity is not None
            else np.zeros((E, C)),
            self._chunk_slice(asm.deltas, c0, c1),
            self._chunk_slice(asm.pre, c0, c1),
        )
        if spread_arg is not None:
            args = args + (spread_arg,)
        if self._mesh_hosts > 1:
            # multi-host: a multi-controller jit cannot conjure a
            # global array from process-local host data — commit the
            # staged args under the runner's own in_specs (each host
            # ships only its shards; carry/mirror pass through).  The
            # cold-compile shield is ALSO bypassed: it "compiles" by
            # executing on a background thread, and a collective
            # execution outside the lockstep launch order would
            # deadlock the pod — first-dispatch compiles block inline
            # instead (pods warm shapes at start, every process
            # running the same warm sequence)
            from ..parallel.mesh import place_chain_inputs

            if self._pod is not None:
                # pod head: peers rebuild this launch from the host
                # args tail plus their OWN device-resident mirror /
                # carry (which track ours message-for-message); the
                # send precedes the execution so the collective order
                # is the stream order on every member
                self._pod.send(
                    "chain",
                    {
                        "n_picks": asm.P,
                        "spread_fit": asm.spread_fit,
                        "with_spread": spread_arg is not None,
                        "spread_even": (
                            spread_arg is not None
                            and spread_arg.even is not None
                        ),
                        "used": (
                            "mirror" if carry is None else "carry"
                        ),
                    },
                    args[6:],
                )
            args = place_chain_inputs(
                self._mesh, args,
                with_spread=spread_arg is not None,
                spread_even=(
                    spread_arg is not None
                    and spread_arg.even is not None
                ),
            )
        elif check_ready and not self._launch_ready(
            args, {}, fn=runner
        ):
            return None
        rows_j, pulls_j, used_out = runner(*args)
        if self._pod is not None and self._pod.check:
            from ..parallel.pod import result_digest

            self._pod.check_results(result_digest(rows_j, pulls_j))
        metrics = getattr(self.server, "metrics", None)
        if metrics is not None:
            metrics.incr("mesh.launches")
        if c0 == 0:
            # once per arena: operators can tell "mesh used" from
            # "mesh skipped" (VERDICT r3 weak #6: the sharded path
            # degraded quietly)
            self._count("mesh_used")
        # same handle layout as the chunk path; the carry's port/dev
        # slots are structurally absent on mesh arenas
        return rows_j, pulls_j, (used_out, None, None)

    # -- cold-compile shield -------------------------------------------

    @staticmethod
    def _launch_signature(args, kwargs) -> tuple:
        import jax

        leaves = jax.tree_util.tree_leaves((args, kwargs))
        return tuple(
            (getattr(l, "shape", None), str(getattr(l, "dtype", l)))
            for l in leaves
        )

    def _launch_ready(
        self, args, kwargs, fn=None, clone_args=False
    ) -> bool:
        """Whether this launch shape has a compiled executable.  A new
        shape kicks off a background compile and returns False — the
        caller falls back to the exact sequential path until the
        executable is ready, so cold XLA compiles never block evals.

        ``clone_args=True`` is REQUIRED when ``fn`` donates any of its
        inputs: the shield compiles by executing, and a donating
        background execution on the caller's live arrays would consume
        buffers another launch is concurrently reading — the clone
        gives the background run its own device copies to burn.

        NOMAD_TPU_SYNC_COMPILE=1 (the test suite, via conftest) makes
        cold compiles block instead, so prescore-rate assertions are
        deterministic."""
        import os

        if os.environ.get("NOMAD_TPU_SYNC_COMPILE") == "1":
            return True
        if fn is None:
            fn = chained_plan_picks_cols
        # backend epoch in the key: an executable compiled before a
        # supervisor failover/recovery targeted a different backend
        sig = (
            getattr(fn, "__name__", str(fn)),
            self._backend_epoch,
        ) + self._launch_signature(args, kwargs)
        with self._compile_lock:
            if sig in self._compiled:
                return True
            if sig in self._compiling or sig in self._compile_failed:
                # a failed shape stays on the sequential path — retrying
                # a multi-second failing compile in the foreground would
                # be exactly the stall this shield exists to prevent
                return False
            self._compiling.add(sig)

        def compile_in_background():
            ok = True
            try:
                import jax as _jax

                a, k = args, kwargs
                if clone_args:
                    a, k = _jax.tree_util.tree_map(
                        lambda leaf: (
                            leaf.copy()
                            if hasattr(leaf, "copy")
                            else leaf
                        ),
                        (args, kwargs),
                    )
                _jax.block_until_ready(fn(*a, **k))
            except Exception:  # noqa: BLE001
                ok = False
                LOG.exception("background kernel compile failed")
            with self._compile_lock:
                self._compiling.discard(sig)
                (self._compiled if ok else self._compile_failed).add(
                    sig
                )

        threading.Thread(
            target=compile_in_background,
            name="kernel-compile",
            daemon=True,
        ).start()
        return False

    # ------------------------------------------------------------------

    def _prescored_scheduler(
        self, snap, planner, ev: Evaluation, job: Job,
        rows: List[int], sim: _Sim, pulls: Optional[List[int]],
        speculative: bool = False,
    ):
        """The replay scheduler: a GenericScheduler whose stack
        replays the prescored pick rows.  Shared by the serial replay
        path (planner = this worker) and the speculative wave
        (planner = a capturing _SpecPlanner pinned to the wave
        snapshot).  Returns (scheduler, made); made[0] is the
        PrescoredStack once the scheduler built it."""
        made: list = []
        pick_tgs = [
            sim.tgs[s].name for s in sim.pick_tg
        ] if sim.pick_tg else []
        batch = ev.type == "batch"
        sched = GenericScheduler(
            snap, planner, batch=batch, use_tpu=False,
            seed=self.seed, speculative=speculative,
        )

        def make_stack():
            if made:
                # a plan-submit retry re-runs _process_once against
                # refreshed state; the prescored rows are stale there
                raise _Deviation("scheduler retry")
            inner = GenericStack(batch, sched.ctx)
            stack = PrescoredStack(
                sched.ctx, job, pick_tgs, rows,
                snap.node_table, sim.penalties, inner,
                evict_rows=sim.evict_rows,
                pulls=pulls,
                n_cand=getattr(sim, "replay_n_cand", 0),
                order=getattr(sim, "replay_order", None),
                batch=batch,
            )
            made.append(stack)
            return stack

        sched._make_stack = make_stack
        return sched, made

    def _process_prescored(
        self, ev: Evaluation, token: str, job: Job,
        rows: List[int], sim: _Sim,
        pulls: Optional[List[int]] = None,
    ) -> bool:
        """Replay one prescored eval through the real scheduler.
        Returns False when the chained kernel state past this eval is
        suspect (a prescored pick failed)."""
        snap = self.store.snapshot_min_index(
            max(ev.modify_index, ev.snapshot_index), timeout=5.0
        )
        ev.snapshot_index = snap.index
        scheduler, made = self._prescored_scheduler(
            snap, self, ev, job, rows, sim, pulls
        )
        scheduler.process(ev)
        # record the committed plan's node touches for the optimistic
        # replay wave's expected-touch ledger ({} = no-op plan)
        result = scheduler.plan_result
        self._last_replay_touches = (
            self._plan_touches(
                result.node_update,
                result.node_allocation,
                result.node_preemptions,
            )
            if result is not None
            else {}
        )
        self.evals_processed += 1
        TRACE.annotate(ev.id, outcome="prescored")
        EXPLAIN.record_eval(
            ev, scheduler, getattr(self.server, "metrics", None)
        )
        self.server.broker.ack(ev.id, token)
        if made and made[0].entered_passthrough:
            self._count("preempt_passthroughs")
        return not (made and made[0].saw_failed_row)
