"""Batched evaluation pipeline: the production integration of the
(evals x nodes x picks) kernel.

The per-eval TPU path pays one device round trip per placement, which is
ruinous when the accelerator sits behind a high-latency link (SURVEY.md
section 7.3).  The BatchWorker instead:

1. drains up to E compatible evals from the broker in one gulp,
2. *prescores* them in a single `batch_plan_picks` launch — every eval's
   full pick sequence, with in-kernel plan-delta accumulation and the
   same seeded visit orders the sequential path would use,
3. runs each eval through the ordinary GenericScheduler so all control
   flow (reconciler, blocked evals, retries, plan bookkeeping, status
   writes) stays in one implementation — but with a `PrescoredStack`
   whose `select` answers from the precomputed rows after exact host
   verification (ports/fit) of each winner,
4. falls back to the normal scheduler for any eval whose shape deviates
   from what was prescored (stops, penalties, preferred nodes, multi
   task groups, spreads, preemption retries, verification mismatches).

Because the kernel reproduces the sequential selection exactly
(ops/batch.py), prescored evals produce bit-identical plans; the
fallback guarantees correctness for everything else.
"""
from __future__ import annotations

import math
import random
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..ops.batch import BatchInputs, chained_plan_picks
from ..ops.constraints import MaskCompiler
from ..sched.feasible import shuffle_permutation
from ..sched.generic_sched import GenericScheduler
from ..sched.rank import BinPackIterator, RankedNode
from ..sched.stack import compute_visit_limit
from ..sched.tpu_stack import _SingleNodeSource
from ..sched.util import ready_nodes_in_dcs
from ..structs import CONSTRAINT_DISTINCT_HOSTS, Evaluation, Job, TaskGroup
from .worker import Worker

BATCH_MAX = 64
BATCH_WAIT_S = 0.005


class _Deviation(Exception):
    """The eval's control flow left the prescored fast path."""


class PrescoredStack:
    """Stack whose select() replays a precomputed pick sequence."""

    def __init__(self, ctx, job: Job, tg_name: str, rows: List[int],
                 table) -> None:
        self.ctx = ctx
        self.job = job
        self.tg_name = tg_name
        self.rows = rows
        self.table = table
        self.cursor = 0

    def set_nodes(self, nodes) -> None:
        # single-node set_nodes comes from inplace-update probing, which
        # the batch path does not prescore
        if len(nodes) <= 1:
            raise _Deviation("inplace probe")

    def set_job(self, job: Job) -> None:
        if job.id != self.job.id or job.version != self.job.version:
            raise _Deviation("job changed")

    def select(self, tg: TaskGroup, options=None) -> Optional[RankedNode]:
        if tg.name != self.tg_name:
            raise _Deviation("unexpected task group")
        if options is not None and (
            options.penalty_node_ids
            or options.preferred_nodes
            or options.preempt
        ):
            raise _Deviation("select options need the sequential path")
        if self.cursor >= len(self.rows):
            raise _Deviation("prescored picks exhausted")
        row = self.rows[self.cursor]
        self.cursor += 1
        if row < 0:
            return None
        node_id = self.table.node_ids[row]
        node = self.ctx.state.node_by_id(node_id)
        if node is None:
            raise _Deviation("node vanished")
        ranked = RankedNode(node=node)
        source = _SingleNodeSource(ranked)
        algorithm = (
            self.ctx.state.scheduler_config().effective_scheduler_algorithm()
        )
        binpack = BinPackIterator(
            self.ctx, source, False, self.job.priority, algorithm
        )
        binpack.set_job(self.job)
        binpack.set_task_group(tg)
        option = binpack.next()
        if option is None:
            raise _Deviation("winner failed exact verification")
        return option


class BatchWorker(Worker):
    """Worker that drains and prescores evals in batches."""

    def __init__(self, server, **kwargs) -> None:
        super().__init__(server, **kwargs)
        self.batch_max = BATCH_MAX
        self.prescored = 0
        self.fallbacks = 0

    # ------------------------------------------------------------------

    def run(self) -> None:
        while not self._stop.is_set():
            batch: List[Tuple[Evaluation, str]] = []
            ev, token = self.server.broker.dequeue(
                self.schedulers, timeout=0.1
            )
            if ev is None:
                continue
            batch.append((ev, token))
            while len(batch) < self.batch_max:
                ev, token = self.server.broker.dequeue(
                    self.schedulers, timeout=BATCH_WAIT_S
                )
                if ev is None:
                    break
                batch.append((ev, token))
            self._process_batch(batch)

    # ------------------------------------------------------------------

    def _process_batch(self, batch: List[Tuple[Evaluation, str]]) -> None:
        """Process the drained evals in queue order, prescoring each
        contiguous run of batchable evals in one chained kernel launch
        so the outcome is exactly what the serial worker loop would
        produce."""
        run: List[Tuple[Evaluation, str, Job, TaskGroup]] = []
        for ev, token in batch:
            job = self.store.job_by_id(ev.namespace, ev.job_id)
            if self._batchable(ev, job):
                run.append((ev, token, job, job.task_groups[0]))
                continue
            self._flush_run(run)
            run = []
            self._process_sequential(ev, token)
        self._flush_run(run)

    def _flush_run(self, run) -> None:
        if not run:
            return
        snap = self.store.snapshot()
        prescored_rows: Dict[str, List[int]] = {}
        try:
            prescored_rows = self._prescore(snap, run)
        except Exception:  # noqa: BLE001
            prescored_rows = {}
        for ev, token, job, tg in run:
            rows = prescored_rows.get(ev.id)
            if rows is None:
                self._process_sequential(ev, token)
                continue
            try:
                self._process_prescored(ev, token, job, tg, rows)
                self.prescored += 1
            except _Deviation:
                self.fallbacks += 1
                self._process_sequential(ev, token)
            except Exception:  # noqa: BLE001
                self._nack_quietly(ev, token)

    def _process_sequential(self, ev, token) -> None:
        try:
            self.process_eval(ev, token)
        except Exception:  # noqa: BLE001
            self._nack_quietly(ev, token)

    def _nack_quietly(self, ev, token) -> None:
        try:
            self.server.broker.nack(ev.id, token)
        except ValueError:
            pass

    # ------------------------------------------------------------------

    def _batchable(self, ev: Evaluation, job: Optional[Job]) -> bool:
        if job is None or job.stopped():
            return False
        if ev.type not in ("service", "batch"):
            return False
        if len(job.task_groups) != 1:
            return False
        tg = job.task_groups[0]
        # percent-target spreads run in-kernel (SpreadInputs carry);
        # even-spread mode (no targets) stays on the exact path
        if any(
            not sp.targets
            for sp in list(tg.spreads) + list(job.spreads)
        ):
            return False
        if tg.networks or any(t.resources.networks for t in tg.tasks):
            return False
        if any(t.resources.devices for t in tg.tasks):
            return False
        if any(
            c.operand == CONSTRAINT_DISTINCT_HOSTS
            for c in list(job.constraints) + list(tg.constraints)
        ):
            # supported by the kernel but interacts with existing allocs
            # through job-level collision sets; keep on the exact path
            return False
        if tg.ephemeral_disk.sticky:
            return False
        # existing non-terminal allocs may trigger stops/updates or
        # reschedule penalties in the reconciler; prescoring assumes a
        # pure place-only outcome
        allocs = self.store.allocs_by_job(ev.namespace, ev.job_id)
        if any(not a.terminal_status() for a in allocs):
            return False
        return True

    # ------------------------------------------------------------------

    def _prescore(self, snap, prescorable) -> Dict[str, List[int]]:
        table = snap.node_table
        C = table.capacity
        compiler = MaskCompiler(table)

        per_eval: List[BatchInputs] = []
        n_cands: List[int] = []
        # per eval: list of (codes, desired, used0, weight_frac) or None
        spread_per_eval: List[Optional[list]] = []
        max_picks = 1
        for ev, _token, job, tg in prescorable:
            nodes, _by_dc = ready_nodes_in_dcs(snap, job.datacenters)
            n_cand = len(nodes)
            rng = random.Random(self.seed)
            order = shuffle_permutation(rng, n_cand)
            rows = np.asarray(
                [table.row_of[n.id] for n in nodes], dtype=np.int32
            )
            present = set(rows.tolist())
            perm = np.concatenate(
                [
                    rows[order],
                    np.asarray(
                        [r for r in range(C) if r not in present],
                        dtype=np.int32,
                    ),
                ]
            )
            feasible = np.zeros(C, dtype=bool)
            feasible[rows] = True
            feasible &= table.active & table.eligible
            for constraint in list(job.constraints) + [
                c
                for c in tg.constraints
            ] + [c for t in tg.tasks for c in t.constraints]:
                m = compiler.constraint_mask(constraint)
                if m is not None:
                    feasible &= m
            for task in tg.tasks:
                col = table.column(f"driver.{task.driver}")
                feasible &= col.codes != -1

            affinities = (
                list(job.affinities)
                + list(tg.affinities)
                + [a for t in tg.tasks for a in t.affinities]
            )
            total, sum_w = compiler.affinity_score_vector(affinities)
            aff_vec = total / sum_w if sum_w else np.zeros(C)

            # percent-target spreads -> in-kernel carry inputs.  The
            # info map is attribute-keyed (shared compute_spread_info,
            # spread.go:232): when job- and group-level stanzas share
            # an attribute, every pset scores with the overwrite
            # winner's desired/weight — exactly like SpreadIterator.
            combined_spreads = list(tg.spreads) + list(job.spreads)
            eval_spreads = None
            if combined_spreads:
                from ..sched.spread import compute_spread_info

                info, spread_sum_w = compute_spread_info(
                    combined_spreads, tg.count
                )
                spread_sum_w = spread_sum_w or 1
                eval_spreads = []
                # one kernel stanza per pset (job-level first, then
                # group-level — spread.py set_task_group ordering)
                for sp in list(job.spreads) + list(tg.spreads):
                    attr_info = info[sp.attribute]
                    codes, desired, used0 = (
                        compiler.spread_kernel_inputs(
                            sp.attribute,
                            attr_info["desired_counts"],
                            {},
                        )
                    )
                    eval_spreads.append(
                        (codes, desired, used0,
                         float(attr_info["weight"])
                         / float(spread_sum_w))
                    )
            spread_per_eval.append(eval_spreads)

            limit = compute_visit_limit(n_cand, ev.type == "batch")
            if affinities or combined_spreads:
                limit = 2**31 - 1

            max_picks = max(max_picks, tg.count)
            n_cands.append(n_cand)
            per_eval.append(
                BatchInputs(
                    feasible=feasible,
                    base_cpu_used=table.cpu_used,
                    base_mem_used=table.mem_used,
                    base_disk_used=table.disk_used,
                    base_collisions=np.zeros(C, np.int32),
                    penalty=np.zeros(C, dtype=bool),
                    affinity_score=aff_vec,
                    perm=perm,
                    ask_cpu=np.float64(
                        sum(t.resources.cpu for t in tg.tasks)
                    ),
                    ask_mem=np.float64(
                        sum(t.resources.memory_mb for t in tg.tasks)
                    ),
                    ask_disk=np.float64(tg.ephemeral_disk.size_mb),
                    desired_count=np.int32(tg.count),
                    limit=np.int32(limit),
                    distinct_hosts=np.bool_(False),
                )
            )

        stacked = BatchInputs(
            *[
                np.stack([getattr(e, f) for e in per_eval])
                for f in BatchInputs._fields
            ]
        )
        spread_stack = None
        if any(s for s in spread_per_eval):
            from ..ops.batch import SpreadInputs

            E = len(per_eval)
            S = max(len(s or ()) for s in spread_per_eval)
            V1 = max(
                (
                    len(d)
                    for s in spread_per_eval
                    for (_c, d, _u, _w) in (s or ())
                ),
                default=1,
            )
            s_codes = np.zeros((E, S, C), np.int32)
            s_desired = np.zeros((E, S, V1))
            s_used0 = np.zeros((E, S, V1))
            s_weight = np.zeros((E, S))
            s_active = np.zeros((E, S), dtype=bool)
            for k, s in enumerate(spread_per_eval):
                for j, (c, d, u, w) in enumerate(s or ()):
                    # this eval's penalty slot moves to the shared
                    # V1-1 slot under padding
                    pen = len(d) - 1
                    s_codes[k, j] = np.where(c == pen, V1 - 1, c)
                    s_desired[k, j, : pen] = d[:-1]
                    s_used0[k, j, : pen] = u[:-1]
                    s_weight[k, j] = w
                    s_active[k, j] = True
            spread_stack = SpreadInputs(
                codes=s_codes,
                desired=s_desired,
                used0=s_used0,
                weight=s_weight,
                active=s_active,
            )
        spread_fit = (
            snap.scheduler_config().effective_scheduler_algorithm()
            == "spread"
        )
        rows_out = np.asarray(
            chained_plan_picks(
                table.cpu_total,
                table.mem_total,
                table.disk_total,
                stacked,
                np.asarray(n_cands, np.int32),
                int(max_picks),
                spread_fit=spread_fit,
                wanted=np.asarray(
                    [tg.count for _e, _t, _j, tg in prescorable],
                    np.int32,
                ),
                spread=spread_stack,
            )
        )
        out: Dict[str, List[int]] = {}
        for k, (ev, _token, _job, tg) in enumerate(prescorable):
            out[ev.id] = [int(r) for r in rows_out[k, : tg.count]]
        return out

    # ------------------------------------------------------------------

    def _process_prescored(
        self, ev: Evaluation, token: str, job: Job, tg: TaskGroup,
        rows: List[int],
    ) -> None:
        snap = self.store.snapshot_min_index(
            max(ev.modify_index, ev.snapshot_index), timeout=5.0
        )
        ev.snapshot_index = snap.index
        outer = self

        class _Factory:
            def __call__(self, state, planner, batch, use_tpu=None,
                         seed=None):
                sched = GenericScheduler(
                    state, planner, batch=batch, use_tpu=False, seed=seed
                )
                def make_stack():
                    return PrescoredStack(
                        sched.ctx, job, tg.name, rows, snap.node_table
                    )
                sched._make_stack = make_stack
                return sched

        scheduler = _Factory()(
            snap, self, ev.type == "batch", seed=self.seed
        )
        scheduler.process(ev)
        self.evals_processed += 1
        self.server.broker.ack(ev.id, token)
