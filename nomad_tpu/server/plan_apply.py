"""Pipelined plan applier (reference nomad/plan_apply.go).

Plans dequeue in priority order, every touched node is re-verified
against current state (evaluateNodePlan:629 re-runs AllocsFit), and the
feasible subset commits through the store's plan-results write path
(partial commits set a refresh index so the submitting worker retries on
fresh state).  Two reference mechanisms are reproduced:

* **Pipelining** (plan_apply.go:45-70): a verifier thread checks plan
  N+1 against an *optimistic* view — base state plus the results of
  plans that are verified but whose (possibly raft-replicated) apply is
  still in flight — while a second thread commits plan N.  Commits stay
  strictly ordered; only verification overlaps the apply latency, which
  matters exactly when the store is a raft facade with real replication
  RTTs (server/cluster.py).  If an apply fails, the overlay epoch bumps
  and any staged result is re-verified against real state before it may
  commit, so optimism never leaks into the log.
* **EvaluatePool** (plan_apply_pool.go:18): per-node verification fans
  out across a thread pool (size cores/2) when a plan touches enough
  nodes to pay for the dispatch.
"""
from __future__ import annotations

import os
import queue as _queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Tuple

from ..raft import NotLeaderError
from ..state.store import StateStore
from ..trace import TRACE
from ..structs import (
    Allocation,
    NetworkIndex,
    Node,
    Plan,
    PlanResult,
    allocs_fit,
)


def _csi_requests(store, alloc: Allocation):
    """(request, (namespace, source)) pairs for an alloc's CSI volume
    requests — the one shared lookup walk behind both optimistic and
    commit-time claim verification."""
    job = alloc.job or store.job_by_id(alloc.namespace, alloc.job_id)
    tg = job.lookup_task_group(alloc.task_group) if job else None
    for req in tg.volumes.values() if tg else ():
        if req.type == "csi":
            yield req, (alloc.namespace, req.source)


def _claim_verdict(vol, alloc: Allocation, read_only: bool) -> str:
    """'held' if the alloc already claims the volume, 'free' if a new
    claim would fit, 'full' otherwise.  Single source of truth for the
    claim rules both verification passes apply."""
    if vol is None:
        return "full"
    if alloc.id in vol.read_claims or alloc.id in vol.write_claims:
        return "held"
    return "free" if vol.claimable(read_only) else "full"


class OptimisticState:
    """Base store + verified-but-uncommitted PlanResults, the view the
    verifier uses while earlier applies are in flight (reference
    plan_apply.go:45-70 — the leader's optimistic snapshot carries plan
    N's results while plan N's raft future is outstanding).

    Every overlay is applied idempotently by alloc id, so a result that
    commits mid-verification (and thus shows up in both the base store
    and the overlay) is counted once.
    """

    def __init__(self, store: StateStore, results: List[PlanResult]) -> None:
        self._store = store
        self._results = results

    def __getattr__(self, name):
        return getattr(self._store, name)

    def allocs_by_node(self, node_id: str) -> List[Allocation]:
        by_id = {a.id: a for a in self._store.allocs_by_node(node_id)}
        for result in self._results:
            for alloc in result.node_update.get(node_id, ()):
                by_id[alloc.id] = alloc
            for alloc in result.node_preemptions.get(node_id, ()):
                by_id[alloc.id] = alloc
            for alloc in result.node_allocation.get(node_id, ()):
                by_id[alloc.id] = alloc
        return list(by_id.values())

    def csi_volume_by_id(self, namespace: str, volume_id: str):
        vol = self._store.csi_volume_by_id(namespace, volume_id)
        if vol is None or not self._results:
            return vol
        import copy

        vol = copy.deepcopy(vol)
        for result in self._results:
            for node_allocs in result.node_allocation.values():
                for alloc in node_allocs:
                    for req, key in _csi_requests(self._store, alloc):
                        if key != (namespace, volume_id):
                            continue
                        if _claim_verdict(
                            vol, alloc, req.read_only
                        ) == "free":
                            vol.claim(
                                alloc.id, alloc.node_id, req.read_only
                            )
        return vol


class EvaluatePool:
    """Per-node plan verification fan-out (reference
    plan_apply_pool.go:18 EvaluatePool, sized cores/2).

    The same pool shape backs the BatchWorker's optimistic parallel
    replay: ``submit`` exposes the raw executor so a wave of
    speculative eval replays can fan out across it without a second
    thread-pool implementation."""

    # below this many nodes the dispatch overhead beats the win
    MIN_FANOUT = 4

    def __init__(
        self, workers: Optional[int] = None,
        thread_name_prefix: str = "plan-eval",
    ) -> None:
        self.workers = workers or max(1, (os.cpu_count() or 2) // 2)
        self.closed = False
        self._pool = ThreadPoolExecutor(
            max_workers=self.workers,
            thread_name_prefix=thread_name_prefix,
        )

    def submit(self, fn, *args, **kwargs):
        """Schedule arbitrary work on the pool; returns the Future."""
        return self._pool.submit(fn, *args, **kwargs)

    def evaluate_nodes(
        self, store, plan: Plan, node_ids: List[str]
    ) -> Dict[str, Tuple[bool, str]]:
        if len(node_ids) < self.MIN_FANOUT:
            return {
                nid: evaluate_node_plan(store, plan, nid)
                for nid in node_ids
            }
        futures = {
            nid: self._pool.submit(evaluate_node_plan, store, plan, nid)
            for nid in node_ids
        }
        return {nid: fut.result() for nid, fut in futures.items()}

    def shutdown(self) -> None:
        self.closed = True
        self._pool.shutdown(wait=False)


def evaluate_node_plan(
    store: StateStore, plan: Plan, node_id: str
) -> Tuple[bool, str]:
    """Whether the plan's changes to one node fit
    (reference plan_apply.go:629 evaluateNodePlan)."""
    # evict-only plans always fit: they only remove things
    # (reference plan_apply.go:631)
    if not plan.node_allocation.get(node_id):
        return True, ""

    node = store.node_by_id(node_id)
    if node is None:
        return False, "node does not exist"
    if node.status != "ready":
        return False, "node is not ready for placements"
    if node.scheduling_eligibility != "eligible":
        return False, "node is not eligible"
    if node.drain:
        return False, "node is draining"

    proposed = [
        a
        for a in store.allocs_by_node(node_id)
        if not a.terminal_status()
    ]
    remove_ids = {a.id for a in plan.node_update.get(node_id, ())}
    remove_ids |= {a.id for a in plan.node_preemptions.get(node_id, ())}
    proposed = [a for a in proposed if a.id not in remove_ids]
    by_id = {a.id: a for a in proposed}
    for alloc in plan.node_allocation.get(node_id, ()):
        by_id[alloc.id] = alloc
    fit, dim, _util = allocs_fit(node, list(by_id.values()))
    return fit, dim


def evaluate_plan(
    store: StateStore, plan: Plan, pool: Optional[EvaluatePool] = None
) -> Tuple[PlanResult, bool]:
    """Verify the plan per node; returns (result, fully_committed)
    (reference plan_apply.go:400 evaluatePlan).  With a pool, per-node
    checks fan out concurrently (plan_apply.go:437
    evaluatePlanPlacements + EvaluatePool)."""
    result = PlanResult(
        node_update={},
        node_allocation={},
        node_preemptions={},
        deployment=plan.deployment,
        deployment_updates=list(plan.deployment_updates),
    )
    node_ids = (
        set(plan.node_update)
        | set(plan.node_allocation)
        | set(plan.node_preemptions)
    )
    verdicts: Optional[Dict[str, Tuple[bool, str]]] = None
    if pool is not None and not plan.all_at_once:
        verdicts = pool.evaluate_nodes(store, plan, sorted(node_ids))
    partial = False
    for node_id in sorted(node_ids):
        fit, _reason = (
            verdicts[node_id]
            if verdicts is not None
            else evaluate_node_plan(store, plan, node_id)
        )
        if fit:
            if plan.node_update.get(node_id):
                result.node_update[node_id] = plan.node_update[node_id]
            if plan.node_allocation.get(node_id):
                result.node_allocation[node_id] = plan.node_allocation[
                    node_id
                ]
            if plan.node_preemptions.get(node_id):
                result.node_preemptions[node_id] = plan.node_preemptions[
                    node_id
                ]
        else:
            partial = True
            if plan.all_at_once:
                # reject everything (reference plan_apply.go:514)
                result.node_update = {}
                result.node_allocation = {}
                result.node_preemptions = {}
                result.deployment = None
                result.deployment_updates = []
                break
    if not _verify_csi_claims(store, result):
        partial = True
    if partial:
        result.refresh_index = store.latest_index()
        # a partial commit must not carry deployment mutations computed
        # against the full plan (reference plan_apply.go:447)
        result.deployment = None
        result.deployment_updates = []
    return result, not partial


def _verify_csi_claims(store: StateStore, result: PlanResult) -> bool:
    """Drop placements whose CSI volume claims cannot all be satisfied
    (the applier is the claim's linearization point: feasibility ran
    against claim-free snapshots, so two optimistic placements can race
    for the last writer slot — the loser is rejected here and its eval
    refreshed, exactly like a node-capacity conflict)."""
    import copy

    sim: Dict[Tuple[str, str], object] = {}
    ok = True
    for node_id in sorted(result.node_allocation):
        kept = []
        for alloc in result.node_allocation[node_id]:
            fits = True
            claimed = []
            for req, key in _csi_requests(store, alloc):
                vol = sim.get(key)
                if vol is None:
                    vol = store.csi_volume_by_id(*key)
                    if vol is not None:
                        vol = copy.deepcopy(vol)
                        sim[key] = vol
                verdict = _claim_verdict(vol, alloc, req.read_only)
                if verdict == "full":
                    fits = False
                    break
                if verdict == "free":
                    claimed.append((vol, req.read_only))
            if fits:
                for vol, read_only in claimed:
                    vol.claim(alloc.id, alloc.node_id, read_only)
                kept.append(alloc)
            else:
                ok = False
        if len(kept) != len(result.node_allocation[node_id]):
            if kept:
                result.node_allocation[node_id] = kept
            else:
                del result.node_allocation[node_id]
    return ok


class PlanApplier:
    """Verifier + committer pipeline with capacity-change fanout to
    blocked evals.  Commits are strictly serialized and ordered; the
    verifier runs one (or two, counting the staged slot) plans ahead
    against an `OptimisticState` overlay."""

    def __init__(
        self,
        store: StateStore,
        plan_queue,
        blocked=None,
        metrics=None,
        pool: Optional[EvaluatePool] = None,
        leader_check: Optional[Callable[[], bool]] = None,
    ) -> None:
        self.store = store
        self.plan_queue = plan_queue
        self.blocked = blocked
        self.metrics = metrics
        self.pool = pool if pool is not None else EvaluatePool()
        # leadership fence: when set and False, in-flight plans are
        # rejected with NotLeaderError instead of committing — the
        # submitting worker converts that to nack-for-redelivery, so
        # the eval is re-run by whoever holds leadership next
        # (reference plan_apply.go: the applier only runs on the
        # leader; here the check closes the revoke race window)
        self._leader_check = leader_check
        # _stop and _staged are REPLACED on every start(): a committer
        # from a previous leadership term that outlived stop()'s join
        # timeout (e.g. blocked >2s in a raft apply) keeps its own
        # generation's event+queue and can never race the new threads
        # for staged plans or observe the cleared stop flag
        self._stop = threading.Event()
        self._verify_thread: Optional[threading.Thread] = None
        self._commit_thread: Optional[threading.Thread] = None
        # staged slot between verify and commit: depth 1 keeps at most
        # two optimistic results outstanding (one staged, one verifying)
        self._staged: _queue.Queue = _queue.Queue(maxsize=1)
        self._lock = threading.Lock()
        self._inflight: List[PlanResult] = []
        self._epoch = 0  # bumped when an apply fails
        self.applied = 0
        self.overlap_verifies = 0  # verifications that ran on an overlay

    def start(self) -> None:
        # re-entrant after stop() (leadership can be re-established,
        # reference leader.go:222): fresh stop event + staged queue per
        # generation, fresh pool, no stale staged results
        self._flush_staged()
        self._stop = threading.Event()
        self._staged = _queue.Queue(maxsize=1)
        if self.pool.closed:
            self.pool = EvaluatePool(self.pool.workers)
        with self._lock:
            self._inflight = []
        self._verify_thread = threading.Thread(
            target=self._verify_loop,
            args=(self._stop, self._staged),
            name="plan-verifier",
            daemon=True,
        )
        self._commit_thread = threading.Thread(
            target=self._commit_loop,
            args=(self._stop, self._staged),
            name="plan-applier",
            daemon=True,
        )
        self._verify_thread.start()
        self._commit_thread.start()

    def stop(self) -> None:
        self._stop.set()
        for t in (self._verify_thread, self._commit_thread):
            if t is not None:
                t.join(timeout=2.0)
        self._flush_staged()
        self.pool.shutdown()

    def _flush_staged(self) -> None:
        while True:
            try:
                pending, _r, _f, _e = self._staged.get_nowait()
                pending.respond(None, NotLeaderError(None))
            except _queue.Empty:
                return

    # ------------------------------------------------------------------
    # stage 1: verification (overlapped with stage-2 commits)
    # ------------------------------------------------------------------

    def _not_leader(self) -> bool:
        return self._leader_check is not None and not self._leader_check()

    def _reject_not_leader(self, pending) -> None:
        if self.metrics is not None:
            self.metrics.incr("leadership.plan_rejected")
        if pending.plan.eval_id:
            TRACE.event(pending.plan.eval_id, "plan.not_leader")
        pending.respond(None, NotLeaderError(None))

    def _verify_loop(self, stop: threading.Event,
                     staged_q: _queue.Queue) -> None:
        while not stop.is_set():
            pending = self.plan_queue.dequeue(timeout=0.1)
            if pending is None:
                continue
            if self._not_leader():
                # leadership revoked with this plan in flight: reject
                # before any verification work — the worker nacks the
                # eval for redelivery under the next leadership
                self._reject_not_leader(pending)
                continue
            import time as _time

            start = _time.monotonic()
            with self._lock:
                overlay = list(self._inflight)
                epoch = self._epoch
            state = (
                OptimisticState(self.store, overlay)
                if overlay
                else self.store
            )
            try:
                result, full = evaluate_plan(state, pending.plan, self.pool)
            except Exception as exc:  # noqa: BLE001
                pending.respond(None, exc)
                continue
            if overlay:
                self.overlap_verifies += 1
                if self.metrics is not None:
                    self.metrics.incr("plan.overlap_verify")
            verify_dt = _time.monotonic() - start
            if self.metrics is not None:
                # (reference plan_apply.go:401 plan.evaluate timing)
                self.metrics.add_sample(
                    "plan.evaluate", verify_dt * 1000.0,
                    exemplar=pending.plan.eval_id or None,
                )
            # flight recorder: the verification interval on the
            # submitting eval's trace (applier-thread attribution)
            if pending.plan.eval_id:
                TRACE.add_span(
                    pending.plan.eval_id, "plan.evaluate",
                    start, verify_dt,
                    overlay=bool(overlay), full=full,
                )
            with self._lock:
                self._inflight.append(result)
            # blocks while the committer still holds an earlier plan:
            # that wait IS the pipeline bubble the overlap hides
            staged = False
            while not stop.is_set():
                try:
                    staged_q.put(
                        (pending, result, full, epoch), timeout=0.1
                    )
                    staged = True
                    break
                except _queue.Full:
                    continue
            if not staged:
                # shutdown raced the hand-off: fail fast like every
                # other flush path instead of leaving the submitter
                # to hit its wait timeout
                with self._lock:
                    self._remove_inflight_locked(result)
                pending.respond(None, NotLeaderError(None))

    # ------------------------------------------------------------------
    # stage 2: ordered commit
    # ------------------------------------------------------------------

    def _commit_loop(self, stop: threading.Event,
                     staged_q: _queue.Queue) -> None:
        while not stop.is_set():
            try:
                pending, result, full, epoch = staged_q.get(
                    timeout=0.1
                )
            except _queue.Empty:
                continue
            if self._not_leader():
                # staged between verify and commit when leadership
                # moved: the optimistic result must never reach the
                # store (a new leader owns that state now)
                with self._lock:
                    self._remove_inflight_locked(result)
                self._reject_not_leader(pending)
                continue
            try:
                with self._lock:
                    stale = epoch != self._epoch
                if stale:
                    # an earlier apply failed after this plan was
                    # verified optimistically: re-verify on real state
                    result2, full = evaluate_plan(
                        self.store, pending.plan, self.pool
                    )
                    with self._lock:
                        for i, r in enumerate(self._inflight):
                            if r is result:
                                self._inflight[i] = result2
                                break
                        # the re-verification may have changed this
                        # result's effect, so verifications that used
                        # the old one are invalid too: bump the epoch
                        # so they also re-verify before committing
                        self._epoch += 1
                    result = result2
                self._commit(pending.plan, result, full)
                with self._lock:
                    self._remove_inflight_locked(result)
                pending.respond(result, None)
            except Exception as exc:  # noqa: BLE001
                # bump + remove under ONE lock acquisition, so the
                # verifier can never snapshot the new epoch together
                # with an overlay still containing the failed result
                with self._lock:
                    self._epoch += 1
                    self._remove_inflight_locked(result)
                pending.respond(None, exc)

    def _remove_inflight_locked(self, result: PlanResult) -> None:
        for i, r in enumerate(self._inflight):
            if r is result:
                del self._inflight[i]
                break

    def _commit(self, plan: Plan, result: PlanResult, full: bool) -> None:
        import time as _time

        start = _time.monotonic()
        if (
            result.node_update
            or result.node_allocation
            or result.node_preemptions
            or result.deployment is not None
            or result.deployment_updates
        ):
            # the producing wave's captured generation, passed only
            # when stamped (so store facades without the kwarg keep
            # working for unstamped plans): the replicated fence must
            # judge the plan by the leadership it RAN under, not by
            # whoever leads when it reaches the store
            gen = getattr(plan, "leader_gen", None)
            if gen is not None:
                index = self.store.upsert_plan_results(
                    result, plan.eval_id, leader_gen=gen
                )
            else:
                index = self.store.upsert_plan_results(
                    result, plan.eval_id
                )
            result.alloc_index = index
            self.applied += 1
            self._notify_capacity_change(result, index)
            # flight recorder: the commit interval + committed index
            # close the eval's write path (dequeue -> ... -> commit)
            if plan.eval_id:
                TRACE.add_span(
                    plan.eval_id, "plan.apply", start,
                    _time.monotonic() - start, index=index,
                )
        if self.metrics is not None:
            # (reference plan_apply.go:185 plan.evaluate/apply timings)
            self.metrics.add_sample(
                "plan.apply", (_time.monotonic() - start) * 1000.0,
                exemplar=plan.eval_id or None,
            )
            self.metrics.incr("plan.applied")
            if not full:
                self.metrics.incr("plan.partial_commit")

    def apply(self, plan: Plan) -> PlanResult:
        """Synchronous verify+commit (test/tooling path; production
        traffic flows through the two pipeline threads)."""
        result, full = evaluate_plan(self.store, plan, self.pool)
        self._commit(plan, result, full)
        return result

    def _notify_capacity_change(self, result: PlanResult, index: int) -> None:
        """Stopped/preempted allocs free capacity: unblock their node
        classes (reference blocked_evals.go:watchCapacity wiring in
        nomad/plan_apply.go + state store)."""
        if self.blocked is None:
            return
        freed_nodes = set(result.node_update) | set(result.node_preemptions)
        for node_id in freed_nodes:
            node = self.store.node_by_id(node_id)
            if node is not None:
                self.blocked.unblock(node.computed_class, index)
