"""Serialized plan applier (reference nomad/plan_apply.go).

A single thread pops plans from the queue, re-verifies every touched node
against current state (evaluateNodePlan:629 re-runs AllocsFit), commits
the feasible subset (partial commits set a refresh index so the submitting
worker retries on fresh state), and applies results through the store's
plan-results write path.  The reference pipelines verification of plan
N+1 against an optimistic snapshot while plan N's raft apply is in flight
(plan_apply.go:45-70); with an in-process store the apply is a dict write,
so the pipeline bubble the reference hides does not exist here — the
applier stays strictly serial, preserving the correctness contract.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..state.store import StateStore
from ..structs import (
    Allocation,
    NetworkIndex,
    Node,
    Plan,
    PlanResult,
    allocs_fit,
)


def evaluate_node_plan(
    store: StateStore, plan: Plan, node_id: str
) -> Tuple[bool, str]:
    """Whether the plan's changes to one node fit
    (reference plan_apply.go:629 evaluateNodePlan)."""
    # evict-only plans always fit: they only remove things
    # (reference plan_apply.go:631)
    if not plan.node_allocation.get(node_id):
        return True, ""

    node = store.node_by_id(node_id)
    if node is None:
        return False, "node does not exist"
    if node.status != "ready":
        return False, "node is not ready for placements"
    if node.scheduling_eligibility != "eligible":
        return False, "node is not eligible"
    if node.drain:
        return False, "node is draining"

    proposed = [
        a
        for a in store.allocs_by_node(node_id)
        if not a.terminal_status()
    ]
    remove_ids = {a.id for a in plan.node_update.get(node_id, ())}
    remove_ids |= {a.id for a in plan.node_preemptions.get(node_id, ())}
    proposed = [a for a in proposed if a.id not in remove_ids]
    by_id = {a.id: a for a in proposed}
    for alloc in plan.node_allocation.get(node_id, ()):
        by_id[alloc.id] = alloc
    fit, dim, _util = allocs_fit(node, list(by_id.values()))
    return fit, dim


def evaluate_plan(
    store: StateStore, plan: Plan
) -> Tuple[PlanResult, bool]:
    """Verify the plan per node; returns (result, fully_committed)
    (reference plan_apply.go:400 evaluatePlan)."""
    result = PlanResult(
        node_update={},
        node_allocation={},
        node_preemptions={},
        deployment=plan.deployment,
        deployment_updates=list(plan.deployment_updates),
    )
    node_ids = (
        set(plan.node_update)
        | set(plan.node_allocation)
        | set(plan.node_preemptions)
    )
    partial = False
    for node_id in sorted(node_ids):
        fit, _reason = evaluate_node_plan(store, plan, node_id)
        if fit:
            if plan.node_update.get(node_id):
                result.node_update[node_id] = plan.node_update[node_id]
            if plan.node_allocation.get(node_id):
                result.node_allocation[node_id] = plan.node_allocation[
                    node_id
                ]
            if plan.node_preemptions.get(node_id):
                result.node_preemptions[node_id] = plan.node_preemptions[
                    node_id
                ]
        else:
            partial = True
            if plan.all_at_once:
                # reject everything (reference plan_apply.go:514)
                result.node_update = {}
                result.node_allocation = {}
                result.node_preemptions = {}
                result.deployment = None
                result.deployment_updates = []
                break
    if not _verify_csi_claims(store, result):
        partial = True
    if partial:
        result.refresh_index = store.latest_index()
        # a partial commit must not carry deployment mutations computed
        # against the full plan (reference plan_apply.go:447)
        result.deployment = None
        result.deployment_updates = []
    return result, not partial


def _verify_csi_claims(store: StateStore, result: PlanResult) -> bool:
    """Drop placements whose CSI volume claims cannot all be satisfied
    (the applier is the claim's linearization point: feasibility ran
    against claim-free snapshots, so two optimistic placements can race
    for the last writer slot — the loser is rejected here and its eval
    refreshed, exactly like a node-capacity conflict)."""
    import copy

    sim: Dict[Tuple[str, str], object] = {}
    ok = True
    for node_id in sorted(result.node_allocation):
        kept = []
        for alloc in result.node_allocation[node_id]:
            job = alloc.job or store.job_by_id(
                alloc.namespace, alloc.job_id
            )
            tg = job.lookup_task_group(alloc.task_group) if job else None
            reqs = [
                r
                for r in (tg.volumes.values() if tg else ())
                if r.type == "csi"
            ]
            fits = True
            claimed = []
            for req in reqs:
                key = (alloc.namespace, req.source)
                vol = sim.get(key)
                if vol is None:
                    vol = store.csi_volume_by_id(*key)
                    if vol is not None:
                        vol = copy.deepcopy(vol)
                        sim[key] = vol
                if vol is None:
                    fits = False
                    break
                if alloc.id in vol.read_claims or (
                    alloc.id in vol.write_claims
                ):
                    continue
                if not vol.claimable(req.read_only):
                    fits = False
                    break
                claimed.append((vol, req.read_only))
            if fits:
                for vol, read_only in claimed:
                    vol.claim(alloc.id, alloc.node_id, read_only)
                kept.append(alloc)
            else:
                ok = False
        if len(kept) != len(result.node_allocation[node_id]):
            if kept:
                result.node_allocation[node_id] = kept
            else:
                del result.node_allocation[node_id]
    return ok


class PlanApplier:
    """The single apply thread + capacity-change fanout to blocked
    evals."""

    def __init__(
        self, store: StateStore, plan_queue, blocked=None, metrics=None
    ) -> None:
        self.store = store
        self.plan_queue = plan_queue
        self.blocked = blocked
        self.metrics = metrics
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.applied = 0

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="plan-applier", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.is_set():
            pending = self.plan_queue.dequeue(timeout=0.1)
            if pending is None:
                continue
            try:
                result = self.apply(pending.plan)
                pending.respond(result, None)
            except Exception as exc:  # noqa: BLE001
                pending.respond(None, exc)

    def apply(self, plan: Plan) -> PlanResult:
        import time as _time

        start = _time.monotonic()
        result, _full = evaluate_plan(self.store, plan)
        if (
            result.node_update
            or result.node_allocation
            or result.node_preemptions
            or result.deployment is not None
            or result.deployment_updates
        ):
            index = self.store.upsert_plan_results(result, plan.eval_id)
            result.alloc_index = index
            self.applied += 1
            self._notify_capacity_change(result, index)
        if self.metrics is not None:
            # (reference plan_apply.go:185 plan.evaluate/apply timings)
            self.metrics.add_sample(
                "plan.apply", (_time.monotonic() - start) * 1000.0
            )
            self.metrics.incr("plan.applied")
            if not _full:
                self.metrics.incr("plan.partial_commit")
        return result

    def _notify_capacity_change(self, result: PlanResult, index: int) -> None:
        """Stopped/preempted allocs free capacity: unblock their node
        classes (reference blocked_evals.go:watchCapacity wiring in
        nomad/plan_apply.go + state store)."""
        if self.blocked is None:
            return
        freed_nodes = set(result.node_update) | set(result.node_preemptions)
        for node_id in freed_nodes:
            node = self.store.node_by_id(node_id)
            if node is not None:
                self.blocked.unblock(node.computed_class, index)
